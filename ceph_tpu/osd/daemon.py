"""OSD daemon: the data-plane node.

Reference parity: ceph-osd (/root/reference/src/osd/OSD.cc,
PrimaryLogPG.cc, ECBackend.cc, ReplicatedBackend.cc) re-designed on an
asyncio event loop:

- boot: connect the mon, MOSDBoot, subscribe to map epochs
  (OSD::init OSD.cc:3283 + monc subscribe);
- client ops (MOSDOp) hit the primary's op engine: version assignment +
  pg log entry (PrimaryLogPG::execute_ctx), EC encode / replica fan-out
  as sub-writes carrying the log entry (ECBackend::submit_transaction
  ECBackend.cc:1502 -> :2066, ReplicatedBackend's repop), client acked
  when every up shard committed;
- peering on map change (PeeringState roles): primary queries shard
  infos+logs (GetInfo/GetLog), elects the authoritative log (max
  last_update), pushes it to peers who merge + rewind divergent entries
  (PGLog.h:1241-1247), computes per-shard missing sets, recovers
  missing objects (EC reconstruct + push — the RecoveryOp role,
  ECBackend.h:249), then activates and drains queued ops;
- OSD<->OSD heartbeats (OSD.cc:5235 handle_osd_ping) with failure
  reports to the mon after the local grace (OSD.cc:5889 send_failures).

TPU placement: the per-op EC encode/decode goes through the registered
codec (ec_jax — batched GF(2^8) MXU matmuls on device when available);
placement comes from the shared OSDMap/CRUSH kernel path; everything
else is host control-plane.
"""

from __future__ import annotations

import asyncio
import bisect
import json
import logging
import math
import os

from ceph_tpu.common import flags
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ceph_tpu.crush.map import CRUSH_ITEM_NONE
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.common import buffer as buffer_mod
from ceph_tpu.common import lockdep, tracing
from ceph_tpu.msg import Connection, Messenger
from ceph_tpu.msg.messages import (
    MConfig,
    MLog,
    Message,
    MGetMap,
    MOSDBoot,
    MOSDCommand,
    MOSDCommandReply,
    MOSDCompute,
    MOSDComputeReply,
    MOSDFailure,
    MOSDMapMsg,
    MOSDOp,
    MOSDOpReply,
    MOSDSubCompute,
    MOSDSubComputeReply,
    MOSDSubRead,
    MOSDSubReadReply,
    MOSDSubWrite,
    MOSDSubWriteReply,
    MPGLogMsg,
    MPGQuery,
    MPing,
    MWatchNotify,
    MWatchNotifyAck,
    PING,
    PING_REPLY,
    ShardOp,
    decode_kv_map,
    decode_str_list,
    encode_kv_map,
)
from ceph_tpu.ops import checksum as cks
from ceph_tpu.os import ObjectId, ObjectStore, Transaction
from ceph_tpu.os.groupcommit import GroupCommitter
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.admission import AdmissionGate, SHED
from ceph_tpu.osd.encode_service import EncodeService
from ceph_tpu.osd.hedge import HedgeTracker
from ceph_tpu.osd.tier import TierAgent
from ceph_tpu.osd import scheduler as sched_mod
from ceph_tpu.osd.osdmap import OSDMap, PgId, TYPE_ERASURE, TYPE_REPLICATED
from ceph_tpu.osd.pg_log import (
    PGLog,
    PGMETA_OID,
    ZERO,
    ev,
    make_entry,
)
from ceph_tpu.rados.embedded import (
    HINFO_ATTR,
    OI_ATTR,
    SS_ATTR,
    shard_collection,
)

log = logging.getLogger("osd")

EAGAIN = -11
ENOENT = -2
ESTALE = -116
EIO = -5
EBUSY = -16
EINVAL = -22
EOPNOTSUPP = -95

DEFAULTS = {
    "osd_heartbeat_interval": 1.0,
    "osd_heartbeat_grace": 4.0,
    "osd_heartbeat_max_peers": 10,
    "osd_sub_op_timeout": 5.0,
    "osd_min_pg_log_entries": 100,
    "osd_pool_erasure_code_stripe_unit": 4096,
}

# client ops whose replay must return the stored reply instead of
# re-executing (non-idempotent mutations; the reqid dedup scope — the
# reference tracks reqids for completed writes, PrimaryLogPG log reqids)
_MUTATING_CLIENT_OPS = frozenset({
    "write_full", "write", "append", "remove", "setxattr", "rmxattr",
    "omap_set", "omap_rm", "call"})

# rollback-generation shard object (ECBackend keeps the previous shard
# generation until a write commits everywhere, so a partial overwrite
# can never destroy the last completed write's reconstructability —
# the ghobject generation / rollback machinery of ECTransaction)
RB_PREFIX = "_rbgen_"

# snapshot clone objects: "<head>\x16<cloneid>" (the ghobject snap
# field role).  The separator is unprintable so client object names can
# never collide with clone names.
SNAP_SEP = "\x16"

# sealed hit sets persist in the pg-meta object's omap under this key
# prefix (the reference persists hit_set archives as PG objects; one
# omap namespace per PG plays that role on this substrate)
HITSET_OMAP_PREFIX = "hitset_"


def clone_name(oid: str, cloneid: int) -> str:
    return f"{oid}{SNAP_SEP}{cloneid}"


# user xattrs are namespaced so they can never collide with internal
# attrs (OI/SS/hinfo) — the reference splits "_"-prefixed internals the
# same way (object_info vs user xattr namespace)
USER_ATTR_PREFIX = "u:"

_encode_kv_map = encode_kv_map
_decode_kv_map = decode_kv_map
_decode_str_list = decode_str_list

def is_internal_name(name: str) -> bool:
    """Names clients may not address and pgls must not list."""
    return name.startswith(RB_PREFIX) or SNAP_SEP in name


def _hinfo_chunk_ok(at: Dict[str, bytes], shard: int,
                    payload: bytes) -> bool:
    """Does this shard payload match its recorded hinfo chunk crc?
    Shards without chunk hashes (RMW-era objects) pass — version
    agreement is their consistency story.  The ONE hash-check rule,
    shared by read-path selection and scrub."""
    try:
        hi = ec_util.HashInfo.from_dict(json.loads(at[HINFO_ATTR]))
    except (KeyError, ValueError):
        return True
    if not hi.has_chunk_hash():
        return True
    return cks.crc32c(0xFFFFFFFF, payload) == hi.get_chunk_hash(shard)


class _SkipApply(Exception):
    """Internal: a sub-write adjudicated as a superseded straggler —
    ack success without applying."""


class UnfoundObject(Exception):
    """Raised when an op needs an object whose acked data is currently
    unlocatable (all sources down); mapped to EAGAIN so the client
    retries until recovery finds a source."""


class PGState:
    """In-memory PG bookkeeping (PG + PeeringState role)."""

    def __init__(self, pg: PgId):
        self.pg = pg
        self.acting: List[int] = []
        self.primary = -1
        self.state = "inactive"          # inactive|peering|active
        self.interval_epoch = 0          # same_interval_since
        self.log: Optional[PGLog] = None  # my shard's log (lazy)
        self.next_version = 1            # primary: next log version
        self.peer_missing: Dict[int, Dict[str, tuple]] = {}
        self.active_event = asyncio.Event()
        self.peering_task: Optional[asyncio.Task] = None
        # objects recovery could not reconstruct yet (pg_missing with no
        # found location); re-peered when the up set changes
        self.unfound = False
        # per-object write serialization + primary-side extent cache
        # (the ECBackend ExtentCache role): oid -> {"version", "size",
        # "stripes": {stripe_start: logical stripe bytes}}.  Coherent
        # because the primary serializes writes per object and the
        # cache is dropped on any interval change.
        self.obj_locks: Dict[str, list] = {}  # oid -> [Lock, refcount]
        self.extent_cache: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()
        # snap ids this primary has already trimmed from its objects
        self.trimmed_snaps: Set[int] = set()
        self.trim_task: Optional[asyncio.Task] = None
        # in-place recovery retry for unfound leftovers (no interval
        # change to trigger re-peering)
        self._unfound_retry: Optional[asyncio.Task] = None

    def obj_lock(self, oid: str) -> "_ObjLockCtx":
        """Refcounted per-object lock: the entry is only evictable when
        NO task holds or awaits it.  (A bare `not lock.locked()` sweep
        races the release->waiter-wakeup window of asyncio.Lock, which
        could hand two writers the same object.)"""
        entry = self.obj_locks.get(oid)
        if entry is None:
            entry = self.obj_locks[oid] = [_ObjLock(), 0]
        return _ObjLockCtx(self.obj_locks, oid, entry)

    def my_shard(self, osd: int, pool_type: int) -> int:
        if pool_type == TYPE_REPLICATED:
            return -1
        try:
            return self.acting.index(osd)
        except ValueError:
            return -1


def _lock_class(oid: str) -> str:
    """lockdep class of an object lock key (lock classes, not
    instances — the reference's lockdep model)."""
    if oid.startswith("sub\x00"):
        return "osd.sublock"
    if oid.startswith("_cls_\x00"):
        return "osd.clslock"
    return "osd.objlock"


class _ObjLock:
    """asyncio.Lock-equivalent mutex with a SYNCHRONOUS uncontended
    acquire (`try_acquire`) — the object-lock half of the sub-chunk
    write fast lane.  The async semantics mirror CPython's
    asyncio.Lock exactly (FIFO waiter wakeup; a waiter cancelled
    after being woken passes the wakeup on), so contended acquirers
    behave as before; the sync path only wins the lock when it is
    free with no waiters, which preserves FIFO fairness."""

    __slots__ = ("_locked", "_waiters")

    def __init__(self) -> None:
        self._locked = False
        self._waiters: Optional[deque] = None

    def locked(self) -> bool:
        return self._locked

    def try_acquire(self) -> bool:
        """Take the lock without suspending iff it is free and nobody
        is queued for it (a queued waiter keeps FIFO priority)."""
        if self._locked or self._waiters:
            return False
        self._locked = True
        return True

    async def acquire(self) -> bool:
        if not self._locked and not self._waiters:
            self._locked = True
            return True
        if self._waiters is None:
            self._waiters = deque()
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            try:
                await fut
            finally:
                self._waiters.remove(fut)
        except asyncio.CancelledError:
            # woken then cancelled: the wakeup must not be lost
            if not self._locked:
                self._wake_up_first()
            raise
        self._locked = True
        return True

    def release(self) -> None:
        if not self._locked:
            raise RuntimeError("release of unlocked _ObjLock")
        self._locked = False
        self._wake_up_first()

    def _wake_up_first(self) -> None:
        if not self._waiters:
            return
        fut = self._waiters[0]
        if not fut.done():
            fut.set_result(True)


class _ObjLockCtx:
    """Context manager pairing an _ObjLock with a user refcount so
    idle entries can be dropped without racing pending acquirers.
    Acquisitions feed lockdep (CEPH_TPU_LOCKDEP=1) for order-inversion
    detection."""

    def __init__(self, table: Dict[str, list], oid: str, entry: list):
        self._table = table
        self._oid = oid
        self._entry = entry

    async def __aenter__(self):
        if lockdep.enabled:
            self._cls = _lock_class(self._oid)
            # remember the acquiring task: the recovery wave enters in
            # gather() subtasks and exits from the parent, and the
            # release must come off the stack the acquire went onto
            self._ld_task = lockdep.acquire(self._cls)
        self._entry[1] += 1
        # obj-lock WAIT is a pipeline stage: span only when contended
        # (an uncontended acquire is a no-op, not time the op spent)
        lk_span = tracing.start_child("objlock") \
            if self._entry[0].locked() else tracing.NULL_SPAN
        try:
            await self._entry[0].acquire()
        except BaseException:
            self._entry[1] -= 1
            if lockdep.enabled:
                lockdep.release(self._cls, getattr(
                    self, "_ld_task", None))
            lk_span.set_attr("cancelled", True)
            lk_span.finish()
            raise
        lk_span.finish()
        return self

    async def __aexit__(self, *exc):
        self._entry[0].release()
        if lockdep.enabled and getattr(self, "_cls", None):
            lockdep.release(self._cls, getattr(self, "_ld_task", None))
        self._entry[1] -= 1
        if self._entry[1] == 0 and \
                self._table.get(self._oid) is self._entry:
            del self._table[self._oid]
        return False

    def try_enter(self) -> bool:
        """Synchronous uncontended acquire — the obj-lock half of the
        sub-chunk fast lane: same lock, refcount, eviction, and
        lockdep discipline as `async with`, minus the coroutine
        round trip (and minus the objlock span, which is
        contended-only anyway).  False = contended; take the async
        path.  Pair a True return with `exit_sync()`."""
        if lockdep.enabled:
            self._cls = _lock_class(self._oid)
            self._ld_task = lockdep.acquire(self._cls)
        if not self._entry[0].try_acquire():
            if lockdep.enabled:
                lockdep.release(self._cls, self._ld_task)
            return False
        self._entry[1] += 1
        return True

    def exit_sync(self) -> None:
        self._entry[0].release()
        if lockdep.enabled and getattr(self, "_cls", None):
            lockdep.release(self._cls, getattr(self, "_ld_task", None))
        self._entry[1] -= 1
        if self._entry[1] == 0 and \
                self._table.get(self._oid) is self._entry:
            del self._table[self._oid]


class OSDDaemon:
    def __init__(self, osd_id: int, mon_addr,
                 store: Optional[ObjectStore] = None,
                 config: Optional[Dict[str, Any]] = None):
        self.osd_id = osd_id
        # one mon address, a comma-separated list, or a list: the OSD
        # hunts to the next mon when the current one goes quiet
        # (MonClient hunting role)
        if isinstance(mon_addr, str):
            self.mon_addrs = [a for a in mon_addr.split(",") if a]
        else:
            self.mon_addrs = list(mon_addr)
        self._mon_idx = 0
        self.config = dict(DEFAULTS)
        self.config.update(config or {})
        from ceph_tpu.common.auth import parse_secret

        self.msgr = Messenger(
            f"osd.{osd_id}", secret=parse_secret(
                self.config.get("auth_secret")))
        self.msgr.secure = bool(self.config.get("auth_secure"))
        self.msgr.local_fastpath = bool(
            self.config.get("ms_local_fastpath", True))
        self.msgr.dispatcher = self._dispatch
        self._apply_msgr_injection()
        # heartbeat_inject_failure: while now < this, the daemon goes
        # heartbeat-silent (no pings, no replies) without dying
        self._hb_mute_until = 0.0
        self.store = store if store is not None else MemStore()
        self._own_store = store is None
        # group commit (os/groupcommit.py): concurrent durable txns
        # share ONE kv sync commit + ONE block fsync through a
        # kv_sync_thread-style commit lane; engages only on stores
        # that amortize barriers (TPUStore), inline otherwise.  Kill
        # switches CEPH_TPU_GROUP_COMMIT=0 / osd_group_commit_enable
        self.committer = GroupCommitter(self.store,
                                        who=f"osd.{osd_id}",
                                        config=self.config)
        self.osdmap: Optional[OSDMap] = None
        self.pgs: Dict[PgId, PGState] = {}
        # pg_num per pool as of the last map processed: growth triggers
        # local PG splitting (PG::split_into role)
        self._pool_pg_nums: Dict[int, int] = {}
        # children minted by a split: their first peering sweeps all up
        # OSDs (the data lives on the PARENT's members, which the
        # child's acting mapping knows nothing about)
        self._split_children: Set[PgId] = set()
        self._codecs: Dict[int, Any] = {}
        self._tid = 0
        self._futures: Dict[int, asyncio.Future] = {}
        self._hb_last_rx: Dict[int, float] = {}
        self._hb_task: Optional[asyncio.Task] = None
        self._map_event = asyncio.Event()
        self._stopping = False
        self._last_boot_sent = 0.0
        self._last_map_rx = time.monotonic()
        # data-path transfer/dispatch accounting (perf-counter tier);
        # tests assert small writes/reads move O(stripe), not O(object)
        self.perf = {"subread_bytes": 0, "subwrite_bytes": 0,
                     "encode_dispatches": 0, "decode_dispatches": 0,
                     # device-fault degradation accounting: decodes
                     # re-run inline on host after a device fault
                     # (scrub-repair / recovery resilience)
                     "decode_host_retries": 0,
                     # objects this shard received as RECOVERY pushes
                     # (installs of entries from its missing set) —
                     # the log-based-vs-backfill discriminator: a
                     # revived OSD with an intact store recovers only
                     # the log diff, not the whole PG
                     "recovery_installs": 0,
                     # repair-bandwidth accounting (ALL codecs): bytes
                     # the recovery engine pulled over the wire vs
                     # bytes of lost chunks it rebuilt — the scrapeable
                     # bytes-read-per-repaired-byte ratio the
                     # regenerating-code path is judged by
                     "recovery_bytes_read": 0,
                     "recovery_bytes_repaired": 0,
                     # fractional-repair engine: waves served by the
                     # MSR repair path vs objects that fell back to
                     # the classic k-read reconstruct
                     "repair_fragments": 0,
                     "repair_objects": 0,
                     "repair_fallbacks": 0}
        # async micro-batching encode/decode front end: concurrent EC
        # ops share plan-cached device dispatches; inline (pre-service
        # behavior) when the device tier is absent or
        # CEPH_TPU_ENCODE_SERVICE=0
        self.encode_service = EncodeService(who=f"osd.{osd_id}")
        # hot-set tracking + decoded-object read tier (HitSet + the
        # PrimaryLogPG agent role); kill switch CEPH_TPU_TIER=0 /
        # osd_tier_enable=false
        self.tier = TierAgent(who=f"osd.{osd_id}", config=self.config)
        # straggler-tolerant reads: per-peer sub-read latency EWMAs +
        # the hedged first-k gather primitive (osd/hedge.py); kill
        # switches CEPH_TPU_HEDGE=0 / osd_hedge_enable=false
        self.hedge = HedgeTracker(who=f"osd.{osd_id}",
                                  config=self.config)
        # coded compute: the MOSDCompute scan engine (osd/compute.py)
        # — linear kernels run ON the coded shards with first-k
        # result-domain decode; nonlinear kernels take the
        # full-decode fallback.  Scheduled under its own `compute`
        # mClock class + the tenant admission gate.
        from ceph_tpu.osd.compute import ComputeEngine
        from ceph_tpu.osd.inference import InferenceEngine

        self.compute = ComputeEngine(self)
        self.inference = InferenceEngine(self)
        self._promote_tasks: Set[asyncio.Task] = set()
        # watch/notify: (pool, oid) -> {(client, cookie): Connection}
        self.watchers: Dict[Tuple[int, str],
                            Dict[Tuple[str, int], Connection]] = {}
        self._notify_seq = 0
        self._pending_notifies: Dict[int, Dict[str, Any]] = {}
        self._pending_repairs: Set[Tuple[PgId, str]] = set()
        # object classes (ClassHandler::open_all role)
        from ceph_tpu.cls import default_handler

        self.class_handler = default_handler()
        # completed-op replay cache (osd_reqid_t dedup): a client
        # resend after a lost reply gets the STORED reply instead of
        # re-executing a non-idempotent op.  Keyed (client, tid);
        # bounded.  Survives neither restart nor failover — the
        # reference carries reqids in the PG log for those cases.
        self._completed_ops: "OrderedDict[Tuple[str, int], Tuple]" = \
            OrderedDict()
        # QoS op scheduler (mClock/WPQ role): client vs recovery vs
        # scrub arbitration at the execute stage; tenant-tagged client
        # ops (MOSDOp v4) schedule as per-tenant `client.<t>` classes
        # with the osd_mclock_tenant_* dmClock triples, behind a
        # token-bucket admission gate (osd/admission.py).  Kill
        # switches: CEPH_TPU_QOS=0 / osd_mclock_tenant_enable=false
        # collapse every tenant back into the shared client class.
        tenant_profiles: Dict[str, tuple] = {}
        raw_profiles = str(self.config.get(
            "osd_mclock_tenant_profiles", "") or "")
        if raw_profiles:
            try:
                tenant_profiles = {
                    t: tuple(float(x) for x in triple)
                    for t, triple in json.loads(raw_profiles).items()}
            except (ValueError, TypeError):
                log.warning("osd.%d: bad osd_mclock_tenant_profiles"
                            " %r ignored", osd_id, raw_profiles)
        tenant_default = (
            float(self.config.get("osd_mclock_tenant_reservation",
                                  0.0)),
            float(self.config.get("osd_mclock_tenant_weight", 1.0)),
            float(self.config.get("osd_mclock_tenant_limit", 0.0)))
        self.scheduler = sched_mod.make_scheduler(
            str(self.config.get("osd_op_queue", "mclock_scheduler")),
            max_concurrent=int(self.config.get(
                "osd_op_num_threads", 8)),
            max_queue_depth=int(self.config.get(
                "osd_scheduler_queue_depth", 1024)),
            overflow=str(self.config.get(
                "osd_scheduler_overflow", "shed")),
            tenant_default=tenant_default,
            tenant_profiles=tenant_profiles)
        self._qos_tenants_enabled = (
            flags.enabled("CEPH_TPU_QOS")
            and bool(self.config.get("osd_mclock_tenant_enable",
                                     True))
            and isinstance(self.scheduler,
                           sched_mod.MClockScheduler))
        # sub-chunk op fast lane (scheduler.try_acquire + sync obj
        # lock): identical admission/QoS accounting, minus the per-op
        # queue/objlock coroutine micro-costs.  CEPH_TPU_OP_FAST_LANE=0
        # pins every op to the queued path (behavioral twin).
        self._op_fast_lane = flags.enabled("CEPH_TPU_OP_FAST_LANE")
        # backfill/recovery throttle (osd_max_backfills role): at most
        # N PGs may run _recover_pg concurrently on this OSD.  An
        # elasticity event (osd out/in, revive) re-peers MANY PGs at
        # once; without the cap their plan waves all contend for
        # scheduler slots and device dispatches at the same time and
        # client reservations starve exactly when the cluster is
        # already degraded.
        self._backfill_sem = asyncio.Semaphore(
            max(int(self.config.get("osd_max_backfills", 1)), 1))
        self.perf["backfills_active"] = 0
        self.perf["backfill_waits"] = 0
        profile_of = (
            (lambda t: self.scheduler.profile_of(
                sched_mod.tenant_class(t)))
            if self._qos_tenants_enabled else (lambda t: (0.0, 1.0,
                                                          0.0)))
        self.admission = AdmissionGate(config=self.config,
                                       profile_of=profile_of)
        if not self._qos_tenants_enabled:
            self.admission.enabled = False
        # op tracking + background scrub + admin socket
        from ceph_tpu.osd.op_tracker import OpTracker

        self.op_tracker = OpTracker(
            history_size=int(self.config.get("osd_op_history_size",
                                             20)),
            complaint_time=float(self.config.get(
                "osd_op_complaint_time", 30.0)),
            who=f"osd.{osd_id}")
        self._scrub_task: Optional[asyncio.Task] = None
        self._admin_socket = None
        self.scrub_stats = {"objects": 0, "errors": 0, "repaired": 0}
        # stage-span tracing: head-sampled ring retention (the bulk),
        # tail-based exemplar retention via the op tracker (the ops
        # worth explaining keep their full tree even at rate 0)
        self.tracer = tracing.Tracer(
            f"osd.{osd_id}",
            sample_rate=float(self.config.get(
                "osd_trace_sample_rate", 1.0)),
            enabled=bool(self.config.get("osd_trace_enable", True)))
        self.encode_service.tracer = self.tracer

    @property
    def mon_addr(self) -> str:
        return self.mon_addrs[self._mon_idx % len(self.mon_addrs)]

    def _hunt_mon(self) -> None:
        stale = self.msgr._conns.get(self.mon_addr)
        if stale is not None:
            stale.close()
        self._mon_idx += 1

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        # prewarm the native library OFF-loop before the store mounts:
        # msgr.bind prewarms too (Messenger._prewarm_native, the shared
        # choke point every daemon and client crosses), but the store's
        # mkfs/mount below can touch native csum BEFORE bind runs
        from ceph_tpu import native
        if not native.prewarmed():
            await asyncio.to_thread(native.get_lib)
        if self._own_store:
            self.store.mkfs()
            self.store.mount()
        self._load_split_meta()
        addr = await self.msgr.bind(host, port)
        for _attempt in range(2 * len(self.mon_addrs)):
            try:
                mon = await self.msgr.connect(self.mon_addr)
                await mon.send(MGetMap(subscribe=True))
                await mon.send(MOSDBoot(self.osd_id, addr))
                break
            except (ConnectionError, OSError):
                self._hunt_mon()
                await asyncio.sleep(0.2)
        # wait until the map marks us up (prepare_boot round trip;
        # _post_map_epoch keeps re-sending boot if adjudication lags)
        for _ in range(200):
            if self.osdmap is not None and \
                    self.osdmap.is_up(self.osd_id) and \
                    self.osdmap.osd_addrs.get(self.osd_id) == addr:
                break
            await asyncio.sleep(0.02)
        self._hb_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop())
        scrub_iv = float(self.config.get("osd_scrub_interval", 0))
        if scrub_iv > 0:
            self._scrub_task = asyncio.get_running_loop().create_task(
                self._scrub_loop(scrub_iv))
        admin_path = self.config.get("admin_socket", "")
        if admin_path:
            self._start_admin_socket(admin_path)
        return addr

    def _admin_commands(self):
        """name -> (handler, help): one admin surface served both by
        the local admin socket and by MOSDCommand over the wire (the
        reference's asok commands vs `ceph tell osd.N` duality —
        OSD::do_command and AdminSocket share the handler tables)."""
        return {
            "dump_ops_in_flight": (
                lambda cmd: self.op_tracker.dump_in_flight(),
                "show in-flight client ops"),
            "dump_historic_ops": (
                lambda cmd: self.op_tracker.dump_historic(),
                "show recently completed client ops"),
            "perf dump": (
                lambda cmd: self._cmd_perf_dump(),
                "data-path transfer/dispatch counters + tier/"
                "plan-cache/encode-service sub-sections"),
            "tier_status": (
                lambda cmd: self.tier.status(),
                "read-tier cache occupancy + hit/miss/promote/evict"
                " counters"),
            "hedge_status": (
                lambda cmd: self.hedge.status(),
                "hedged-read scheduler: per-peer latency EWMAs/p95 +"
                " breaker states, hedges fired/won, cancelled"
                " sub-reads, Δ escalation"),
            "hitset_dump": (
                lambda cmd: self._cmd_hitset_dump(),
                "per-PG hot-set stacks + persisted hitset omap keys"),
            "dump_pgs": (
                lambda cmd: {str(pg): {"state": st.state,
                                       "primary": st.primary,
                                       "acting": list(st.acting)}
                             for pg, st in list(self.pgs.items())},
                "per-PG state"),
            "scrub_stats": (
                lambda cmd: dict(self.scrub_stats),
                "lifetime scrub object/error/repair counters"),
            "encode_service": (
                lambda cmd: self.encode_service.stats(),
                "micro-batching encode service: batch/fill/wait"
                " histograms, queue depth, inline fallbacks"),
            "device_health": (
                lambda cmd: self._cmd_device_health(),
                "per-family circuit-breaker states, trip/probe/"
                "fallback counters, per-chip breakers + live mesh"
                " membership, poisoned-plan quarantine, and the"
                " active fault-injection spec"),
            "qos_status": (
                lambda cmd: self._cmd_qos_status(),
                "per-tenant mClock QoS: scheduler grant/queue state,"
                " tenant profiles, admission-gate admit/delay/shed"
                " decisions and live bucket levels"),
            "store_status": (
                lambda cmd: self._cmd_store_status(),
                "backing object store: type, fsid, mount state,"
                " statfs, and the durability counters (journal"
                " replays/bytes, csum read failures, deferred-queue"
                " depth, fsyncs)"),
            "dump_traces": (
                lambda cmd: {"spans": self.tracer.dump(
                    int(cmd["trace_id"], 16)
                    if cmd.get("trace_id") else None)},
                "blkin-role spans collected on this daemon"),
            "dump_op_trace": (
                lambda cmd: self._cmd_dump_op_trace(
                    cmd.get("trace_id", "")),
                "render one tail-exemplar op's span tree with"
                " critical-path stage self-times (no trace_id lists"
                " the retained exemplars)"),
            "statfs": (
                lambda cmd: self._cmd_statfs(),
                "store usage + per-pool object/byte breakdown"),
            "inference_status": (
                lambda cmd: self.inference.perf_dump(),
                "coded inference serving: query/approx/fallback"
                " counters, substituted streams, and the estimated"
                " relative-error histogram"),
        }

    def _cmd_perf_dump(self) -> Dict[str, Any]:
        """Flat data-path counters plus the nested observability
        sections the prometheus exporter flattens: tier (hit-set +
        cache), plan_cache (ExecPlan hits/misses/retraces/dispatches)
        and encode_service (micro-batching counters + per-profile
        batch/fill stats)."""
        from ceph_tpu.ec import plan as ec_plan

        out: Dict[str, Any] = dict(self.perf)
        out["tier"] = self.tier.counters()
        out["plan_cache"] = {
            k: int(v) for k, v in ec_plan.stats().items()
            if isinstance(v, (bool, int))}
        svc = self.encode_service.stats()
        out["encode_service"] = {
            k: (int(v) if isinstance(v, bool) else v)
            for k, v in svc.items()
            if isinstance(v, (bool, int, float))}
        out["encode_service"]["profiles"] = {
            label: {k: v for k, v in st.items()
                    if isinstance(v, (int, float, dict))
                    and not isinstance(v, bool)}
            for label, st in svc.get("profiles", {}).items()}
        # breaker states per dispatch family (numeric-only: the
        # prometheus flattener exports state as the state_code gauge);
        # per-chip breakers ride a `devices` label map so each chip is
        # a ceph_osd_device_health_device_*{device=...} row, with its
        # live mesh membership alongside
        from ceph_tpu.common import circuit

        dh = circuit.perf_dump()
        devices = {
            dev: {k: v for k, v in st.items()
                  if not isinstance(v, str)}
            for dev, st in circuit.device_stats().items()}
        if devices:
            healthy = set(ec_plan.mesh_info().get("healthy", []))
            for dev, st in devices.items():
                st["mesh_member"] = int(int(dev) in healthy)
            dh["devices"] = devices
        out["device_health"] = dh
        # hedged-read scheduler: counters + the per-peer EWMA model
        # (the prometheus flattener turns `peers` into peer-labeled
        # rows)
        out["hedge"] = self.hedge.perf()
        # coded-compute engine: pushdown-vs-fallback split + result
        # bytes moved (the scan observability surface)
        out["compute"] = self.compute.perf()
        # coded inference serving: approx-vs-exact split + the
        # est_error histogram (flattens to ceph_osd_inference_* rows)
        out["inference"] = self.inference.perf_dump()
        # per-tenant QoS: scheduler queue/grant state + admission
        # decisions (`tenants` flattens to tenant-labeled rows)
        out["qos"] = self._qos_perf()
        # backing-store durability counters (TPUStore; MemStore has
        # none) — flattens to ceph_osd_store_* gauges
        pc = getattr(self.store, "perf_counters", None)
        if callable(pc):
            out["store"] = {k: v for k, v in pc().items()
                            if isinstance(v, (int, float))}
        # group commit: batches / txns-per-batch histogram / window-
        # vs-budget flushes (fsyncs_saved rides the store section as
        # gc_fsyncs_saved) — ceph_osd_group_commit_* rows
        gc = self.committer.stats()
        out["group_commit"] = {
            k: (int(v) if isinstance(v, bool) else v)
            for k, v in gc.items()
            if isinstance(v, (bool, int, float))}
        out["group_commit"]["txns_per_batch_hist"] = \
            dict(gc["txns_per_batch_hist"])
        # op tracker: lifetime op count, in-flight gauge, slow-op and
        # tail-exemplar totals
        out["op_tracker"] = self.op_tracker.perf()
        # critical-path tracing: per-stage self-time histograms (the
        # `stage` label map flattens to ceph_osd_trace_stage_* rows)
        out["trace"] = {
            "enabled": int(self.tracer.enabled),
            "sample_rate": self.tracer.sample_rate,
            **self.tracer.counters,
            "stage": self.tracer.stage_perf(),
        }
        return out

    def _cmd_dump_op_trace(self, trace_id: str) -> Dict[str, Any]:
        """One tail-exemplar op's journey: the span tree, the
        critical-path stage decomposition, and a rendered text tree
        (self-time per span) — the operator's answer to 'which stage
        did this slow op spend its time in'."""
        if not trace_id:
            return {"exemplars": self.op_tracker.exemplar_ids()}
        doc = self.op_tracker.get_trace(trace_id)
        if doc is None:
            return {"error": f"no exemplar for trace {trace_id!r}",
                    "exemplars": self.op_tracker.exemplar_ids()}
        cp = doc.get("critical_path") or {}
        rendered = [
            "{}{} [{}] self={:.3f}ms span={:.3f}ms".format(
                "  " * e.get("depth", 0), e.get("name", ""),
                e.get("stage", ""), e.get("self_us", 0) / 1e3,
                e.get("span_us", 0) / 1e3)
            for e in cp.get("path", [])]
        return {**doc, "rendered": rendered}

    def _cmd_store_status(self) -> Dict[str, Any]:
        """The operator view of the backing store: what engine, which
        disk (fsid), is it mounted, how full, and whether the
        durability machinery (deferred WAL, csum reads) has been
        exercised or is reporting failures."""
        pc = getattr(self.store, "perf_counters", None)
        return {
            "type": type(self.store).__name__,
            "fsid": getattr(self.store, "fsid", ""),
            "mounted": bool(getattr(self.store, "_mounted", True)),
            "statfs": self.store.statfs(),
            "perf": pc() if callable(pc) else {},
            "group_commit": self.committer.stats(),
        }

    def _qos_perf(self) -> Dict[str, Any]:
        """Nested `qos` perf-dump section: numeric scheduler state
        plus the admission gate's decision counters, with per-tenant
        rows under the `tenants` label map."""
        st = self.scheduler.stats()
        adm = self.admission.perf()
        adm["admission_enabled"] = adm.pop("enabled", 0)
        tenants: Dict[str, Dict[str, Any]] = {
            t: dict(c) for t, c in adm.pop("tenants", {}).items()}
        for cls, depth in st.get("queue_depths", {}).items():
            if cls.startswith(sched_mod.TENANT_PREFIX):
                t = cls[len(sched_mod.TENANT_PREFIX):]
                tenants.setdefault(t, {})["queue_depth"] = depth
        for cls, n in st.get("granted", {}).items():
            if cls.startswith(sched_mod.TENANT_PREFIX):
                t = cls[len(sched_mod.TENANT_PREFIX):]
                tenants.setdefault(t, {})["granted"] = n
        return {
            "enabled": int(self._qos_tenants_enabled),
            "in_flight": st["in_flight"],
            "queued": st["queued"],
            "max_concurrent": st["max_concurrent"],
            "max_queue_depth": st["max_queue_depth"],
            "queue_shed": sum(st.get("queue_shed", {}).values()),
            "cancelled_before_grant":
                st.get("cancelled_before_grant", 0),
            **adm,
            "tenants": tenants,
        }

    def _cmd_qos_status(self) -> Dict[str, Any]:
        """The operator view of 'who is being served, delayed, shed,
        and under what profile' — scheduler + admission in one
        dump."""
        out: Dict[str, Any] = {
            "enabled": self._qos_tenants_enabled,
            "scheduler": self.scheduler.stats(),
            "admission": self.admission.status(),
        }
        if isinstance(self.scheduler, sched_mod.MClockScheduler):
            out["tenant_default"] = list(
                self.scheduler.tenant_default)
            out["tenant_profiles"] = {
                t: list(p) for t, p in
                self.scheduler.tenant_profiles.items()}
        return out

    def _cmd_device_health(self) -> Dict[str, Any]:
        """The device-tier fault surface: breaker state machines,
        poisoned-plan quarantine, encode-service shed accounting, and
        whatever fault injection is currently scripted — the operator
        view of 'is the accelerator path healthy, and what is serving
        traffic while it is not'."""
        from ceph_tpu.common import circuit
        from ceph_tpu.ec import plan as ec_plan

        return {
            "breakers": circuit.stats_all(),
            # per-chip health + the live mesh: which chips are in the
            # dispatch mesh right now, which are held out, and the
            # shrink/probe history ('one sick chip shrinks the mesh,
            # not the batch to host' — the operator proof)
            "devices": circuit.device_stats(),
            "mesh": ec_plan.mesh_info(),
            "plan_quarantine": ec_plan.quarantine_info(),
            "encode_service_device_fallback":
                self.encode_service.counters.get("device_fallback", 0),
            "encode_service_mesh_batches":
                self.encode_service.counters.get("mesh_batches", 0),
            "decode_host_retries":
                self.perf.get("decode_host_retries", 0),
            "injection": flags.get(
                "CEPH_TPU_INJECT_DEVICE_FAIL") or "",
            "guard_enabled": circuit.enabled(),
        }

    def _cmd_hitset_dump(self) -> Dict[str, Any]:
        """Live per-PG stacks + the hitset omap keys persisted on this
        daemon's shard collections (the kv omap prefix archive)."""
        out: Dict[str, Any] = {"stacks": self.tier.hitset_dump(),
                               "persisted": {}}
        for pg, state in list(self.pgs.items()):
            pool = self.osdmap.pools.get(pg.pool) \
                if self.osdmap else None
            if pool is None:
                continue
            shard = state.my_shard(self.osd_id, pool.type)
            try:
                omap = self.store.omap_get(self._cid(pg, shard),
                                           ObjectId(PGMETA_OID))
            except (KeyError, IOError):
                continue
            keys = sorted(k for k in omap
                          if k.startswith(HITSET_OMAP_PREFIX))
            if keys:
                out["persisted"][str(pg)] = keys
        return out

    async def _cmd_statfs(self) -> Dict[str, Any]:
        """Store usage plus a per-pool breakdown from this OSD's own
        shard collections (the MPGStats/osd_stat_t reporting role,
        pulled over the tell surface instead of pushed): bytes are
        RAW stored bytes on THIS osd (chunks for EC, one copy for
        replicated); objects count heads only.  Yields between PGs —
        a large OSD's scan must not stall heartbeats and client I/O
        sharing the event loop."""
        out: Dict[str, Any] = dict(self.store.statfs())
        pools: Dict[int, Dict[str, int]] = {}
        for pg, state in list(self.pgs.items()):
            await asyncio.sleep(0)
            pool = self.osdmap.pools.get(pg.pool)
            if pool is None:
                continue
            try:
                my_shard = state.my_shard(self.osd_id, pool.type)
            except Exception:
                continue
            agg = pools.setdefault(pg.pool,
                                   {"objects": 0, "bytes": 0})
            for i, name in enumerate(
                    self._list_shard_objects(pg, my_shard)):
                if i % 256 == 255:
                    await asyncio.sleep(0)
                try:
                    st = self.store.stat(self._cid(pg, my_shard),
                                         ObjectId(name))
                except (KeyError, IOError, OSError):
                    continue
                agg["bytes"] += int(st.get("size", 0))
                if not is_internal_name(name):
                    agg["objects"] += 1
        out["pools"] = {str(k): v for k, v in pools.items()}
        return out

    def _start_admin_socket(self, path: str) -> None:
        from ceph_tpu.common.admin_socket import AdminSocket

        loop = asyncio.get_running_loop()

        def wrap(fn):
            # the asok serve thread is synchronous: run coroutine
            # handlers on the daemon loop and wait for the result
            def call(cmd):
                out = fn(cmd)
                if asyncio.iscoroutine(out):
                    return asyncio.run_coroutine_threadsafe(
                        out, loop).result(30)
                return out
            return call

        sock = AdminSocket(path, version=f"ceph_tpu osd.{self.osd_id}")
        for name, (fn, help_text) in self._admin_commands().items():
            sock.register_command(name, wrap(fn), help_text)
        sock.init()
        self._admin_socket = sock

    async def stop(self) -> None:
        self._stopping = True
        for task in list(self._promote_tasks):
            task.cancel()
        if self._promote_tasks:
            await asyncio.gather(*list(self._promote_tasks),
                                 return_exceptions=True)
        await self.scheduler.stop()
        # after the scheduler drained: no new client ops enqueue, and
        # any encode futures still in flight resolve before teardown
        await self.encode_service.stop()
        # flush the group-commit window: every acked txn is durable
        # and no caller is stranded on an unresolved commit future
        await self.committer.stop()
        if self._admin_socket is not None:
            # shutdown joins the serve thread: keep that wait OFF the
            # shared event loop (co-hosted daemons keep running)
            await asyncio.to_thread(self._admin_socket.shutdown)
        if self._scrub_task is not None:
            self._scrub_task.cancel()
        if self._hb_task is not None:
            self._hb_task.cancel()
        for ps in self.pgs.values():
            if ps.peering_task is not None:
                ps.peering_task.cancel()
            if ps._unfound_retry is not None:
                ps._unfound_retry.cancel()
        await self.msgr.shutdown()
        if self._own_store:
            self.store.umount()

    async def kill(self) -> None:
        """Crash: drop off the network without unmounting cleanly."""
        self._stopping = True
        if self._hb_task is not None:
            self._hb_task.cancel()
        if self._scrub_task is not None:
            self._scrub_task.cancel()
        for task in list(self._promote_tasks):
            task.cancel()
        await self.scheduler.stop()
        await self.encode_service.stop()
        # drain the commit lane even on crash-style teardown: an
        # ACKED txn sitting in a worker-thread batch must reach the
        # store before the harness power-cuts it (unacked window
        # txns flush too — they simply commit unacked, which the
        # crash model allows; acked-but-lost is what it forbids)
        await self.committer.stop()
        for ps in self.pgs.values():
            if ps.peering_task is not None:
                ps.peering_task.cancel()
            if ps._unfound_retry is not None:
                ps._unfound_retry.cancel()
        await self.msgr.shutdown()

    # -- plumbing ----------------------------------------------------------

    def _next_tid(self) -> int:
        self._tid += 1
        return self._tid

    def _codec(self, pool_id: int):
        codec = self._codecs.get(pool_id)
        if codec is None:
            pool = self.osdmap.pools[pool_id]
            profile = self.osdmap.erasure_code_profiles[
                pool.erasure_code_profile]
            codec = create_erasure_code(dict(profile))
            self._codecs[pool_id] = codec
        return codec

    def _sinfo(self, pool_id: int) -> ec_util.StripeInfo:
        codec = self._codec(pool_id)
        k = codec.get_data_chunk_count()
        # per-profile stripe_unit override, falling back to the global
        # default — the reference's erasure-code-profile stripe_unit
        # key (OSDMonitor.cc parse_erasure_code_profile; option
        # osd_pool_erasure_code_stripe_unit options.cc:2662).  Larger
        # units amortize per-chunk costs (crc lane combines, region-op
        # setup) on big-object pools.
        pool = self.osdmap.pools[pool_id]
        profile = self.osdmap.erasure_code_profiles.get(
            pool.erasure_code_profile, {})
        base = int(profile.get(
            "stripe_unit",
            self.config["osd_pool_erasure_code_stripe_unit"]))
        unit = codec.get_chunk_size(k * base)
        return ec_util.StripeInfo(k, k * unit)

    def _op_fast_lane_ok(self, pool, nbytes: int) -> bool:
        """Gate for the sub-chunk client-op fast lane: EC-pool ops
        whose payload fits in one chunk (the small-object band the
        encode service packs into native tape batches).  Anything
        bigger keeps the queued path — large ops are the ones mClock
        reordering actually helps."""
        if not self._op_fast_lane or pool.type != TYPE_ERASURE:
            return False
        try:
            return nbytes <= self._sinfo(pool.id).get_chunk_size()
        except Exception:
            return False

    async def _traced_subwrite(self, osd: int, msg: Message,
                               tid: int) -> Optional[Message]:
        """Per-peer `subwrite osd.N` stage span around the ack wait —
        the write-side twin of hedge.py's per-peer subread spans, so a
        slow replica's ack attributes to ITS span instead of opaque
        osd_op self-time.  child_span installs the span as current, so
        _request stamps the wire context with the PER-PEER span and
        the replica's sub_write tree parents under it."""
        async with tracing.child_span(f"subwrite osd.{osd}", peer=osd):
            return await self._request(osd, msg, tid)

    async def _request(self, osd: int, msg: Message,
                       tid: int) -> Optional[Message]:
        """Send to a peer OSD and await the tid-matched reply; None on
        timeout/fault (caller treats the shard as unavailable)."""
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            return None
        if isinstance(msg, (MOSDSubWrite, MOSDSubRead,
                            MOSDSubCompute)) and \
                msg.trace is None:
            # sub-ops fanned out under a SAMPLED client op inherit its
            # span as parent (blkin's "span per sub-op" shape); the
            # hedged sub-read fan-out rides the same tail field
            # (MOSDSubRead v4).  Unsampled ops do NOT propagate: the
            # peer would pay span + ring retention for a trace nobody
            # keeps (tail exemplars are primary-local trees)
            parent = tracing.current_span.get()
            if parent is not None and parent.sampled and \
                    parent.context is not None:
                msg.trace = parent.context
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._futures[tid] = fut
        try:
            await self.msgr.send_to(addr, msg)
            return await asyncio.wait_for(
                fut, self.config["osd_sub_op_timeout"])
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return None
        finally:
            self._futures.pop(tid, None)

    def _resolve(self, tid: int, msg: Message) -> bool:
        fut = self._futures.get(tid)
        if fut is not None and not fut.done():
            fut.set_result(msg)
            return True
        return False

    # -- dispatch ----------------------------------------------------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        if isinstance(msg, MConfig):
            self._apply_central_config(msg)
            return
        if isinstance(msg, MOSDMapMsg):
            self._handle_map(msg)
        elif isinstance(msg, MPing):
            await self._handle_ping(conn, msg)
        elif isinstance(msg, MOSDOp):
            await self._handle_client_op(conn, msg)
        elif isinstance(msg, MOSDCompute):
            await self._handle_compute_op(conn, msg)
        elif isinstance(msg, MOSDSubWrite):
            await self._handle_sub_write(conn, msg)
        elif isinstance(msg, MOSDSubRead):
            await self._handle_sub_read(conn, msg)
        elif isinstance(msg, MOSDSubCompute):
            await self._handle_sub_compute(conn, msg)
        elif isinstance(msg, (MOSDSubWriteReply, MOSDSubReadReply,
                              MOSDSubComputeReply)):
            self._resolve(msg.tid, msg)
        elif isinstance(msg, MWatchNotifyAck):
            self._handle_notify_ack(conn, msg)
        elif isinstance(msg, MPGQuery):
            await self._handle_pg_query(conn, msg)
        elif isinstance(msg, MPGLogMsg):
            if msg.is_reply:
                self._resolve(msg.tid, msg)  # late replies just drop
            else:
                await self._handle_pg_log_push(conn, msg)
        elif isinstance(msg, MOSDCommand):
            await self._handle_osd_command(conn, msg)

    async def _handle_osd_command(self, conn: Connection,
                                  msg: MOSDCommand) -> None:
        """`ceph tell osd.N` surface: the admin-socket command table
        served over the wire (OSD::do_command role)."""
        prefix = msg.cmd.get("prefix", "")
        entry = self._admin_commands().get(prefix)
        try:
            if entry is not None:
                out = entry[0](msg.cmd)
                if asyncio.iscoroutine(out):
                    out = await out  # async handlers (statfs scan)
                rc = 0
            elif prefix == "scrub":
                # trigger an immediate scrub of my primary PGs and
                # report the run's totals (`ceph tell osd.N scrub`)
                out = {"objects": 0, "errors": 0, "repaired": 0}
                for pg, state in list(self.pgs.items()):
                    if state.primary != self.osd_id or \
                            state.state != "active" or self.osdmap is None:
                        continue
                    pool = self.osdmap.pools.get(pg.pool)
                    if pool is None:
                        continue
                    run = await self.scrub_pg(state, pool)
                    for key in out:
                        out[key] += run[key]
                rc = 0
            else:
                rc, out = EINVAL, {"error": f"unknown command {prefix!r}"}
        except Exception as e:
            log.exception("osd.%d: command %r failed", self.osd_id,
                          prefix)
            rc, out = EINVAL, {"error": str(e)}
        await conn.send(MOSDCommandReply(msg.tid, rc, out))

    # -- map handling ------------------------------------------------------

    def _apply_central_config(self, msg: MConfig) -> None:
        """ConfigMonitor push: overlay centralized options with the
        reference's mask precedence (global < osd < osd.N), coerced to
        the local option's existing type.  Loops read config per tick,
        so changes take effect live."""
        merged: Dict[str, str] = {}
        for section in ("global", "osd", f"osd.{self.osd_id}"):
            merged.update(msg.values.get(section, {}))
        if not hasattr(self, "_central_baseline"):
            self._central_baseline: Dict[str, Any] = {}
        # a key REMOVED centrally reverts to its pre-override value
        # (config rm must take effect live, not at next restart)
        for name in list(self._central_baseline):
            if name not in merged:
                val = self._central_baseline.pop(name)
                log.info("osd.%d: config %s -> %r (central override"
                         " removed)", self.osd_id, name, val)
                if val is None:
                    # the option had NO local value before the central
                    # override: restore absence, not a None mapping
                    self.config.pop(name, None)
                else:
                    self.config[name] = val
        for name, raw in merged.items():
            cur = self.config.get(name)
            val: Any = raw
            try:
                if isinstance(cur, bool):
                    val = str(raw).lower() in ("1", "true", "yes", "on")
                elif isinstance(cur, int):
                    val = int(raw)
                elif isinstance(cur, float):
                    val = float(raw)
            except (TypeError, ValueError):
                log.warning("osd.%d: bad central config %s=%r",
                            self.osd_id, name, raw)
                continue
            if self.config.get(name) != val:
                self._central_baseline.setdefault(name, cur)
                log.info("osd.%d: config %s -> %r (centralized)",
                         self.osd_id, name, val)
                self.config[name] = val
        self._apply_msgr_injection()
        # sample_rate is deliberately NOT FLAG_STARTUP (options.py): a
        # central `config set osd osd_trace_sample_rate ...` must reach
        # the live Tracer, whose copy was taken at construction
        try:
            self.tracer.sample_rate = float(self.config.get(
                "osd_trace_sample_rate", self.tracer.sample_rate))
        except (TypeError, ValueError):
            pass

    def _apply_msgr_injection(self) -> None:
        """Push ms_inject_* config into the live messenger (the options
        take effect on the next frame, like the reference's md_config
        observer on AsyncMessenger).  Each option parses independently
        — one bad value must neither block the other nor vanish
        silently."""
        try:
            self.msgr.inject_socket_failures = int(
                self.config.get("ms_inject_socket_failures", 0) or 0)
        except (TypeError, ValueError):
            log.warning("osd.%d: ignoring bad ms_inject_socket_"
                        "failures=%r", self.osd_id,
                        self.config.get("ms_inject_socket_failures"))
        try:
            self.msgr.inject_internal_delays = float(
                self.config.get("ms_inject_internal_delays", 0) or 0)
        except (TypeError, ValueError):
            log.warning("osd.%d: ignoring bad ms_inject_internal_"
                        "delays=%r", self.osd_id,
                        self.config.get("ms_inject_internal_delays"))
        self.msgr.apply_compress_config(self.config)

    def _clog(self, level: str, message: str) -> None:
        """Fire one cluster-log entry at the mon (MLog role)."""
        entry = {"stamp": time.time(), "level": level,
                 "who": f"osd.{self.osd_id}", "message": message}

        async def send():
            try:
                await self.msgr.send_to(self.mon_addr, MLog([entry]))
            except (ConnectionError, OSError):
                pass

        self.msgr._spawn(send())

    def _handle_map(self, msg: MOSDMapMsg) -> None:
        """Advance the local map EPOCH BY EPOCH."""
        self._last_map_rx = time.monotonic()
        self._handle_map_inner(msg)

    def _handle_map_inner(self, msg: MOSDMapMsg) -> None:
        """Advance the local map EPOCH BY EPOCH.

        Interval detection (_scan_pgs) is only correct if every epoch is
        observed in order: a skipped epoch can hide a primary change, so
        a daemon would keep writing under an interval its replicas have
        already fenced off.  Incrementals apply contiguously; a gap
        triggers a pull of the missing range from the mon (the
        handle_osd_map / osdmap subscribe discipline, OSD.cc)."""
        from ceph_tpu.osd.osdmap import Incremental

        applied = False
        if msg.incrementals and self.osdmap is not None:
            for raw in msg.incrementals:
                inc = Incremental.decode(raw)
                if inc.epoch <= self.osdmap.epoch:
                    continue
                if inc.epoch != self.osdmap.epoch + 1:
                    log.debug("osd.%d: inc %d does not follow %d,"
                              " pulling range", self.osd_id, inc.epoch,
                              self.osdmap.epoch)
                    self._request_map_range()
                    return
                prev_up = set(self.osdmap.get_up_osds())
                self.osdmap.apply_incremental(inc)
                log.debug("osd.%d: advanced to epoch %d (inc)",
                          self.osd_id, self.osdmap.epoch)
                self._post_map_epoch(prev_up)
                applied = True
        if applied or msg.full_map is None:
            return
        newmap = OSDMap.decode(msg.full_map)
        if self.osdmap is not None and newmap.epoch <= self.osdmap.epoch:
            return
        if self.osdmap is not None and \
                newmap.epoch > self.osdmap.epoch + 1 and \
                not msg.gap_unfillable:
            self._request_map_range()
            return
        prev_up = set(self.osdmap.get_up_osds()) \
            if self.osdmap is not None else set()
        if self.osdmap is not None and msg.gap_unfillable:
            log.warning("osd.%d: adopting full map %d over a gap from"
                        " %d (mon inc log trimmed)", self.osd_id,
                        newmap.epoch, self.osdmap.epoch)
        self.osdmap = newmap
        # mutation-through-incrementals contract: enable placement memo
        self.osdmap.enable_placement_cache()
        self._post_map_epoch(prev_up)

    def _request_map_range(self) -> None:
        """Pull the incrementals between my epoch and the mon's."""
        now = time.monotonic()
        if now - getattr(self, "_last_range_req", 0.0) < 0.2:
            return
        self._last_range_req = now
        self.msgr._spawn(self.msgr.send_to(
            self.mon_addr,
            MGetMap(since_epoch=self.osdmap.epoch, subscribe=False)))

    _META_CID = "osd_meta"

    def _load_split_meta(self) -> None:
        """Split bookkeeping survives restarts: a durable OSD that was
        down across a pg_num increase must still redistribute its
        on-disk objects when it boots into the grown map."""
        try:
            omap = self.store.omap_get(self._META_CID,
                                       ObjectId("split_state"))
            doc = json.loads(omap["v"])
            self._pool_pg_nums = {int(k): v
                                  for k, v in doc["pg_nums"].items()}
            self._split_children = {PgId(p, ps)
                                    for p, ps in doc["children"]}
        except (KeyError, ValueError):
            pass

    def _save_split_meta(self, t: Optional[Transaction] = None) -> None:
        own = t is None
        if own:
            t = Transaction()
        if not self.store.collection_exists(self._META_CID):
            t.create_collection(self._META_CID)
        t.omap_setkeys(self._META_CID, ObjectId("split_state"), {
            "v": json.dumps({
                "pg_nums": self._pool_pg_nums,
                "children": sorted([p.pool, p.ps]
                                   for p in self._split_children),
            }).encode()})
        if own:
            self.store.queue_transaction(t)

    def _check_pool_splits(self) -> None:
        """pg_num growth observed: redistribute local PG state.  Safe
        across multi-epoch jumps — stable-mod placement depends only on
        the FINAL pg_num, so folding several growth steps into one
        redistribution lands objects exactly where stepwise splitting
        would."""
        changed = False
        for pool in self.osdmap.pools.values():
            old = self._pool_pg_nums.get(pool.id)
            if old != pool.pg_num:
                changed = True
            self._pool_pg_nums[pool.id] = pool.pg_num
            if old is None or pool.pg_num <= old:
                continue
            try:
                self._split_pool_pgs(pool, old, pool.pg_num)
            except Exception:
                log.exception("osd.%d: split of pool %d (%d->%d)"
                              " failed", self.osd_id, pool.id, old,
                              pool.pg_num)
        if changed:
            self._save_split_meta()

    @staticmethod
    def _head_name(name: str) -> str:
        """Companion object -> owning head (rollback generations and
        snap clones split WITH their head)."""
        if name.startswith(RB_PREFIX):
            name = name[len(RB_PREFIX):]
        return name.split(SNAP_SEP, 1)[0]

    def _split_pool_pgs(self, pool, old_num: int, new_num: int) -> None:
        """PG::split_into (PG.cc:578) re-designed for this store: move
        each object (with its companions) whose stable-mod placement
        under new_num leaves its parent into the child's shard
        collection, and partition the parent's PG log/missing by
        object the same way.  Children inherit the parent's
        last_update/log_tail, so auth-log election at the child's
        first peering prefers members holding split state."""
        from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins
        from ceph_tpu.osd.osdmap import _calc_mask
        from ceph_tpu.osd.pg_log import PGInfo

        # total-order barrier: the split both READS pgmeta from the
        # store and re-stages it, so any client txn still in the
        # group-commit window must land first — and because this
        # function never awaits, nothing can slip into the window
        # while it runs
        self.committer.flush_sync()
        mask = _calc_mask(new_num)
        if pool.type == TYPE_ERASURE:
            shard_list = list(
                range(self._codec(pool.id).get_chunk_count()))
        else:
            shard_list = [-1]

        def child_ps_of(head: str) -> int:
            from ceph_tpu.osd.osdmap import ceph_stable_mod

            return ceph_stable_mod(
                ceph_str_hash_rjenkins(head.encode()), new_num, mask)

        for ps in range(old_num):
            parent = PgId(pool.id, ps)
            for shard in shard_list:
                cid = self._cid(parent, shard)
                if not self.store.collection_exists(cid):
                    continue
                plog = PGLog.load(self.store, cid)
                moves: Dict[int, List[str]] = {}
                for o in self.store.list_objects(cid):
                    name = str(o)
                    if name == PGMETA_OID:
                        continue
                    cps = child_ps_of(self._head_name(name))
                    if cps != ps:
                        moves.setdefault(cps, []).append(name)
                child_entries: Dict[int, List[dict]] = {}
                keep_entries = []
                for e in plog.entries:
                    cps = child_ps_of(self._head_name(e.get("oid", "")))
                    if cps == ps:
                        keep_entries.append(e)
                    else:
                        child_entries.setdefault(cps, []).append(e)
                child_missing: Dict[int, Dict[str, tuple]] = {}
                keep_missing = {}
                for oid, v in plog.missing.items():
                    cps = child_ps_of(self._head_name(oid))
                    if cps == ps:
                        keep_missing[oid] = v
                    else:
                        child_missing.setdefault(cps, {})[oid] = v
                touched = (set(moves) | set(child_entries)
                           | set(child_missing))
                if not touched:
                    continue
                t = Transaction()
                for cps in touched:
                    ccid = self._cid(PgId(pool.id, cps), shard)
                    if not self.store.collection_exists(ccid):
                        t.create_collection(ccid)
                    for name in moves.get(cps, []):
                        t.collection_move_rename(
                            cid, ObjectId(name), ccid, ObjectId(name))
                    clog = PGLog(
                        PGInfo(last_update=plog.info.last_update,
                               log_tail=plog.info.log_tail),
                        child_entries.get(cps, []),
                        child_missing.get(cps, {}))
                    clog.stage(t, ccid)
                plog.entries = keep_entries
                plog.missing = keep_missing
                plog.stage(t, cid)
                self.store.queue_transaction(t)
                log.info("osd.%d: split %s shard %s: %d objects to %d"
                         " children", self.osd_id, parent, shard,
                         sum(len(v) for v in moves.values()),
                         len(touched))
            # parent's cached log is stale after the partition
            ps_state = self.pgs.get(parent)
            if ps_state is not None:
                ps_state.log = None
        for cps in range(old_num, new_num):
            child = PgId(pool.id, cps)
            self._split_children.add(child)
            cstate = self.pgs.get(child)
            if cstate is not None:
                cstate.log = None

    def _post_map_epoch(self, prev_up: Set[int]) -> None:
        """Per-epoch bookkeeping after the local map advanced."""
        self._check_pool_splits()
        # reset the heartbeat clock for peers that just came (back) up:
        # their last_rx predates the outage and would otherwise make us
        # insta-report the freshly booted peer as failed again
        # (maybe_update_heartbeat_peers role, OSD.cc)
        now = time.monotonic()
        for osd in self.osdmap.get_up_osds():
            if osd not in prev_up:
                self._hb_last_rx[osd] = now
        self._map_event.set()
        self._map_event = asyncio.Event()
        # falsely marked down while alive: re-boot (MOSDAlive role).
        # NOT while heartbeat-muted — an injected heartbeat outage must
        # look dead to the cluster, so re-booting through it would
        # defeat the injection (recovery happens when the mute expires)
        if not self.osdmap.is_up(self.osd_id) and not self._stopping \
                and now >= self._hb_mute_until \
                and self.msgr.addr and \
                time.monotonic() - self._last_boot_sent > 1.0:
            self._last_boot_sent = time.monotonic()
            self.msgr._spawn(self.msgr.send_to(
                self.mon_addr, MOSDBoot(self.osd_id, self.msgr.addr)))
        self._scan_pgs()

    def _scan_pgs(self) -> None:
        """Map epoch changed: find my PGs, detect interval changes,
        kick peering where I'm primary (the load_pgs/advance_pg role)."""
        for pool in self.osdmap.pools.values():
            for ps_num in range(pool.pg_num):
                pg = PgId(pool.id, ps_num)
                acting, primary = self.osdmap.pg_to_acting_osds(pg)
                in_acting = self.osd_id in [
                    o for o in acting if o != CRUSH_ITEM_NONE]
                state = self.pgs.get(pg)
                if state is None:
                    if not in_acting:
                        continue
                    state = PGState(pg)
                    self.pgs[pg] = state
                if state.acting != acting or state.primary != primary:
                    # every member records EVERY membership change —
                    # including intervals it is not part of.  Skipping
                    # the not-in-acting epochs would make a member that
                    # leaves and rejoins with identical membership see
                    # "no change" and keep an interval stamp its peers
                    # have long fenced off.  Deterministic because
                    # _handle_map advances epoch by epoch, so all
                    # daemons observe the same acting-change epochs
                    # (same_interval_since discipline).
                    state.acting = acting
                    state.primary = primary
                    state.interval_epoch = self.osdmap.epoch
                    state.state = "inactive"
                    state.active_event.clear()
                    # primary-side extent cache and read tier are only
                    # coherent within one interval — a new primary may
                    # have applied writes this daemon never saw
                    state.extent_cache.clear()
                    self.tier.drop_pg(pg)
                    if state.peering_task is not None:
                        state.peering_task.cancel()
                        state.peering_task = None
                    if state._unfound_retry is not None:
                        state._unfound_retry.cancel()
                        state._unfound_retry = None
                if not in_acting:
                    state.state = "inactive"
                    state.active_event.clear()
                    if state.peering_task is not None:
                        state.peering_task.cancel()
                        state.peering_task = None
                    continue
                if primary == self.osd_id and state.peering_task is None \
                        and (state.state == "inactive" or
                             (state.state == "active" and state.unfound)):
                    # an unfound-carrying PG re-peers on ANY map change:
                    # a revived stray may now hold the needed shards
                    state.state = "peering"
                    state.active_event.clear()
                    state.peering_task = \
                        asyncio.get_running_loop().create_task(
                            self._peer_pg(state, pool))
                self._note_trim_candidates(state, pool)

    # -- heartbeats --------------------------------------------------------

    async def _handle_ping(self, conn: Connection, msg: MPing) -> None:
        if time.monotonic() < self._hb_mute_until:
            return  # injected heartbeat failure: swallow pings silently
        if msg.from_osd >= 0:
            self._hb_last_rx[msg.from_osd] = time.monotonic()
        if msg.kind == PING:
            await conn.send(MPing(PING_REPLY, msg.stamp,
                                  epoch=self._epoch(),
                                  from_osd=self.osd_id))

    def _epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap is not None else 0

    def _heartbeat_peers(self) -> Set[int]:
        """Bounded peer set (OSD.cc maybe_update_heartbeat_peers role):
        OSDs sharing a PG with me, plus my ring neighbors in the sorted
        up set so detection coverage stays connected, capped at
        osd_heartbeat_max_peers.  The full N x N mesh is quadratic
        traffic and saturates loops past ~8 daemons."""
        pg_peers: Set[int] = set()
        for state in self.pgs.values():
            for osd in state.acting:
                if osd != CRUSH_ITEM_NONE and osd != self.osd_id:
                    pg_peers.add(osd)
        ring: Set[int] = set()
        up = [o for o in self.osdmap.get_up_osds() if o != self.osd_id]
        if up:
            # ring neighbors by rank around my id
            pos = bisect.bisect_left(up, self.osd_id)
            ring.add(up[pos % len(up)])
            ring.add(up[(pos - 1) % len(up)])
        cap = int(self.config.get("osd_heartbeat_max_peers", 10))
        pg_peers = {p for p in pg_peers
                    if self.osdmap.is_up(p) and p not in ring}
        # the cap trims only the PG-peer overflow — ring neighbors are
        # the connectedness guarantee (a naive global sort-and-truncate
        # would leave the highest-id OSDs unmonitored by everyone)
        keep = max(0, cap - len(ring))
        if len(pg_peers) > keep:
            pg_peers = set(sorted(pg_peers)[:keep])
        return ring | pg_peers

    async def _heartbeat_loop(self) -> None:
        interval = self.config["osd_heartbeat_interval"]
        grace = self.config["osd_heartbeat_grace"]
        while not self._stopping:
            await asyncio.sleep(interval)
            try:
                await self._heartbeat_once(interval, grace)
            except asyncio.CancelledError:
                raise
            except Exception:
                # this loop carries failure detection AND the mon-
                # subscription keepalive: one bad iteration must
                # never kill it for the daemon's lifetime (a silent
                # death here recreates the mapless-zombie wedge)
                log.exception("osd.%d: heartbeat iteration failed",
                              self.osd_id)

    async def _heartbeat_once(self, interval: float,
                              grace: float) -> None:
        now = time.monotonic()
        # mon session keepalive: a restarted mon loses subscriber
        # connections silently, and a BOOT whose subscription
        # sends were injected/faulted away leaves this daemon
        # mapless — in both cases maps go quiet.  This check runs
        # BEFORE the mapless guard below: osdmap None is the
        # WORST staleness, not an exemption (a zombie OSD that
        # never re-subscribes wedges recovery cluster-wide; found
        # by the injection thrasher).
        if now - self._last_map_rx > max(5.0, 4 * interval):
            self._last_map_rx = now
            epoch = self.osdmap.epoch if self.osdmap else 0
            # a MAPLESS renew is abnormal (boot subscription
            # lost); a steady-state renew on an idle cluster is
            # routine and must not spam the log
            (log.info if epoch == 0 else log.debug)(
                "osd.%d: mon quiet at epoch %s; re-subscribing",
                self.osd_id, epoch or "none")
            # hunt: rotating through the monmap finds a serving
            # peer behind a dead mon / dropped conn
            self._hunt_mon()
            try:
                await self.msgr.send_to(
                    self.mon_addr,
                    MGetMap(since_epoch=epoch, subscribe=True))
                if self.osdmap is None and self.msgr.addr:
                    # never booted into the map either: the mon
                    # may not know this daemon exists at all
                    await self.msgr.send_to(
                        self.mon_addr,
                        MOSDBoot(self.osd_id, self.msgr.addr))
            except (ConnectionError, OSError):
                pass  # this mon down too; next cycle hunts on
        if self.osdmap is None:
            return
        # one-shot injected heartbeat outage
        # (heartbeat_inject_failure = seconds of silence): mute
        # pings AND replies for that long, then self-clear.  Peers
        # see a dead heartbeat surface on a live daemon — exactly
        # the failure the mon's reporter quorum must adjudicate.
        inj = float(self.config.get(
            "heartbeat_inject_failure", 0) or 0)
        if inj > 0 and now >= self._hb_mute_until:
            self.config["heartbeat_inject_failure"] = 0
            self._hb_mute_until = now + inj
            log.warning("osd.%d: injecting %.1fs heartbeat"
                        " failure", self.osd_id, inj)
        if now < self._hb_mute_until:
            self._hb_resume_stale = True
            return
        if getattr(self, "_hb_resume_stale", False):
            # coming out of a mute: every peer timestamp is stale by
            # the mute length — restart the clocks or this daemon
            # would instantly (and falsely) report every peer failed
            self._hb_resume_stale = False
            self._hb_last_rx.clear()
            # and if the outage got us (rightly) marked down, no map
            # event will re-fire the MOSDAlive path — re-boot now
            if not self.osdmap.is_up(self.osd_id) and self.msgr.addr:
                self._last_boot_sent = now
                try:
                    await self.msgr.send_to(
                        self.mon_addr,
                        MOSDBoot(self.osd_id, self.msgr.addr))
                except (ConnectionError, OSError):
                    pass
        self.op_tracker.check_slow()
        peers = self._heartbeat_peers()
        # prune state for ex-peers so a later re-add restarts fresh
        for gone in set(self._hb_last_rx) - peers:
            self._hb_last_rx.pop(gone, None)

        async def ping_one(peer: int) -> None:
            addr = self.osdmap.osd_addrs.get(peer)
            if addr is None:
                return
            self._hb_last_rx.setdefault(peer, now)
            try:
                await self.msgr.send_to(
                    addr, MPing(PING, now, epoch=self._epoch(),
                                from_osd=self.osd_id))
            except (ConnectionError, OSError):
                pass
            elapsed = now - self._hb_last_rx[peer]
            if elapsed > grace:
                # report to mon (send_failures, OSD.cc:5889)
                try:
                    await self.msgr.send_to(
                        self.mon_addr,
                        MOSDFailure(peer, self.osd_id, elapsed,
                                    self._epoch()))
                except (ConnectionError, OSError):
                    pass

        await asyncio.gather(*(ping_one(p) for p in peers))

    # -- local shard store helpers -----------------------------------------

    def _cid(self, pg: PgId, shard: int) -> str:
        return shard_collection(pg, shard)

    def _load_log(self, state: PGState, pool) -> PGLog:
        if state.log is None:
            shard = state.my_shard(self.osd_id, pool.type)
            state.log = PGLog.load(self.store, self._cid(state.pg, shard))
        return state.log

    def _apply_shard_ops(self, t: Transaction, cid: str, oid: str,
                         ops: List[ShardOp],
                         save_rollback: bool = False) -> None:
        obj = ObjectId(oid)
        if not self.store.collection_exists(cid):
            t.create_collection(cid)
        if save_rollback:
            # preserve the current generation before overwriting: until
            # this write commits on every shard, the previous version
            # must stay reconstructable
            try:
                self.store.stat(cid, obj)
            except (KeyError, IOError):
                pass
            else:
                t.clone(cid, obj, ObjectId(RB_PREFIX + oid))
        for op in ops:
            if op.op == "create":
                t.touch(cid, obj)
            elif op.op == "truncate":
                t.truncate(cid, obj, op.size)
            elif op.op == "write":
                t.write(cid, obj, op.offset, len(op.data), op.data)
            elif op.op == "setattr":
                t.setattr(cid, obj, op.name, op.value)
            elif op.op == "rmattr":
                t.rmattr(cid, obj, op.name)
            elif op.op == "omap_set":
                t.omap_setkeys(cid, obj, _decode_kv_map(op.data))
            elif op.op == "omap_rm":
                t.omap_rmkeys(cid, obj, _decode_str_list(op.data))
            elif op.op == "omap_clear":
                t.omap_clear(cid, obj)
            elif op.op == "remove":
                t.remove(cid, obj)
                # the rollback clone goes with it: a deleted object
                # whose clone survives is RESURRECTABLE — the
                # rollback-aware recovery gather would reassemble the
                # pre-remove generation from k surviving clones and
                # reinstall an object the client was told is gone
                t.remove(cid, ObjectId(RB_PREFIX + oid))
            elif op.op == "clone":
                # snapshot clone-on-write (make_writeable role): copy
                # the shard's CURRENT state to the clone object.  A
                # shard that doesn't hold the object yet (degraded)
                # simply skips — recovery will reconstruct the clone.
                try:
                    self.store.stat(cid, obj)
                except (KeyError, IOError):
                    pass
                else:
                    t.clone(cid, obj, ObjectId(op.name))
            else:
                raise ValueError(f"unknown shard op {op.op!r}")

    def _read_shard(self, pg: PgId, shard: int, oid: str,
                    offset: int = 0, length: int = 0
                    ) -> Tuple[int, bytes, Dict[str, bytes]]:
        """Local shard read with attrs; rc<0 on missing/corrupt.
        offset/length push the range down to the STORE so a ranged read
        costs O(range) of store I/O, not O(shard)."""
        cid = self._cid(pg, shard)
        obj = ObjectId(oid)
        try:
            data = self.store.read(cid, obj, offset, length)
            attrs = self.store.getattrs(cid, obj)
        except KeyError:
            return ENOENT, b"", {}
        except IOError:
            return EIO, b"", {}
        return 0, data, attrs

    # -- sub-ops (replica side) --------------------------------------------

    async def _handle_sub_write(self, conn: Connection,
                                msg: MOSDSubWrite) -> None:
        if msg.trace is not None:
            # tracer.span installs the span as current: the replica-
            # side stage spans below (kv_commit/fsync in the store,
            # contended objlock) attach to THIS tree — the place the
            # write actually pays its durability cost must not render
            # as an opaque span
            async with self.tracer.span(
                    f"sub_write {msg.oid} shard {msg.shard}",
                    context=msg.trace):
                await self._handle_sub_write_inner(conn, msg)
            return
        await self._handle_sub_write_inner(conn, msg)

    async def _handle_sub_write_inner(self, conn: Connection,
                                      msg: MOSDSubWrite) -> None:
        state = self.pgs.get(msg.pg)
        # fencing: a primary from an older interval must not mutate
        if state is not None and msg.epoch < state.interval_epoch:
            log.debug("osd.%d: sub-write %s/%s fenced: epoch %d <"
                      " interval %d", self.osd_id, msg.pg, msg.oid,
                      msg.epoch, state.interval_epoch)
            await conn.send(MOSDSubWriteReply(msg.tid, ESTALE, msg.shard))
            return
        if state is not None:
            # a newer-interval primary's write also fences older ones
            state.interval_epoch = max(state.interval_epoch, msg.epoch)
        pool = self.osdmap.pools.get(msg.pg.pool) if self.osdmap else None
        cid = self._cid(msg.pg, msg.shard)
        if state is None:
            state = self.pgs.setdefault(msg.pg, PGState(msg.pg))
        try:
            # dispatch is concurrent per message, so two sub-writes to
            # one object can otherwise apply OUT OF ORDER — a delayed
            # older write overwriting a newer one leaves stale data
            # under a current-looking log (the reference's sequential
            # per-PG op queue makes this impossible; here the object
            # lock + version monotonicity restores it)
            async with state.obj_lock(f"sub\x00{msg.shard}\x00"
                                      f"{msg.oid}"):
                if pool is not None:
                    plog = self._load_log(state, pool)
                else:
                    plog = state.log or PGLog()
                    state.log = plog
                # version floor = newer of (stored OI, newest PG
                # log entry for this object).  The log term is
                # load-bearing after a DELETE: the remove erases
                # the object's own version history, and without it
                # a straggler sub-write of an older write would
                # silently RESURRECT the deleted object.
                def current_floor() -> Optional[tuple]:
                    floor = self._oi_version(
                        self._read_shard(msg.pg, msg.shard, msg.oid,
                                         0, 1)[2])
                    for le in reversed(plog.entries):
                        if le.get("oid") == msg.oid:
                            lv = ev(le["version"])
                            if floor is None or lv > floor:
                                floor = lv
                            break
                    return floor

                if msg.log_entry is not None:
                    # CLIENT write ordering guard
                    incoming = self._sub_write_version(msg)
                    floor = current_floor() \
                        if incoming is not None else None
                    if incoming is not None and floor is not None \
                            and incoming < floor:
                        # a late straggler that already lost the race:
                        # the newer state supersedes it — ack without
                        # applying (idempotent-outcome discipline).
                        # The reply is sent OUTSIDE the lock: a send
                        # wedged on a dead peer must never park this
                        # (shard, object)'s write lock.
                        raise _SkipApply()
                elif msg.oid not in plog.missing:
                    # RECOVERY/REPAIR sub-write (no log entry) to an
                    # object this shard is NOT missing.  Legitimate
                    # below-floor installs (divergent rewind, rollback
                    # reinstall) always target objects in the missing
                    # set; outside it, a below-floor install is a stale
                    # push — one that timed out at the primary, stayed
                    # in flight, and was overtaken by a newer client
                    # write — and applying it would silently roll this
                    # copy back under a current-looking PG log.  The
                    # guard token decides: the push applies only if the
                    # plan OBSERVED (adjudicated over) this shard's
                    # current state.  Covers removes too: a stale
                    # rollback-purge remove must not destroy an object
                    # a client has since recreated.
                    floor = current_floor()
                    if floor is not None:
                        rec_v = self._sub_write_version(msg)
                        observed = msg.guard is not None and \
                            msg.guard >= floor
                        if rec_v is not None:
                            if rec_v < floor and not observed:
                                raise _SkipApply()
                        elif any(op.op == "remove" for op in msg.ops):
                            # includes rollback trims: guard=prior keeps
                            # a stale trim from eating the FRESH clone a
                            # later write just preserved
                            if not observed:
                                raise _SkipApply()
                t = Transaction()
                self._apply_shard_ops(
                    t, cid, msg.oid, msg.ops,
                    save_rollback=msg.log_entry is not None)
                if msg.log_entry is not None:
                    version = ev(msg.log_entry["version"])
                    if version > plog.info.last_update:
                        plog.append(msg.log_entry)
                        plog.trim_to(
                            int(self.config["osd_min_pg_log_entries"]))
                # a write (client or recovery push) fills the object in
                if msg.log_entry is None and msg.oid in plog.missing:
                    self.perf["recovery_installs"] += 1
                plog.missing.pop(msg.oid, None)
                plog.stage(t, cid)
                # replica-side group commit: concurrent sub-writes on
                # this shard share one barrier (safe under the
                # per-(shard,object) lock — the await resolves only
                # when THIS txn is durable, so acks stay honest)
                await self.committer.queue_transaction(t)
        except _SkipApply:
            pass
        except Exception:
            log.exception("osd.%d: sub-write %s/%s failed",
                          self.osd_id, msg.pg, msg.oid)
            await conn.send(MOSDSubWriteReply(msg.tid, EIO, msg.shard))
            return
        await conn.send(MOSDSubWriteReply(msg.tid, 0, msg.shard))

    @staticmethod
    def _sub_write_version(msg: MOSDSubWrite) -> Optional[tuple]:
        """The object generation this sub-write installs: the log
        entry's version (client writes) or the OI attr riding the ops
        (recovery installs); None for version-less ops (remove,
        attr-only tweaks) which must always apply."""
        if msg.log_entry is not None:
            return ev(msg.log_entry["version"])
        for op in msg.ops:
            if op.op == "setattr" and op.name == OI_ATTR:
                try:
                    v = json.loads(op.value).get("version")
                    return ev(v) if v else None
                except (ValueError, AttributeError):
                    return None
        return None

    async def _handle_sub_read(self, conn: Connection,
                               msg: MOSDSubRead) -> None:
        if getattr(msg, "trace", None) is not None:
            # tracer.span installs the span as current so replica-side
            # annotations (tier recording, store spans) land in this
            # tree
            async with self.tracer.span(
                    f"sub_read {msg.oid} shard {msg.shard}",
                    context=msg.trace):
                await self._handle_sub_read_inner(conn, msg)
            return
        await self._handle_sub_read_inner(conn, msg)

    async def _handle_sub_read_inner(self, conn: Connection,
                                     msg: MOSDSubRead) -> None:
        state = self.pgs.get(msg.pg)
        pool = self.osdmap.pools.get(msg.pg.pool) if self.osdmap else None
        if self.tier.enabled and state is not None and \
                getattr(msg, "record", False) and \
                not is_internal_name(msg.oid) and \
                msg.oid != PGMETA_OID:
            # replica-side hot-set observability for CLIENT reads only
            # (msg.record rides from the primary's _op_read gather);
            # scrub/recovery/stat sub-reads would drown the skew
            # signal.  Promotion decisions stay with the primary's
            # own hitset.
            self.tier.record_read(msg.pg, msg.oid)
            if self.tier.sealed_pending():
                self._persist_sealed_hitsets()
        if state is not None and pool is not None:
            plog = self._load_log(state, pool)
            # the missing guard protects my CURRENT shard only; stray
            # reads of prior-interval shard collections are always fair
            # game (they serve the MissingLoc search)
            if msg.shard == state.my_shard(self.osd_id, pool.type) and \
                    msg.oid in plog.missing:
                await conn.send(MOSDSubReadReply(
                    msg.tid, ENOENT, shard=msg.shard))
                return
        if getattr(msg, "repair", None) is not None:
            await self._answer_repair_read(conn, msg, pool)
            return
        rc, data, attrs = self._read_shard(
            msg.pg, msg.shard, msg.oid,
            msg.offset if msg.length else 0, msg.length)
        omap: Dict[str, bytes] = {}
        if rc == 0 and msg.want_omap:
            try:
                omap = self.store.omap_get(
                    self._cid(msg.pg, msg.shard), ObjectId(msg.oid))
            except (KeyError, IOError):
                omap = {}
        await conn.send(MOSDSubReadReply(
            msg.tid, rc, data, attrs if msg.want_attrs else {},
            shard=msg.shard, omap=omap))

    async def _answer_repair_read(self, conn: Connection,
                                  msg: MOSDSubRead, pool) -> None:
        """Helper side of regenerating-code repair: read my full
        chunk, project it against the codec's repair vector for the
        lost chunk, ship the beta = chunk/alpha byte fragment.  Any
        mismatch with the primary's view of the codec (no fractional
        repair, alpha drift, misaligned chunk) answers EOPNOTSUPP —
        the primary treats that helper as failed and, past d
        survivors, falls back to the classic k-read path."""
        lost, alpha = msg.repair
        rc, data, attrs = self._read_shard(msg.pg, msg.shard, msg.oid,
                                           0, 0)
        if rc == 0:
            codec = self._codec(pool.id) if pool is not None else None
            if codec is None or \
                    not getattr(codec, "supports_fractional_repair",
                                lambda: False)() or \
                    codec.get_sub_chunk_count() != alpha or \
                    len(data) % max(alpha, 1):
                rc, data = EOPNOTSUPP, b""
            else:
                try:
                    frag = await asyncio.to_thread(
                        codec.repair_project, lost, data)
                    self.perf["repair_fragments"] += 1
                    data = frag
                except Exception:
                    rc, data = EOPNOTSUPP, b""
        await conn.send(MOSDSubReadReply(
            msg.tid, rc, data if rc == 0 else b"",
            attrs if msg.want_attrs and rc == 0 else {},
            shard=msg.shard))

    # -- peering -----------------------------------------------------------

    async def _handle_pg_query(self, conn: Connection,
                               msg: MPGQuery) -> None:
        pool = self.osdmap.pools.get(msg.pg.pool) if self.osdmap else None
        state = self.pgs.setdefault(msg.pg, PGState(msg.pg))
        # answering a peering query is a BARRIER: once we reply, no
        # older-interval primary may commit further writes here, or the
        # new interval could roll back an acked write (the PeeringState
        # Reset discipline — the reply's content must stay authoritative)
        state.interval_epoch = max(state.interval_epoch, msg.epoch)
        if msg.shard is not None:
            # explicit-shard query (split-child stray sweep): answer
            # from that shard's collection directly — a stray cannot
            # be located through an acting set it is not part of
            shard = msg.shard
            plog = PGLog.load(self.store,
                              self._cid(msg.pg, shard))
        else:
            shard = state.my_shard(self.osd_id, pool.type) if pool \
                else -1
            if pool is not None:
                plog = self._load_log(state, pool)
            else:
                plog = state.log or PGLog()
        info = plog.info.to_dict()
        info["missing"] = {k: list(v) for k, v in plog.missing.items()}
        # shard object listing rides along so the primary can build
        # backfill sets for peers too far behind the log tail
        info["objects"] = self._list_shard_objects(msg.pg, shard)
        await conn.send(MPGLogMsg(msg.tid, msg.pg, shard, info,
                                  list(plog.entries),
                                  epoch=self._epoch(),
                                  from_osd=self.osd_id, is_reply=True))

    def _list_shard_objects(self, pg: PgId, shard: int) -> List[str]:
        cid = self._cid(pg, shard)
        try:
            return sorted(str(o) for o in self.store.list_objects(cid)
                          if str(o) != PGMETA_OID
                          and not str(o).startswith(RB_PREFIX))
        except KeyError:
            return []

    async def _handle_pg_log_push(self, conn: Connection,
                                  msg: MPGLogMsg) -> None:
        """Primary pushed the authoritative log: merge + rewind, persist,
        reply with my resulting missing set."""
        from ceph_tpu.osd.pg_log import PGInfo

        pool = self.osdmap.pools.get(msg.pg.pool) if self.osdmap else None
        state = self.pgs.setdefault(msg.pg, PGState(msg.pg))
        if pool is None:
            return
        state.interval_epoch = max(state.interval_epoch, msg.epoch)
        plog = self._load_log(state, pool)
        auth_info = PGInfo.from_dict(msg.info)
        missing = plog.merge(auth_info, msg.entries)
        # keep pre-existing missing entries not superseded by the merge
        for oid, need in list(plog.missing.items()):
            missing.setdefault(oid, need)
        plog.missing = missing
        cid = self._cid(msg.pg, msg.shard)
        t = Transaction()
        if not self.store.collection_exists(cid):
            t.create_collection(cid)
        plog.stage(t, cid)
        # peering barrier: the adopted log must not reorder around an
        # open group-commit window (commit_now drains, then commits)
        await self.committer.commit_now(t)
        info = plog.info.to_dict()
        info["missing"] = {k: list(v) for k, v in plog.missing.items()}
        await conn.send(MPGLogMsg(msg.tid, msg.pg, msg.shard, info, [],
                                  epoch=self._epoch(),
                                  from_osd=self.osd_id, is_reply=True))

    async def _peer_pg(self, state: PGState, pool) -> None:
        """Primary peering: GetInfo/GetLog -> auth election -> push ->
        missing -> recover -> active."""
        pg = state.pg
        try:
            my_shard = state.my_shard(self.osd_id, pool.type)
            plog = self._load_log(state, pool)
            # 1. collect infos+logs(+object listings) from up shards
            peers: Dict[int, tuple] = {}
            peers[my_shard] = (plog.info, list(plog.entries),
                               dict(plog.missing),
                               self._list_shard_objects(pg, my_shard))
            peer_shards: Dict[int, int] = {}  # shard -> osd
            for idx, osd in enumerate(state.acting):
                shard = idx if pool.type == TYPE_ERASURE else -1
                if osd == CRUSH_ITEM_NONE or osd == self.osd_id or \
                        not self.osdmap.is_up(osd):
                    continue
                if pool.type == TYPE_REPLICATED and shard == -1:
                    shard_key = -(idx + 2)  # unique key per replica
                else:
                    shard_key = shard
                tid = self._next_tid()
                # the query carries the INTERVAL epoch, not the live
                # one: replies to it are the interval barrier, and
                # sub-writes of this interval are stamped with the same
                # value so they pass the fence the barrier establishes
                reply = await self._request(
                    osd, MPGQuery(tid, pg, state.interval_epoch,
                                  self.osd_id), tid)
                if reply is None or reply.pg != pg:
                    continue
                from ceph_tpu.osd.pg_log import PGInfo

                info = PGInfo.from_dict(reply.info)
                peer_missing = {k: ev(v) for k, v in
                                reply.info.get("missing", {}).items()}
                peers[shard_key] = (info, reply.entries, peer_missing,
                                    reply.info.get("objects", []))
                peer_shards[shard_key] = osd
            if pg in self._split_children:
                # split child: its data was minted on the PARENT's
                # members, which this acting mapping knows nothing
                # about.  One exhaustive (up-OSDs x shards) info/log
                # sweep lets the auth election see the split state;
                # per-object recovery already probes strays.  (The
                # reference instead instantiates children directly on
                # the parent's OSDs; this sweep is the asyncio-shaped
                # equivalent, paid only at the first post-split
                # peering.)
                await self._sweep_split_strays(state, pool, peers,
                                               peer_shards)
            # pre-merge heads: needed for the backfill decision below
            pre_lu = {k: v[0].last_update for k, v in peers.items()}
            # 2. elect authoritative log (max last_update, then longest)
            auth_key = max(
                peers,
                key=lambda s: (peers[s][0].last_update,
                               len(peers[s][1]),
                               s == my_shard))
            auth_info, auth_entries = peers[auth_key][0], \
                peers[auth_key][1]
            # 3. adopt locally if I'm not authoritative
            if auth_key != my_shard:
                my_missing = plog.merge(auth_info, auth_entries)
                for oid, need in my_missing.items():
                    plog.missing.setdefault(oid, need)
                cid = self._cid(pg, my_shard)
                t = Transaction()
                if not self.store.collection_exists(cid):
                    t.create_collection(cid)
                plog.stage(t, cid)
                # peering barrier: drain the window, commit inline
                await self.committer.commit_now(t)
            # 4. push auth log to peers; collect their missing sets
            state.peer_missing = {}
            auth_wire_info = plog.info.to_dict()
            for shard_key, osd in peer_shards.items():
                shard = shard_key if shard_key >= -1 else -1
                tid = self._next_tid()
                reply = await self._request(
                    osd, MPGLogMsg(tid, pg, shard, auth_wire_info,
                                   list(plog.entries),
                                   epoch=state.interval_epoch,
                                   from_osd=self.osd_id), tid)
                if reply is None or reply.pg != pg:
                    continue
                state.peer_missing[shard_key] = {
                    k: ev(v)
                    for k, v in reply.info.get("missing", {}).items()}
            # 4b. backfill: a shard whose pre-merge head predates the
            # auth log tail cannot be caught up by log replay — every
            # object in the auth shard's listing is potentially stale
            # (the scan-based backfill of PeeringState)
            tail = plog.info.log_tail
            if tail > ZERO:
                auth_objects = peers[auth_key][3]
                if auth_key != my_shard and pre_lu[my_shard] < tail:
                    for obj in auth_objects:
                        plog.missing.setdefault(obj, ZERO)
                for shard_key in peer_shards:
                    if pre_lu.get(shard_key, ZERO) < tail:
                        pm = state.peer_missing.setdefault(shard_key, {})
                        for obj in auth_objects:
                            pm.setdefault(obj, ZERO)
            # 5. recovery: self first, then peers
            await self._recover_pg(state, pool, peer_shards)
            # 6. activate (possibly with unfound objects: reads of those
            # fail until a map change brings a shard source back)
            state.unfound = bool(plog.missing) or \
                any(bool(m) for m in state.peer_missing.values())
            state.next_version = plog.info.last_update[1] + 1
            plog.info.same_interval_since = state.interval_epoch
            plog.info.last_epoch_started = self._epoch()
            state.state = "active"
            state.active_event.set()
            # a split child that peered once has adopted its state
            # from the parent's members; later peerings are normal
            if pg in self._split_children:
                self._split_children.discard(pg)
                self._save_split_meta()
            if state.unfound:
                self._clog("WRN", f"pg {pg} active with unfound"
                                  " objects (sources down?)")
                # leftover missing entries are not only map-change
                # driven: a recovery PUSH can fail on a transient
                # timeout with no interval change, and nothing else
                # would ever retry it — keep retrying in place with
                # backoff (the DoRecovery requeue discipline)
                self._schedule_unfound_retry(state, pool)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("osd.%d: peering %s failed", self.osd_id, pg)
            state.state = "inactive"
            # retry: peering must not park the PG forever on a transient
            # failure (a peer bouncing mid-query)
            if not self._stopping:
                asyncio.get_running_loop().create_task(
                    self._retry_peering(state))
        finally:
            state.peering_task = None

    async def _sweep_split_strays(self, state: PGState, pool,
                                  peers: Dict[int, tuple],
                                  peer_shards: Dict[int, int]) -> None:
        """Collect split-child state from OUTSIDE the acting mapping:
        every up OSD is asked for every shard collection of this pg.
        Hits join the auth-log election under synthetic keys (never
        push/recovery targets — those stay acting-only; the per-object
        gather finds the stray payloads on its own)."""
        from ceph_tpu.osd.pg_log import PGInfo

        pg = state.pg
        if pool.type == TYPE_ERASURE:
            shard_list = list(
                range(self._codec(pool.id).get_chunk_count()))
        else:
            shard_list = [-1]
        # my own non-acting shard collections (an ex-parent member's
        # parent-shard index need not match its child acting slot)
        my_shard = state.my_shard(self.osd_id, pool.type)
        for shard in shard_list:
            if shard == my_shard:
                continue
            cid = self._cid(pg, shard)
            if not self.store.collection_exists(cid):
                continue
            lplog = PGLog.load(self.store, cid)
            if lplog.info.last_update > ZERO:
                key = -(10_000 + self.osd_id * 64 + shard + 2)
                peers[key] = (lplog.info, list(lplog.entries),
                              dict(lplog.missing),
                              self._list_shard_objects(pg, shard))
        # (osd, shard) pairs already covered: the acting loop asked
        # each acting member for ITS OWN slot only — an acting member
        # may still hold split state under a DIFFERENT shard index
        # (its parent slot), so acting OSDs are swept for the others
        covered = {(osd, sk if sk >= -1 else -1)
                   for sk, osd in peer_shards.items()}
        covered |= {(self.osd_id, shard) for shard in shard_list}

        async def ask(osd: int, shard: int):
            tid = self._next_tid()
            reply = await self._request(
                osd, MPGQuery(tid, pg, state.interval_epoch,
                              self.osd_id, shard=shard), tid)
            return osd, shard, reply

        jobs = [ask(osd, shard)
                for osd in self.osdmap.get_up_osds()
                for shard in shard_list
                if (osd, shard) not in covered]
        results = await asyncio.gather(*jobs) if jobs else []
        for osd, shard, reply in results:
            if reply is None or reply.pg != pg:
                continue
            info = PGInfo.from_dict(reply.info)
            if info.last_update <= ZERO:
                continue  # nothing split onto this OSD
            key = -(10_000 + osd * 64 + shard + 2)
            peers[key] = (info, reply.entries,
                          {k: ev(v) for k, v in
                           reply.info.get("missing", {}).items()},
                          reply.info.get("objects", []))

    def _schedule_unfound_retry(self, state: PGState, pool) -> None:
        """Re-run recovery for an active PG that still carries missing
        entries, with backoff, until it drains or the interval moves
        on (then peering owns it again).  Armed from EVERY path that
        can leave entries behind without an interval change —
        activation, failed recovery pushes, scrub repairs."""
        interval = state.interval_epoch
        if state._unfound_retry is not None:
            return
        state.unfound = True

        def live_peers() -> Dict[int, int]:
            out: Dict[int, int] = {}
            for idx, osd in enumerate(state.acting):
                if osd == CRUSH_ITEM_NONE or osd == self.osd_id or \
                        not self.osdmap.is_up(osd):
                    continue
                out[idx if pool.type == TYPE_ERASURE
                    else -(idx + 2)] = osd
            return out

        async def retry() -> None:
            backoff = 1.0
            try:
                while not self._stopping and state.state == "active" \
                        and state.interval_epoch == interval \
                        and state.unfound:
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 8.0)
                    if state.state != "active" or \
                            state.interval_epoch != interval:
                        return
                    plog = self._load_log(state, pool)
                    await self._recover_pg(state, pool, live_peers())
                    state.unfound = bool(plog.missing) or \
                        any(bool(m)
                            for m in state.peer_missing.values())
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: unfound retry of %s failed",
                              self.osd_id, state.pg)
            finally:
                state._unfound_retry = None

        state._unfound_retry = \
            asyncio.get_running_loop().create_task(retry())

    async def _retry_peering(self, state: PGState) -> None:
        await asyncio.sleep(0.5)
        if self._stopping or state.state != "inactive" or \
                state.peering_task is not None or self.osdmap is None:
            return
        pool = self.osdmap.pools.get(state.pg.pool)
        if pool is None or state.primary != self.osd_id:
            return
        state.state = "peering"
        state.peering_task = asyncio.get_running_loop().create_task(
            self._peer_pg(state, pool))

    # -- recovery ----------------------------------------------------------

    async def _read_candidates(
            self, pg: PgId, shard: int, osd: int, oid: str,
            include_rollback: bool,
            offset: int = 0, length: int = 0,
            record: bool = False
    ) -> Tuple[List[Tuple[int, bytes, Dict[str, bytes]]], bool]:
        """Read one (shard, osd)'s main object — and, when asked, its
        rollback generation — as selection candidates.  offset/length
        trim the shard payload to the requested chunk range (the
        get_want_to_read_shards range discipline).

        Second return: True iff every query got a DEFINITIVE answer
        (the copy exists, rc=0, or definitively does not, ENOENT).  A
        dead peer or transport failure is NOT evidence of absence —
        conflating the two is how acked writes get garbage-collected
        as "divergent creates" (the MissingLoc have-vs-unfound
        distinction, /root/reference/src/osd/MissingLoc.h)."""
        names = [oid]
        if include_rollback:
            names.append(RB_PREFIX + oid)
        out: List[Tuple[int, bytes, Dict[str, bytes]]] = []
        definitive = True
        for name in names:
            t0 = time.monotonic()
            if osd == self.osd_id:
                rc, data, at = self._read_shard(
                    pg, shard, name, offset if length else 0, length)
                # the local read feeds the EWMA too: self ranks by its
                # actual store latency, not a synthetic zero
                self.hedge.observe(osd, time.monotonic() - t0,
                                   ok=rc in (0, ENOENT))
                if rc == 0:
                    out.append((shard, data, at))
                elif rc != ENOENT:
                    definitive = False
                continue
            tid = self._next_tid()
            reply = await self._request(
                osd, MOSDSubRead(tid, pg, shard, name, offset, length,
                                 record=record and name == oid),
                tid)
            # every sub-read round trip feeds the per-peer latency
            # model; a timeout/fault charges the peer its full cost
            # and trips its breaker toward rank-last.  A fast reply
            # carrying an ERROR rc (EIO from a dying store) is a
            # fault too — counting it a success would rank the peer
            # FASTEST while it serves nothing.  (A CANCELLED request
            # never reaches here — cancelled RTTs would poison the
            # model with the canceller's impatience.)
            self.hedge.observe(osd, time.monotonic() - t0,
                               ok=reply is not None
                               and reply.rc in (0, ENOENT))
            if reply is not None and reply.rc == 0:
                self.perf["subread_bytes"] += len(reply.data)
                out.append((shard, reply.data, reply.attrs))
            elif reply is None or reply.rc != ENOENT:
                definitive = False
        return out, definitive

    async def _gather_object_shards(
            self, state: PGState, pool, oid: str,
            exclude_missing: bool = True,
            include_rollback: bool = False,
            offset: int = 0, length: int = 0,
            record: bool = False,
            need: Optional[int] = None,
            verify_hinfo: bool = False,
            selection_out: Optional[list] = None
    ) -> Tuple[List[Tuple[int, bytes, Dict[str, bytes]]], bool]:
        """Collect available (shard, payload, attrs) candidates for an
        object from up acting shards, CONCURRENTLY (local read for mine,
        sub-reads for peers).  include_rollback adds each shard's
        preserved previous generation; offset/length restrict each
        shard's payload to a chunk range.

        need=k opts the gather into HEDGED mode (osd/hedge.py): the k
        fastest-ranked shards plus Δ speculative extras launch first,
        stragglers recruit spares at their peer's p95-EWMA mark, and
        the gather returns as soon as `need` DISTINCT shards agree on
        one version (_select_consistent with the same need/
        verify_hinfo the caller will apply) — stragglers are cancelled
        and awaited, never leaked.  Recovery/absence probes pass
        need=None and keep the exhaustive all-shard semantics.

        Second return: True iff every acting member was probed and
        answered definitively (a down member, failed query, or hedged
        early completion means the gather proves nothing about
        absence)."""
        pg = state.pg
        plog = self._load_log(state, pool)
        jobs: List[Tuple[int, Any]] = []
        complete = True
        for idx, osd in enumerate(state.acting):
            shard = idx if pool.type == TYPE_ERASURE else -1
            if osd == CRUSH_ITEM_NONE:
                continue
            if not self.osdmap.is_up(osd):
                if not self.osdmap.is_destroyed(osd):
                    complete = False
                continue
            if osd == self.osd_id and exclude_missing and \
                    oid in plog.missing:
                continue
            shard_key = idx if pool.type == TYPE_ERASURE else -(idx + 2)
            if exclude_missing and \
                    oid in state.peer_missing.get(shard_key, {}):
                # a copy scrub adjudicated bad (or a peer known to
                # lack the object) must never serve as a repair
                # source — the data stays on disk but is excluded
                # from selection
                continue

            def job(shard=shard, osd=osd):
                return self._read_candidates(
                    pg, shard, osd, oid, include_rollback, offset,
                    length, record=record)

            jobs.append((osd, job))
        sufficient = None
        if need is not None:
            # CRC verdicts memoized across the gather's completion
            # waves: the results list keeps every candidate alive, so
            # id(attrs) keys stay valid for the memo's whole lifetime
            hinfo_memo: Dict[int, bool] = {}

            def sufficient(results):
                cands = [c for sub, _ok in results for c in sub]
                sel = self._select_consistent(
                    cands, need=need, verify_hinfo=verify_hinfo,
                    hinfo_memo=hinfo_memo)
                if sel[0] is None:
                    return False
                # hand the winning (version, chosen, oi) back to the
                # caller: the accepting sufficient() call ran on
                # exactly the candidates being returned, so hedged
                # readers skip re-selecting (and re-verifying hinfo
                # CRCs over) the same payloads
                if selection_out is not None:
                    selection_out[:] = [sel]
                return True
        results, ran_all = await self.hedge.gather(
            jobs, need=need, sufficient=sufficient,
            failed=(lambda res: not res[0])
            if need is not None else None)
        complete = complete and ran_all and \
            all(ok for _sub, ok in results)
        return [c for sub, _ok in results for c in sub], complete

    async def _gather_and_select(
            self, state: PGState, pool, oid: str, *, need: int,
            verify_hinfo: bool = False, offset: int = 0,
            length: int = 0, record: bool = False
    ) -> Tuple[List[Tuple[int, bytes, Dict[str, bytes]]], bool,
               Optional[tuple], Dict[int, bytes], Optional[dict]]:
        """Hedged gather + consistent selection in ONE step:
        (candidates, complete, version, chosen, oi).  The selection
        from the gather's accepting sufficiency check is reused when
        the gather exited early (it ran on exactly the returned
        candidates) and recomputed otherwise (all-shard mode, kill
        switch, insufficient) — the reuse-or-recompute contract lives
        here once, not at every read site."""
        sel: list = []
        candidates, complete = await self._gather_object_shards(
            state, pool, oid, offset=offset, length=length,
            record=record, need=need, verify_hinfo=verify_hinfo,
            selection_out=sel)
        if not candidates:
            return [], complete, None, {}, None
        version, chosen, oi = sel[0] if sel else \
            self._select_consistent(candidates, need=need,
                                    verify_hinfo=verify_hinfo)
        return candidates, complete, version, chosen, oi

    async def _gather_stray_shards(
            self, state: PGState, pool, oid: str,
            have: Set[Tuple[int, int]],
            length: int = 0
    ) -> Tuple[List[Tuple[int, bytes, Dict[str, bytes]]], bool]:
        """Search shards OUTSIDE the acting mapping: prior-interval
        members may hold the only up-to-date copies after several
        remaps (the MissingLoc / might_have_unfound role,
        /root/reference/src/osd/MissingLoc.h).  Queries every up OSD for
        every shard collection of this pg not already in `have`
        ((shard, osd) pairs).

        Second return: True iff the search was EXHAUSTIVE — every OSD
        that could possibly hold a stray copy was probed and answered.
        Any down-but-existing OSD makes it False: it might be the sole
        holder of the newest acked write (might_have_unfound)."""
        pg = state.pg
        if pool.type == TYPE_ERASURE:
            shard_list = list(
                range(self._codec(pool.id).get_chunk_count()))
        else:
            shard_list = [-1]
        # a DESTROYED (`osd lost`) OSD is definitively absent by admin
        # decree — only plain-down OSDs leave the search inconclusive
        complete = all(self.osdmap.is_up(o) or self.osdmap.is_destroyed(o)
                       for o in range(self.osdmap.max_osd)
                       if self.osdmap.exists(o))
        jobs = [self._read_candidates(pg, shard, osd, oid,
                                      include_rollback=True,
                                      length=length)
                for osd in self.osdmap.get_up_osds()
                for shard in shard_list
                if (shard, osd) not in have]
        results = await asyncio.gather(*jobs) if jobs else []
        complete = complete and all(ok for _sub, ok in results)
        return [c for sub, _ok in results for c in sub], complete

    def _shard_rank(self, state: PGState):
        """Shard-index sort key fed by the hedge tracker's per-peer
        EWMAs: survivor-set choices (decode inputs, recovery's
        chosen-k) prefer shards whose source OSDs are currently
        fastest, degraded peers last.  The EWMA is quantized to
        OCTAVES here — the live model decays and takes samples
        between two calls in the same recovery wave, and a raw-float
        key would let that jitter normalize identical survivor sets
        differently and split decode_many's batches; only a genuine
        (2x) speed difference may reorder shards."""
        acting = list(state.acting)

        def key(shard: int) -> tuple:
            osd = acting[shard] if 0 <= shard < len(acting) \
                else CRUSH_ITEM_NONE
            if osd == CRUSH_ITEM_NONE:
                return (2, 1 << 30, shard)
            degraded, ewma, _osd = self.hedge.rank_key(osd)
            return (degraded, int(math.log2(max(ewma, 1e-6))), shard)

        return key

    @staticmethod
    def _oi_version(at: Dict[str, bytes]) -> Optional[tuple]:
        try:
            oi = json.loads(at[OI_ATTR])
            version = oi.get("version")
            return ev(version) if version else ZERO
        except (KeyError, ValueError):
            return None

    def _select_consistent(
            self, candidates: List[Tuple[int, bytes, Dict[str, bytes]]],
            need: int, verify_hinfo: bool = False,
            hinfo_memo: Optional[Dict[int, bool]] = None
    ) -> Tuple[Optional[tuple], Dict[int, bytes], Optional[dict]]:
        """Newest object version reconstructible from >= need distinct
        shards.

        Mixing shard generations corrupts EC decode and lets stale data
        win reads, so every multi-shard consumer picks ONE version: the
        newest one enough shards agree on.  An unacked write that
        reached < need shards is thereby rolled back to the last
        completed write (the role of ECBackend's rollback-aware log).
        Returns (version, {shard: payload}, object_info) or
        (None, {}, None).

        hinfo_memo (id(attrs) -> verdict) lets a caller that re-runs
        selection over a growing candidate list — the hedged gather's
        sufficiency check, once per completion wave — pay each
        payload's CRC verification once instead of once per wave.
        Only valid while the caller keeps the candidate tuples alive
        (id() reuse) and candidates are immutable, both true there.
        """
        groups: Dict[tuple, Dict[int, bytes]] = {}
        ois: Dict[tuple, dict] = {}
        for shard, payload, at in candidates:
            version = self._oi_version(at)
            if version is None:
                continue
            if verify_hinfo:
                if HINFO_ATTR not in at:
                    continue  # EC shard without its ledger: suspicious
                if hinfo_memo is None:
                    ok = _hinfo_chunk_ok(at, shard, payload)
                else:
                    ok = hinfo_memo.get(id(at))
                    if ok is None:
                        ok = hinfo_memo[id(at)] = _hinfo_chunk_ok(
                            at, shard, payload)
                if not ok:
                    continue  # corrupt shard: erasure
            groups.setdefault(version, {}).setdefault(shard, payload)
            ois.setdefault(version, json.loads(at[OI_ATTR]))
        for version in sorted(groups, reverse=True):
            members = groups[version]
            if len(members) >= need:
                return version, members, ois[version]
        return None, {}, None

    # -- snapshots (self-managed snaps, SnapMapper-lite) -------------------
    #
    # SnapSet JSON on every head shard (SS_ATTR): {"seq", "clones":
    # [{"cloneid", "snaps", "size"}]} — the object_snaps/SnapSet role
    # (/root/reference/src/osd/osd_types.h SnapSet,
    # src/osd/PrimaryLogPG.cc make_writeable).  Clone shard objects are
    # "<oid>\x16<cloneid>" in the same collections, recovered/backfilled
    # like any object.

    @staticmethod
    def _decode_ss(at: Dict[str, bytes]) -> Dict[str, Any]:
        try:
            return json.loads(at[SS_ATTR])
        except (KeyError, ValueError):
            return {"seq": 0, "clones": []}

    async def _head_info(self, state: PGState, pool, oid: str
                         ) -> Tuple[Optional[dict], Dict[str, Any]]:
        """(object_info | None, snapset) of the head via a 1-byte
        ranged gather (attrs ride along).  Raises UnfoundObject when
        the head exists per the log but no copy is locatable."""
        need = self._codec(pool.id).get_data_chunk_count() \
            if pool.type == TYPE_ERASURE else 1
        candidates, _complete, version, chosen, oi = \
            await self._gather_and_select(state, pool, oid,
                                          need=need, length=1)
        if not candidates:
            self._block_if_unfound(state, pool, oid)
            return None, {"seq": 0, "clones": []}
        if version is None:
            self._block_if_unfound(state, pool, oid)
            return None, {"seq": 0, "clones": []}
        self._require_fresh(state, pool, oid, version)
        src = next(iter(chosen))
        for shard, _payload, at in candidates:
            if shard == src and self._oi_version(at) == version:
                return oi, self._decode_ss(at)
        return oi, {"seq": 0, "clones": []}

    async def _snap_clone_prep(
            self, state: PGState, pool, oid: str,
            snapc_seq: int, snapc_snaps: List[int],
            head: Optional[Tuple[Optional[dict], Dict[str, Any]]] = None
    ) -> Tuple[List[ShardOp], Optional[bytes]]:
        """make_writeable: if the object predates the newest snap,
        emit clone ops (prepended to the write on every shard) and the
        updated SnapSet attr bytes.  Returns ([], None) when no snap
        bookkeeping applies to this write.  Callers that already hold
        the head's (oi, ss) pass them via `head` to skip the re-read
        (both reads happen under the same object lock)."""
        if snapc_seq <= 0:
            return [], None
        oi, ss = head if head is not None \
            else await self._head_info(state, pool, oid)
        # never mutate a caller-held SnapSet (the clones list would
        # alias through a shallow copy)
        ss = {**ss, "clones": list(ss.get("clones", []))}
        clone_ops: List[ShardOp] = []
        if oi is not None and not oi.get("whiteout") and \
                ss.get("seq", 0) < snapc_seq:
            covered = sorted(s for s in snapc_snaps
                             if s > ss.get("seq", 0))
            if covered:
                cloneid = covered[-1]
                clone_ops.append(
                    ShardOp("clone", name=clone_name(oid, cloneid)))
                ss.setdefault("clones", []).append(
                    {"cloneid": cloneid, "snaps": covered,
                     "size": oi.get("size", 0)})
        ss["seq"] = max(ss.get("seq", 0), snapc_seq)
        return clone_ops, json.dumps(ss).encode()

    async def _resolve_read_snap(self, state: PGState, pool, oid: str,
                                 snap_id: int) -> Optional[str]:
        """Map (oid, snap_id) -> the object holding that snap's data:
        the head (data unchanged since the snap) or a clone.  None =
        did not exist at that snap (PrimaryLogPG find_object_context
        snap resolution)."""
        oi, ss = await self._head_info(state, pool, oid)
        if oi is None and not ss.get("clones"):
            return None
        prev = 0
        for clone in sorted(ss.get("clones", []),
                            key=lambda c: c["cloneid"]):
            # a clone covers the snap range (prev_cloneid, cloneid],
            # but only the snaps RECORDED in it existed with this
            # object alive — a snap in the range but not in the list
            # predates the object's creation (ENOENT at that snap)
            if prev < snap_id <= clone["cloneid"]:
                if snap_id in clone["snaps"]:
                    return clone_name(oid, clone["cloneid"])
                return None
            prev = clone["cloneid"]
        if oi is not None and not oi.get("whiteout") and \
                snap_id > ss.get("seq", 0):
            # no write has landed since that snap: head IS the snap.
            # A snap <= seq with no covering clone predates the
            # object's creation (the head was first written under a
            # newer snap context) — ENOENT.
            return oid
        return None

    def _note_trim_candidates(self, state: PGState, pool) -> None:
        """Spawn a background trim when the pool's removed_snaps grew
        (the snap trim role; scan-based SnapMapper-lite)."""
        removed = set(getattr(pool, "removed_snaps", []))
        pending = removed - state.trimmed_snaps
        if not pending or state.primary != self.osd_id or \
                state.state != "active" or state.trim_task is not None:
            return
        state.trim_task = asyncio.get_running_loop().create_task(
            self._trim_pg_snaps(state, pool, pending))

    async def _trim_pg_snaps(self, state: PGState, pool,
                             pending: Set[int]) -> None:
        try:
            my_shard = state.my_shard(self.osd_id, pool.type)
            # heads only: clones carry a STALE SnapSet copied by the
            # store-level clone op and must never drive trim decisions
            heads = [name for name in
                     self._list_shard_objects(state.pg, my_shard)
                     if not is_internal_name(name)]
            for oid in heads:
                async with state.obj_lock(oid):
                    await self._trim_object(state, pool, oid, pending)
            state.trimmed_snaps |= pending
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("osd.%d: snap trim %s failed", self.osd_id,
                          state.pg)
        finally:
            state.trim_task = None
            # snaps removed WHILE this trim ran would otherwise wait
            # for an unrelated map change: re-check immediately
            if not self._stopping and self.osdmap is not None:
                cur = self.osdmap.pools.get(state.pg.pool)
                if cur is not None:
                    self._note_trim_candidates(state, cur)

    async def _trim_object(self, state: PGState, pool, oid: str,
                           pending: Set[int]) -> None:
        oi, ss = await self._head_info(state, pool, oid)
        clones = ss.get("clones", [])
        if not clones:
            return
        keep = []
        doomed = []
        for clone in clones:
            live = [s for s in clone["snaps"] if s not in pending]
            if live:
                clone["snaps"] = live
                keep.append(clone)
            else:
                doomed.append(clone)
        if not doomed:
            return
        ss["clones"] = keep
        n_shards = self._codec(pool.id).get_chunk_count() \
            if pool.type == TYPE_ERASURE else 1
        shards = range(n_shards) if pool.type == TYPE_ERASURE else [-1]
        for clone in doomed:
            entry = self._next_entry(
                state, pool, clone_name(oid, clone["cloneid"]),
                "delete")
            await self._submit_shard_writes(
                state, pool, clone_name(oid, clone["cloneid"]),
                {s: [ShardOp("remove")] for s in shards}, entry)
        if oi is not None and oi.get("whiteout") and not keep:
            # deleted head kept alive only for its clones: finish it
            entry = self._next_entry(state, pool, oid, "delete")
            await self._submit_shard_writes(
                state, pool, oid,
                {s: [ShardOp("remove")] for s in shards}, entry)
        elif oi is not None:
            entry = self._next_entry(state, pool, oid, "modify",
                                     oi.get("size", 0))
            ss_raw = json.dumps(ss).encode()
            await self._submit_shard_writes(
                state, pool, oid,
                {s: [ShardOp("setattr", name=SS_ATTR, value=ss_raw)]
                 for s in shards}, entry)

    async def _fetch_omap_any(self, state: PGState, pool, oid: str
                              ) -> Optional[Dict[str, bytes]]:
        """Best-effort omap fetch from any up holder (recovery needs
        the omap too, or a recovered replica silently loses it)."""
        plog = self._load_log(state, pool)
        if oid not in plog.missing:
            try:
                return self.store.omap_get(self._cid(state.pg, -1),
                                           ObjectId(oid))
            except (KeyError, IOError):
                pass
        for osd in state.acting:
            if osd == CRUSH_ITEM_NONE or osd == self.osd_id or \
                    not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            reply = await self._request(
                osd, MOSDSubRead(tid, state.pg, -1, oid, 0, 1,
                                 want_omap=True), tid)
            if reply is not None and reply.rc == 0:
                return reply.omap
        return None

    # -- scrub (daemon-side scheduled scrub; PG.cc scrub + be_deep_scrub
    # roles) ---------------------------------------------------------------

    async def _scrub_loop(self, interval: float) -> None:
        """Background scrub: walk my primary PGs comparing shard
        payloads against their recorded digests, repairing through the
        recovery path."""
        while not self._stopping:
            await asyncio.sleep(interval)
            if self.osdmap is None:
                continue
            for pg, state in list(self.pgs.items()):
                if state.primary != self.osd_id or \
                        state.state != "active":
                    continue
                pool = self.osdmap.pools.get(pg.pool)
                if pool is None:
                    continue
                try:
                    await self.scrub_pg(state, pool)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("osd.%d: scrub %s failed",
                                  self.osd_id, pg)

    async def scrub_pg(self, state: PGState, pool) -> Dict[str, int]:
        """Scrub one PG; returns this run's {objects, errors,
        repaired}.  Exposed for tests and an admin trigger."""
        run = {"objects": 0, "errors": 0, "repaired": 0}
        my_shard = state.my_shard(self.osd_id, pool.type)
        scrub_interval_epoch = state.interval_epoch
        # union the listings across the ACTING set: a straggler copy
        # (e.g. one that missed a remove fan-out) may exist only on a
        # peer shard, invisible to the primary's own listing — the
        # reference's scrub maps cover every shard for the same reason
        name_set = set(self._list_shard_objects(state.pg, my_shard))

        async def peer_listing(osd: int):
            tid = self._next_tid()
            return await self._request(
                osd, MPGQuery(tid, state.pg, state.interval_epoch,
                              self.osd_id), tid)

        peers = [osd for osd in state.acting
                 if osd != CRUSH_ITEM_NONE and osd != self.osd_id
                 and self.osdmap.is_up(osd)]
        for reply in await asyncio.gather(*(peer_listing(o)
                                            for o in peers)):
            if reply is not None:
                name_set.update(reply.info.get("objects", []))
        names = sorted(n for n in name_set if not is_internal_name(n))
        for oid in names:
            # QoS admit BEFORE taking the object lock: a scrub item
            # parked in the queue while holding the lock would stall
            # that object's client ops behind the lowest-priority class
            async def scrub_one(oid=oid):
                async with state.obj_lock(oid):
                    if state.state != "active" or \
                            state.interval_epoch != scrub_interval_epoch:
                        return False
                    await self._scrub_object(state, pool, oid, run)
                    return True

            if not await self.scheduler.run(sched_mod.SCRUB, 1.0,
                                            scrub_one):
                # an interval change mid-scrub hands the PG to
                # peering; repairs computed against the old acting set
                # would corrupt state — abort, next pass rescans
                break
        self.scrub_stats["objects"] += run["objects"]
        self.scrub_stats["errors"] += run["errors"]
        self.scrub_stats["repaired"] += run["repaired"]
        if run["errors"]:
            self._clog("ERR", f"scrub {state.pg}: {run['errors']}"
                              f" inconsistencies, {run['repaired']}"
                              " repaired")
        return run

    @staticmethod
    def _newest_log_entry(plog, oid: str) -> Optional[Dict[str, Any]]:
        for le in reversed(plog.entries):
            if le.get("oid") == oid:
                return le
        return None

    async def _scrub_object(self, state: PGState, pool, oid: str,
                            run: Dict[str, int]) -> None:
        run["objects"] += 1
        plog = self._load_log(state, pool)
        if oid in plog.missing or \
                any(oid in m for m in state.peer_missing.values()):
            return  # recovery owns this object right now
        newest = self._newest_log_entry(plog, oid)
        if newest is not None and newest.get("op") == "delete":
            # the log says this object was DELETED: any surviving copy
            # is a straggler that missed the remove fan-out — purge it
            # rather than adjudicating it as data (reinstalling would
            # resurrect a deletion the client was acked for)
            await self._purge_deleted_stragglers(state, pool, oid,
                                                 ev(newest["version"]))
            return
        # gather with explicit per-copy identity: (acting position,
        # osd, payload, attrs) — candidate order from the generic
        # gather cannot identify WHICH replica a copy came from
        copies: List[Tuple[int, int, bytes, Dict[str, bytes]]] = []

        async def fetch(idx: int, osd: int, shard: int) -> None:
            if osd == self.osd_id:
                rc, data, at = self._read_shard(state.pg, shard, oid)
            else:
                tid = self._next_tid()
                reply = await self._request(
                    osd, MOSDSubRead(tid, state.pg, shard, oid), tid)
                if reply is None or reply.rc != 0:
                    return
                rc, data, at = 0, reply.data, reply.attrs
            if rc == 0:
                copies.append((idx, osd, data, at))

        jobs = []
        expected: List[Tuple[int, int]] = []
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE or not self.osdmap.is_up(osd):
                continue
            shard = idx if pool.type == TYPE_ERASURE else -1
            expected.append((idx, osd))
            jobs.append(fetch(idx, osd, shard))
        await asyncio.gather(*jobs)
        if not copies:
            return
        # an up acting member that should hold the object but returned
        # nothing IS an inconsistency (a silently lost copy) — count it
        # and repair it like a corrupt one
        absent = [(idx, osd) for idx, osd in expected
                  if not any(c[0] == idx for c in copies)]
        k = self._codec(pool.id).get_data_chunk_count() \
            if pool.type == TYPE_ERASURE else 1
        versions: Dict[tuple, int] = {}
        for _idx, _osd, _data, at in copies:
            v = self._oi_version(at)
            if v is not None:
                versions[v] = versions.get(v, 0) + 1
        auth = [v for v, n in versions.items() if n >= k]
        if not auth:
            # no version reaches k among the acting HEADS — a
            # soft-failed write fan-out left mixed generations.
            # Re-select across heads + rollback generations + strays
            # and reinstall every acting shard (the roll-forward/
            # roll-back decision ECBackend encodes in log entries,
            # recomputed from the data itself).
            run["errors"] += 1
            if await self._repair_mixed_generations(state, pool, oid):
                run["repaired"] += 1
            return
        version = max(auth)
        bad: List[Tuple[int, int]] = []  # (acting idx, osd)
        # a copy at any OTHER version than the adjudicated one is
        # stale (older: missed a write fan-out; newer: an unacked
        # partial that lost — ECBackend would roll it back).  Without
        # this, a soft-timed-out shard stays divergent forever while
        # the k-quorum masks it, and redundancy silently degrades.
        for idx, osd, _payload, at in copies:
            if self._oi_version(at) != version:
                bad.append((idx, osd))
        if pool.type == TYPE_ERASURE:
            # hinfo chunk crcs identify the corrupt shard exactly
            # (be_deep_scrub re-hash, ECBackend.cc:2494); RMW-era
            # objects without chunk hashes fall back to the version
            # agreement already checked above
            for idx, osd, payload, at in copies:
                if self._oi_version(at) != version:
                    continue
                if not _hinfo_chunk_ok(at, idx, payload):
                    bad.append((idx, osd))
        else:
            # replicated: a STRICT majority digest wins; dissenters are
            # corrupt.  A tie (1-vs-1 on a 2-copy object) is
            # undecidable — repairing on a tie can destroy the good
            # copy, so it is reported and left alone (inconsistent).
            digests: Dict[int, List[Tuple[int, int]]] = {}
            voters = 0
            for idx, osd, payload, at in copies:
                if self._oi_version(at) != version:
                    continue
                voters += 1
                d = cks.crc32c(0xFFFFFFFF, payload)
                digests.setdefault(d, []).append((idx, osd))
            if len(digests) > 1:
                majority = max(digests.values(), key=len)
                if len(majority) * 2 > voters:
                    # EXTEND: version-stale copies collected above must
                    # not be discarded by the digest adjudication
                    bad.extend(who for members in digests.values()
                               if members is not majority
                               for who in members)
                else:
                    run["errors"] += 1
                    log.warning(
                        "osd.%d: scrub %s/%s: digest tie (%d groups),"
                        " cannot adjudicate — left inconsistent",
                        self.osd_id, state.pg, oid, len(digests))
                    return
        bad.extend(absent)
        if not bad:
            return
        run["errors"] += len(bad)
        log.warning("osd.%d: scrub %s/%s: %d bad cop%s at %s",
                    self.osd_id, state.pg, oid, len(bad),
                    "y" if len(bad) == 1 else "ies", bad)
        repaired = await self._scrub_repair(state, pool, oid, bad,
                                            version)
        run["repaired"] += repaired

    async def _purge_deleted_stragglers(self, state: PGState, pool,
                                        oid: str,
                                        del_version: tuple) -> None:
        """Remove copies of an object the log says was deleted at
        del_version from every acting shard that still holds one."""
        pg = state.pg
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            shard = idx if pool.type == TYPE_ERASURE else -1
            if osd == self.osd_id:
                rc, _d, at = self._read_shard(pg, shard, oid, 0, 1)
                if rc == 0:
                    v = self._oi_version(at)
                    if v is None or v < del_version:
                        t = Transaction()
                        cid = self._cid(pg, shard)
                        t.remove(cid, ObjectId(oid))
                        t.remove(cid, ObjectId(RB_PREFIX + oid))
                        # scrub barrier: bypass the window (drain +
                        # inline) so the purge cannot reorder around
                        # in-window client txns
                        await self.committer.commit_now(t)
                        log.info("osd.%d: scrub purged deleted"
                                 " straggler %s/%s (shard %d)",
                                 self.osd_id, pg, oid, shard)
            elif self.osdmap.is_up(osd):
                cands, _ok = await self._read_candidates(
                    pg, shard, osd, oid, include_rollback=False,
                    offset=0, length=1)
                for _s, _p, at in cands:
                    v = self._oi_version(at)
                    if v is None or v < del_version:
                        tid = self._next_tid()
                        await self._request(
                            osd, MOSDSubWrite(
                                tid, pg, shard, oid,
                                [ShardOp("remove")],
                                state.interval_epoch, None,
                                self.osd_id, guard=del_version), tid)
                        log.info("osd.%d: scrub purged deleted"
                                 " straggler %s/%s on osd.%d",
                                 self.osd_id, pg, oid, osd)

    async def _repair_mixed_generations(self, state: PGState, pool,
                                        oid: str) -> bool:
        """Reinstall one consistent generation of an object whose
        acting heads disagree below reconstructibility: select the
        newest version reaching k across heads + rollback generations
        + strays, rebuild, and install on EVERY acting shard."""
        candidates, _c1 = await self._gather_object_shards(
            state, pool, oid, exclude_missing=False,
            include_rollback=True)
        have = {(idx if pool.type == TYPE_ERASURE else -1, osd)
                for idx, osd in enumerate(state.acting)
                if osd != CRUSH_ITEM_NONE}
        strays, _c2 = await self._gather_stray_shards(
            state, pool, oid, have)
        candidates += strays

        def attrs_of(version, chosen) -> Dict[str, bytes]:
            src = next(iter(chosen))
            for shard, _payload, at in candidates:
                if shard == src and self._oi_version(at) == version:
                    return at
            return {}

        targets = []
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE or osd == self.osd_id or \
                    not self.osdmap.is_up(osd):
                continue
            targets.append((idx if pool.type == TYPE_ERASURE
                            else -(idx + 2), osd))
        guard = self._plan_guard(candidates)
        if pool.type == TYPE_REPLICATED:
            version, chosen, _oi = self._select_consistent(
                candidates, need=1)
            if version is None:
                return False
            plan = {"kind": "replicated", "oid": oid,
                    "targets": targets, "i_need": True,
                    "guard": guard,
                    "payload": {-1: chosen[next(iter(chosen))]},
                    "attrs": attrs_of(version, chosen),
                    "omap": await self._fetch_omap_any(
                        state, pool, oid)}
        else:
            codec = self._codec(pool.id)
            k = codec.get_data_chunk_count()
            version, chosen, _oi = self._select_consistent(
                candidates, need=k, verify_hinfo=True)
            if version is None:
                return False  # genuinely below k: recovery/rollback
                # adjudication owns this on the next peering
            chosen_k = ec_util.choose_decode_set(
                codec, chosen, k, prefer=self._shard_rank(state),
                first_k=True)
            plan = {"kind": "ec", "oid": oid, "targets": targets,
                    "i_need": True, "guard": guard,
                    "chosen": chosen_k,
                    "attrs": attrs_of(version, chosen), "omap": None}
            if not await self._batch_reconstruct(pool, [plan]):
                return False
        await self._recover_commit(state, pool, plan)
        log.info("osd.%d: %s/%s: reinstalled generation %s across"
                 " the acting set", self.osd_id, state.pg, oid,
                 version)
        return True

    async def _scrub_repair(self, state: PGState, pool, oid: str,
                            bad: List[Tuple[int, int]],
                            version: tuple) -> int:
        """Repair through the recovery path: drop the corrupt copies,
        mark them missing AT THE OBJECT'S authoritative version (not
        the PG head's last_update — recovery's need_v guard compares
        against this, and an inflated version makes the located,
        correct copy look too old to install), reconstruct + push."""
        peer_shards = self._acting_peer_shards(state, pool)
        plog = self._load_log(state, pool)
        my_cid = self._cid(state.pg,
                           state.my_shard(self.osd_id, pool.type))
        for idx, osd in bad:
            shard_key = idx if pool.type == TYPE_ERASURE else -(idx + 2)
            # mark missing WITHOUT removing the data: recovery's
            # install overwrites the stale copy atomically, so a
            # failed push leaves the old (degraded but real) copy
            # instead of destroying it — repeated drop-then-fail
            # cycles under load would otherwise bleed away every copy
            # of the authoritative generation one scrub at a time
            if osd == self.osd_id:
                t = Transaction()
                plog.missing[oid] = version
                # DURABLE missing marker: a crash before recovery must
                # resume the repair, not strand reduced redundancy
                # (scrub barrier: drained bypass, never windowed)
                plog.stage(t, my_cid)
                await self.committer.commit_now(t)
            else:
                state.peer_missing.setdefault(shard_key, {})[oid] = \
                    version
        await self._recover_object(state, pool, oid, peer_shards)
        # count repaired only if recovery actually restored everything
        still_bad = (oid in plog.missing) or any(
            oid in m for m in state.peer_missing.values())
        if still_bad:
            # arm the in-place retry: nothing else re-runs recovery
            # for entries created outside peering
            self._schedule_unfound_retry(state, pool)
        return 0 if still_bad else len(bad)

    async def _recover_pg(self, state: PGState, pool,
                          peer_shards: Dict[int, int]) -> None:
        """Recover missing objects: mine by reconstruct, peers by push.

        Three phases, shaped for the device (the RecoveryOp batching of
        ECBackend.h:249, re-designed TPU-first):
        1. PLAN — gather candidate shards for EVERY missing object
           concurrently (each gather already fans its sub-reads out).
        2. RECONSTRUCT — group EC objects by survivor-shard set and
           decode + re-encode each group's concatenated stripe streams
           in ONE device dispatch per group (dispatch-per-object would
           pay host<->device latency O(objects) times).
        3. COMMIT — install/push all objects concurrently.
        """
        pg = state.pg
        # the per-OSD backfill cap: PGs queue here, not in the device
        # layer.  Taken BEFORE any object lock (same slot/lock
        # discipline as the pacing token below — a capped PG holds
        # nothing a client op could be waiting on).
        if self._backfill_sem.locked():
            self.perf["backfill_waits"] = \
                self.perf.get("backfill_waits", 0) + 1
        async with self._backfill_sem:
            self.perf["backfills_active"] = \
                self.perf.get("backfills_active", 0) + 1
            try:
                await self._recover_pg_throttled(state, pool,
                                                peer_shards)
            finally:
                self.perf["backfills_active"] -= 1

    async def _recover_pg_throttled(self, state: PGState, pool,
                                    peer_shards: Dict[int, int]
                                    ) -> None:
        pg = state.pg
        plog = self._load_log(state, pool)
        my_shard = state.my_shard(self.osd_id, pool.type)
        # union of all objects anyone is missing
        todo: Set[str] = set(plog.missing)
        for missing in state.peer_missing.values():
            todo.update(missing)
        order = sorted(todo)
        # fixed-size waves bound memory (shard streams + reconstructed
        # payloads resident at once) and in-flight probe RPCs while
        # keeping the per-wave dispatch batching win
        WAVE = 64
        for lo in range(0, len(order), WAVE):
            wave = order[lo:lo + WAVE]
            # each object's lock is held from plan through commit:
            # client writes to an object being recovered wait (and vice
            # versa), so a push selected at version v can never be
            # overtaken by a concurrent write at v+1 on the primary
            # (the wait_for_degraded_object serialization; the replica-
            # side guard token covers the timed-out-push-in-flight case)
            #
            # LOCK/SLOT DISCIPLINE: client ops wait for obj locks while
            # INSIDE bounded scheduler slots, so a lock holder must
            # never wait on a slot grant — blocked clients would pin
            # every slot and wedge the grant loop.  QoS pacing for
            # recovery therefore uses a pacing token (a slot acquired
            # and released BEFORE touching any lock); plan and commit
            # themselves run outside the scheduler.
            held: Dict[str, Any] = {}

            async def _noop():
                return None

            async def plan_locked(oid: str):
                # push-only objects (a peer is behind, this primary is
                # whole) are BACKFILL work: they ride the best-effort
                # class so a drain/add wave cannot eat the reservation
                # budget client ops share with genuine self-recovery
                cls = sched_mod.RECOVERY if oid in plog.missing \
                    else sched_mod.BEST_EFFORT
                await self.scheduler.run(cls, 1.0, _noop)
                ctx = state.obj_lock(oid)
                await ctx.__aenter__()
                held[oid] = ctx
                return await self._recover_plan(
                    state, pool, oid, peer_shards)

            try:
                results = await asyncio.gather(
                    *(plan_locked(oid) for oid in wave),
                    return_exceptions=True)
                plans = []
                for oid, plan in zip(wave, results):
                    if isinstance(plan, Exception):
                        # an unrecoverable object stays missing; the
                        # next interval retries
                        log.error(
                            "osd.%d: recovery plan of %s/%s failed",
                            self.osd_id, pg, oid, exc_info=plan)
                        continue
                    if isinstance(plan, BaseException):  # Cancelled
                        raise plan
                    if plan is not None:
                        plans.append(plan)
                reconstructed = await self._batch_reconstruct(
                    pool, [p for p in plans
                           if p["kind"] in ("ec", "ec_repair")])
                plans = [p for p in plans
                         if p["kind"] not in ("ec", "ec_repair")
                         or p in reconstructed]
                # commits run OUTSIDE the QoS scheduler: object locks
                # are held here, and client ops blocked on those locks
                # sit inside scheduler slots — commits queued behind
                # them would deadlock the slot pool.  The wave is
                # already QoS-paced by its plan phase.
                commits = await asyncio.gather(
                    *(self._recover_commit(state, pool, plan)
                      for plan in plans),
                    return_exceptions=True)
                for plan, res in zip(plans, commits):
                    if isinstance(res, Exception):
                        log.error(
                            "osd.%d: recovery commit of %s/%s failed",
                            self.osd_id, pg, plan["oid"], exc_info=res)
                    elif isinstance(res, BaseException):
                        raise res
            finally:
                for ctx in held.values():
                    await ctx.__aexit__(None, None, None)
        # persist whatever missing state remains
        cid = self._cid(pg, my_shard)
        t = Transaction()
        if not self.store.collection_exists(cid):
            t.create_collection(cid)
        plog.stage(t, cid)
        # recovery barrier: drained bypass, never windowed
        await self.committer.commit_now(t)

    async def _recover_object(self, state: PGState, pool, oid: str,
                              peer_shards: Dict[int, int]) -> None:
        """Single-object recovery (scrub repair's and
        wait_for_degraded's entry point): plan, reconstruct, commit —
        the unbatched form of _recover_pg.  CONTRACT: the caller holds
        state.obj_lock(oid) (every current caller does), which is what
        serializes this install against concurrent client writes."""
        plan = await self._recover_plan(state, pool, oid, peer_shards)
        if plan is None:
            return
        if plan["kind"] in ("ec", "ec_repair") and \
                not await self._batch_reconstruct(pool, [plan]):
            return
        await self._recover_commit(state, pool, plan)

    async def _recover_plan(self, state: PGState, pool, oid: str,
                            peer_shards: Dict[int, int],
                            allow_repair: bool = True
                            ) -> Optional[Dict[str, Any]]:
        """Locate and select an object's authoritative copy; returns a
        commit plan or None (unfound — stays missing).

        allow_repair=False forces the classic full-chunk plan even for
        regenerating codecs — the recursion target when the repair
        fast path hits a complication (too few helpers, fragment
        fetch/verify failure)."""
        pg = state.pg
        plog = self._load_log(state, pool)
        state.extent_cache.pop(oid, None)  # recovery rewrites shards
        targets = [(shard_key, osd)
                   for shard_key, osd in peer_shards.items()
                   if oid in state.peer_missing.get(shard_key, {})]
        i_need = oid in plog.missing
        # REPAIR-AWARE probe sizing: when every missing target is the
        # SAME single chunk of a regenerating codec, the plan needs
        # only versions and attrs from the survivors — 1-byte thin
        # reads — because the payload will be rebuilt from beta-size
        # repair fragments shipped by d helpers, never from full
        # chunks.  Any complication downgrades to the classic plan.
        repair_lost: Optional[int] = None
        if allow_repair and pool.type == TYPE_ERASURE and \
                self._repair_enabled():
            codec0 = self._codec(pool.id)
            lost_set = {sk for sk, _o in targets}
            if i_need:
                lost_set.add(state.my_shard(self.osd_id, pool.type))
            if len(lost_set) == 1 and \
                    codec0.supports_fractional_repair():
                cand = next(iter(lost_set))
                if 0 <= cand < codec0.get_chunk_count():
                    repair_lost = cand
        probe_len = 1 if repair_lost is not None else 0
        t_read = time.monotonic()
        # include_rollback: an acked write that later partial writes
        # pushed off some heads may survive only in acting members'
        # rollback generations — recovery (and especially the
        # no-version purge decision below) must see them
        candidates, acting_complete = await self._gather_object_shards(
            state, pool, oid, include_rollback=True, length=probe_len)
        # always search strays during recovery: after several remaps the
        # newest acked version may exist only on prior-interval members
        have = set()
        for idx, osd in enumerate(state.acting):
            if osd != CRUSH_ITEM_NONE:
                have.add((idx if pool.type == TYPE_ERASURE else -1, osd))
        strays, stray_complete = await self._gather_stray_shards(
            state, pool, oid, have, length=probe_len)
        candidates += strays
        self.tracer.record_stages(
            {"recover_read": int((time.monotonic() - t_read) * 1e6)})
        probes_complete = acting_complete and stray_complete
        # the newest version the PG log says was acked — recovery may
        # not install anything OLDER unless every possible source was
        # probed (otherwise a stale stray copy silently rolls back an
        # acked write while its real holder is down)
        need_v = plog.missing.get(oid) or ZERO
        for shard_key, _osd in targets:
            nv = state.peer_missing.get(shard_key, {}).get(oid) or ZERO
            if nv > need_v:
                need_v = nv
        # causality token for the pushes: the newest version this plan
        # OBSERVED anywhere.  A replica whose state moved past this
        # after the plan was made (a newer client write landed) refuses
        # the push — that push is by definition stale.
        guard = self._plan_guard(candidates, need_v)

        # DELETE-AWARE adjudication: if the authoritative log's newest
        # word on this object is a delete (and nothing recreated it
        # after), the recovered state is ABSENT.  Without this check a
        # stale replica's older generation reaches k/1 candidates and
        # recovery would faithfully REINSTALL it — resurrecting an
        # acked remove (found by the thrash model checker).  The
        # reference encodes deletes in the missing set as
        # "need > have, item.is_delete()" (PGLog) for the same reason.
        newest = self._newest_log_entry(plog, oid)
        if newest is not None and newest.get("op") == "delete" and \
                ev(newest["version"]) >= need_v:
            dv = ev(newest["version"])
            if dv > guard:
                guard = dv
            holders = await self._locate_holders(pg, pool, oid)
            log.info("osd.%d: %s/%s: newest log entry is a delete at"
                     " %s — propagating removal (%d stale holders)",
                     self.osd_id, pg, oid, dv, len(holders))
            return {"kind": "remove", "oid": oid, "targets": targets,
                    "i_need": i_need, "purge": True, "guard": guard,
                    "purge_locations": holders}

        if not candidates:
            if not probes_complete:
                # zero copies found but a possible source is down or
                # unreachable: the object is UNFOUND, not deleted.
                # Removing here would garbage-collect an acked write
                # whose only holders are temporarily dead.  Keep it
                # missing; the PG stays unfound and re-peers on every
                # map change until a source comes back (the reference
                # blocks recovery the same way until might_have_unfound
                # is drained or an OSD is declared lost).
                log.warning(
                    "osd.%d: %s/%s unfound (0 copies located, probes"
                    " incomplete — possible source down)",
                    self.osd_id, pg, oid)
                return None
            # object does not exist at any authoritative source: the
            # divergent entry was a create nobody kept — remove it
            return {"kind": "remove", "oid": oid, "targets": targets,
                    "i_need": i_need, "guard": guard}

        def _attrs_of(version, chosen) -> Dict[str, bytes]:
            src = next(iter(chosen))
            for shard, _payload, at in candidates:
                if shard == src and self._oi_version(at) == version:
                    return at
            return {}

        if pool.type == TYPE_REPLICATED:
            version, chosen, _oi = self._select_consistent(
                candidates, need=1)
            if version is None:
                return None  # no readable copy with object_info: retry
            if not probes_complete and need_v > version:
                log.warning(
                    "osd.%d: %s/%s unfound at acked version %s (best"
                    " located %s, probes incomplete — possible source"
                    " down)", self.osd_id, pg, oid, need_v, version)
                return None
            return {"kind": "replicated", "oid": oid,
                    "targets": targets, "i_need": i_need,
                    "guard": guard,
                    "payload": {-1: chosen[next(iter(chosen))]},
                    "attrs": _attrs_of(version, chosen),
                    "omap": await self._fetch_omap_any(
                        state, pool, oid)}

        codec = self._codec(pool.id)
        k = codec.get_data_chunk_count()
        # thin probes carry 1-byte payloads, so the per-shard CRC
        # ledger cannot be checked here; the repair path instead
        # verifies the REBUILT stream against the ledger and falls
        # back to this plan (full reads, verify_hinfo) on mismatch
        version, chosen, _oi = self._select_consistent(
            candidates, need=k, verify_hinfo=repair_lost is None)
        if version is None:
            if not probes_complete:
                # not enough same-version shards REACHABLE yet: the
                # object stays missing (unfound), a later interval
                # retries when sources return
                log.warning("osd.%d: %s/%s unfound (candidate versions"
                            " %s, probes incomplete)", self.osd_id, pg,
                            oid, sorted({self._oi_version(at)
                                         for _s, _p, at in candidates
                                         if self._oi_version(at)}))
                return None
            # EVERY possible source answered and no version — head or
            # rollback generation — reaches k shards: the logged entry
            # was an in-progress write that never committed on enough
            # shards (its older generations were already consumed or
            # the object was removed before it).  Roll back to the last
            # complete state, which the candidate set proved is
            # "object absent" — the role of ECBackend's rollback of
            # uncommitted log entries (ECBackend.cc try_state_to_reads
            # rollback path, PGLog rollback metadata).  An acked write
            # can never land here: ack requires every shard durable, so
            # some version would reconstruct.
            log.warning("osd.%d: %s/%s: no reconstructible version"
                        " after exhaustive probe — rolling back the"
                        " uncommitted entry (remove)",
                        self.osd_id, pg, oid)
            # locate the partial fragments so the purge removes
            # exactly the holders (quiet + O(holders), not a
            # cluster-wide broadcast)
            holders = await self._locate_holders(pg, pool, oid)
            return {"kind": "remove", "oid": oid, "targets": targets,
                    "i_need": i_need, "purge": True, "guard": guard,
                    "purge_locations": holders}
        if not probes_complete and need_v > version:
            log.warning(
                "osd.%d: %s/%s unfound at acked version %s (best"
                " located %s, probes incomplete — possible source"
                " down)", self.osd_id, pg, oid, need_v, version)
            return None
        if repair_lost is not None:
            # rank the helper pool by the hedge tracker's EWMAs (the
            # same octave-quantized key the decode survivor choice
            # uses) and keep every eligible shard: the fragment fetch
            # hedges over the tail as straggler replacements
            rank = self._shard_rank(state)
            acting = list(state.acting)
            helper_pool = [
                s for s in sorted(chosen, key=rank)
                if s != repair_lost and 0 <= s < len(acting)
                and acting[s] != CRUSH_ITEM_NONE
                and self.osdmap.is_up(acting[s])]
            if len(helper_pool) >= codec.repair_degree():
                return {"kind": "ec_repair", "oid": oid,
                        "targets": targets, "i_need": i_need,
                        "lost": repair_lost,
                        "helpers": [(s, acting[s])
                                    for s in helper_pool],
                        "guard": guard,
                        "attrs": _attrs_of(version, chosen),
                        "version": version, "omap": None, "pg": pg,
                        "state": state,
                        "peer_shards": dict(peer_shards)}
            # fewer than d up acting helpers hold this version: the
            # repair math needs exactly d, so take the classic k-read
            # plan (which may also use strays/rollback generations)
            return await self._recover_plan(
                state, pool, oid, peer_shards, allow_repair=False)
        # normalize to k shards (what decode consumes) pulled from the
        # FASTEST survivor set — the hedge tracker's EWMA rank is
        # stable across a wave, so equal survivor sets batch together
        # exactly as the old first-k normalization did
        chosen_k = ec_util.choose_decode_set(
            codec, chosen, k, prefer=self._shard_rank(state),
            first_k=True)
        return {"kind": "ec", "oid": oid, "targets": targets,
                "i_need": i_need, "chosen": chosen_k, "guard": guard,
                "attrs": _attrs_of(version, chosen), "omap": None}

    async def _batch_reconstruct(self, pool,
                                 ec_plans: List[Dict[str, Any]]
                                 ) -> List[Dict[str, Any]]:
        """Fill each EC plan's `payload` (all n shard streams): decode
        groups that share a survivor set in one dispatch each, then
        re-encode every successful object's data in one dispatch total
        — shard streams are chunk-aligned, so cross-object batching is
        plain concatenation along the stripe axis.  Both legs await
        the encode service, so concurrent recovery waves (and client
        writes) share device dispatches.  A group whose batch fails
        falls back to per-object decode so one malformed object cannot
        livelock the rest of the PG; returns the plans that got
        payloads.

        `ec_repair` plans take the regenerating-code leg first
        (_batch_repair: beta-size fragments from d helpers, one
        plan-cached dispatch per helper set); a repair that cannot
        complete is RE-PLANNED classic (allow_repair=False, full reads
        + hinfo verify) in place and rejoins the decode leg — the
        caller's plan identity is preserved by mutating the dict."""
        if not ec_plans:
            return []
        repair_plans = [p for p in ec_plans if p["kind"] == "ec_repair"]
        ec_plans = [p for p in ec_plans if p["kind"] != "ec_repair"]
        done_repair: List[Dict[str, Any]] = []
        if repair_plans:
            repaired, fallbacks = await self._batch_repair(
                pool, repair_plans)
            done_repair.extend(repaired)
            for p in fallbacks:
                self.perf["repair_fallbacks"] += 1
                try:
                    p2 = await self._recover_plan(
                        p["state"], pool, p["oid"], p["peer_shards"],
                        allow_repair=False)
                except Exception:
                    log.exception(
                        "osd.%d: classic re-plan of %s after repair"
                        " fallback failed", self.osd_id, p["oid"])
                    continue
                if p2 is None:
                    continue
                p.clear()
                p.update(p2)
                if p["kind"] == "ec":
                    ec_plans.append(p)
                else:
                    # adjudicated remove: needs no reconstruct, commit
                    # handles it — but it must count as "done" so the
                    # wave's commit phase keeps the plan
                    done_repair.append(p)
        if not ec_plans:
            return done_repair
        codec = self._codec(pool.id)
        sinfo = self._sinfo(pool.id)
        n = codec.get_chunk_count()
        chunk = sinfo.get_chunk_size()
        width = sinfo.get_stripe_width()
        maps = [p["chosen"] for p in ec_plans]
        for p in ec_plans:
            self.perf["recovery_bytes_read"] += sum(
                len(b) for b in p["chosen"].values())
        # one fold per distinct survivor set (the service/ec_util
        # decode_many contract), counted as such
        self.perf["decode_dispatches"] += len(
            {tuple(sorted(m)) for m in maps})
        t_dec = time.monotonic()
        results = await self.encode_service.decode_many(sinfo, codec,
                                                        maps)
        datas: Dict[str, bytes] = {}
        for p, res in zip(ec_plans, results):
            if isinstance(res, BaseException):
                # device-fault resilience (scrub repair rides this
                # path): a decode that died on the device tier must
                # retry on the bit-exact host path before the object
                # counts unrepaired — by now the breaker guard has
                # degraded the dispatch, so this inline re-run only
                # raises for genuine data errors (below k survivors,
                # malformed streams)
                try:
                    res = await asyncio.to_thread(
                        ec_util.decode, sinfo, codec, p["chosen"])
                    self.perf["decode_host_retries"] += 1
                except Exception as host_err:
                    # the host retry's OWN error is the actionable
                    # one (below-k survivors, malformed streams); the
                    # superseded batch error rides the message
                    log.error("osd.%d: reconstruct of %s failed on"
                              " host retry (batched decode had"
                              " failed with %r)",
                              self.osd_id, p["oid"], res,
                              exc_info=host_err)
                    continue
            datas[p["oid"]] = res
        done = [p for p in ec_plans if p["oid"] in datas]
        if not done:
            return []
        try:
            all_data = b"".join(datas[p["oid"]] for p in done)
            self.perf["encode_dispatches"] += 1
            full = await self.encode_service.encode(
                sinfo, codec, all_data, range(n))
            offsets: Dict[int, int] = {s: 0 for s in range(n)}
            for p in done:
                span = len(datas[p["oid"]])
                shard_len = (span // width) * chunk
                payload = {}
                for s in range(n):
                    payload[s] = full.get(s, b"")[
                        offsets[s]:offsets[s] + shard_len]
                    offsets[s] += shard_len
                p["payload"] = payload
        except Exception:
            done2 = []
            for p in done:
                try:
                    self.perf["encode_dispatches"] += 1
                    p["payload"] = await self.encode_service.encode(
                        sinfo, codec, datas[p["oid"]], range(n))
                    done2.append(p)
                except Exception:
                    log.exception("osd.%d: re-encode of %s failed",
                                  self.osd_id, p["oid"])
            done = done2
        self.tracer.record_stages(
            {"recover_decode": int((time.monotonic() - t_dec) * 1e6)})
        return done + done_repair

    def _repair_enabled(self) -> bool:
        """Repair-aware recovery kill switch: CEPH_TPU_MSR_REPAIR=0
        (env) or osd_msr_repair_enable=false (config) forces the
        classic k-read reconstruct for every object.  Results are
        bit-identical either way — repair and full decode agree by
        construction — so the switch exists for triage, not safety."""
        if not flags.enabled("CEPH_TPU_MSR_REPAIR"):
            return False
        return bool(self.config.get("osd_msr_repair_enable", True))

    async def _batch_repair(
            self, pool, plans: List[Dict[str, Any]]
    ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
        """Regenerating-code leg of _batch_reconstruct: fetch beta =
        chunk/alpha byte fragments from d helpers per object (hedged —
        stragglers recruit the next-ranked helper), then rebuild every
        lost chunk with ONE plan-cached dispatch per (lost, helper
        set) group: fragment streams of same-group objects concatenate
        along the byte axis, so cross-object batching is free exactly
        as in the decode leg.  Returns (done, fallbacks); fallback
        plans re-enter planning as classic full reads.

        The rebuilt stream is verified against the shard's crc32c
        ledger (hinfo) before it counts — fragments themselves cannot
        be CRC-checked, so a corrupt helper surfaces HERE and demotes
        the object to the verified classic path."""
        codec = self._codec(pool.id)
        alpha = codec.get_sub_chunk_count()
        d = codec.repair_degree()
        t_read = time.monotonic()

        async def fetch_one(plan: Dict[str, Any]
                            ) -> Optional[Dict[int, bytes]]:
            pg, oid = plan["pg"], plan["oid"]
            lost, want_v = plan["lost"], plan["version"]

            async def frag_job(shard: int, osd: int):
                ts = time.monotonic()
                if osd == self.osd_id:
                    rc, data, at = self._read_shard(pg, shard, oid,
                                                    0, 0)
                    self.hedge.observe(osd, time.monotonic() - ts,
                                       ok=rc in (0, ENOENT))
                    if rc != 0 or self._oi_version(at) != want_v:
                        return None
                    try:
                        frag = await asyncio.to_thread(
                            codec.repair_project, lost, data)
                    except Exception:
                        return None
                    self.perf["repair_fragments"] += 1
                    return (shard, frag)
                tid = self._next_tid()
                m = MOSDSubRead(tid, pg, shard, oid)
                m.repair = (lost, alpha)
                reply = await self._request(osd, m, tid)
                self.hedge.observe(osd, time.monotonic() - ts,
                                   ok=reply is not None
                                   and reply.rc in (0, ENOENT))
                if reply is None or reply.rc != 0 or \
                        self._oi_version(reply.attrs) != want_v:
                    # EOPNOTSUPP (codec drift), a stale version, or a
                    # transport fault all just fail this helper; the
                    # hedge recruits the next-ranked one
                    return None
                self.perf["subread_bytes"] += len(reply.data)
                return (shard, reply.data)

            jobs = [(osd, (lambda s=shard, o=osd: frag_job(s, o)))
                    for shard, osd in plan["helpers"]]

            def sufficient(results) -> bool:
                return len({r[0] for r in results
                            if r is not None}) >= d

            results, _all = await self.hedge.gather(
                jobs, need=d, sufficient=sufficient,
                failed=lambda r: r is None, label="repair_read")
            frags: Dict[int, bytes] = {}
            for r in results:
                if r is not None:
                    frags.setdefault(r[0], r[1])
            if len(frags) < d:
                return None
            # exactly d fragments in helper-rank order feed the math
            rank = {s: i for i, (s, _o) in enumerate(plan["helpers"])}
            keep = sorted(frags, key=lambda s: rank.get(s, 1 << 30))[:d]
            if len({len(frags[s]) for s in keep}) != 1:
                return None  # ragged shard lengths: not one version
            self.perf["recovery_bytes_read"] += sum(
                len(frags[s]) for s in keep)
            return {s: frags[s] for s in keep}

        fetched = await asyncio.gather(*(fetch_one(p) for p in plans))
        self.tracer.record_stages(
            {"recover_read": int((time.monotonic() - t_read) * 1e6)})

        t_dec = time.monotonic()
        done: List[Dict[str, Any]] = []
        fallbacks: List[Dict[str, Any]] = []
        groups: Dict[tuple, List[Dict[str, Any]]] = {}
        for plan, frags in zip(plans, fetched):
            if frags is None:
                fallbacks.append(plan)
                continue
            plan["_frags"] = frags
            groups.setdefault(
                (plan["lost"], tuple(sorted(frags))), []).append(plan)
        for (lost, helpers), group in groups.items():
            try:
                stacked = np.concatenate(
                    [np.stack([np.frombuffer(p["_frags"][h],
                                             dtype=np.uint8)
                               for h in helpers]) for p in group],
                    axis=1)
                syms = await asyncio.to_thread(
                    codec.repair_syms, lost, helpers, stacked)
                off = 0
                for p in group:
                    flen = len(p["_frags"][helpers[0]])
                    stream = codec.repair_assemble(
                        syms[:, off:off + flen])
                    off += flen
                    if not _hinfo_chunk_ok(p["attrs"], lost, stream):
                        log.warning(
                            "osd.%d: repaired chunk of %s fails its"
                            " crc ledger — falling back to verified"
                            " full decode", self.osd_id, p["oid"])
                        fallbacks.append(p)
                        continue
                    p["payload"] = {lost: stream}
                    self.perf["repair_objects"] += 1
                    done.append(p)
            except Exception:
                log.exception(
                    "osd.%d: batched repair of %d objects (lost=%d)"
                    " failed", self.osd_id, len(group), lost)
                fallbacks.extend(group)
        for p in plans:
            p.pop("_frags", None)
        self.tracer.record_stages(
            {"recover_decode": int((time.monotonic() - t_dec) * 1e6)})
        return done, fallbacks

    async def _locate_holders(self, pg: PgId, pool,
                              oid: str) -> List[Tuple[int, int]]:
        """(shard, osd) pairs of every up OSD holding any copy/fragment
        of oid — the purge target list for rollback/delete propagation."""
        if pool.type == TYPE_ERASURE:
            shard_list = list(
                range(self._codec(pool.id).get_chunk_count()))
        else:
            shard_list = [-1]
        probes = [(shard, osd)
                  for osd in self.osdmap.get_up_osds()
                  for shard in shard_list if osd != self.osd_id]
        results = await asyncio.gather(
            *(self._read_candidates(pg, shard, osd, oid,
                                    include_rollback=True)
              for shard, osd in probes))
        return [(shard, osd)
                for (shard, osd), (cands, _ok)
                in zip(probes, results) if cands]

    def _plan_guard(self, candidates, *extra) -> tuple:
        """Newest object version a recovery plan observed: max over the
        probed candidates' OI versions and any extra versions (need_v,
        adjudicated version).  Stamped on the plan's pushes so replicas
        can refuse pushes that predate their current state."""
        guard = ZERO
        for v in extra:
            if v is not None and v > guard:
                guard = v
        for _s, _p, at in candidates:
            v = self._oi_version(at)
            if v is not None and v > guard:
                guard = v
        return guard

    async def _recover_commit(self, state: PGState, pool,
                              plan: Dict[str, Any]) -> None:
        """Apply one plan: remove everywhere, or install the
        reconstructed copy wherever it's missing (concurrent pushes)."""
        pg = state.pg
        plog = self._load_log(state, pool)
        my_shard = state.my_shard(self.osd_id, pool.type)
        oid = plan["oid"]
        targets = plan["targets"]
        i_need = plan["i_need"]
        # recovery rewrites shards (or removes the object): the tier
        # entry may describe pre-adjudication state
        self.tier.invalidate(pg, oid)

        if plan["kind"] == "remove":
            async def remove_peer(shard_key: int, osd: int) -> None:
                shard = shard_key if shard_key >= -1 else -1
                tid = self._next_tid()
                # recovery ops carry the INTERVAL epoch: a live-epoch
                # stamp would raise replica fences above this interval
                # and fence out every subsequent client write
                reply = await self._request(
                    osd, MOSDSubWrite(tid, pg, shard, oid,
                                      [ShardOp("remove")],
                                      state.interval_epoch, None,
                                      self.osd_id,
                                      guard=plan.get("guard")), tid)
                # the remove RESOLVES the missing entry: the rollback
                # adjudicated "object does not exist" as the recovered
                # state.  Leaving peer_missing populated would re-plan
                # the same remove from the unfound-retry loop forever —
                # the silent livelock that parked k2m2 thrash runs with
                # an active+unfound PG and an empty log.
                if reply is None or reply.rc != 0:
                    log.warning(
                        "osd.%d: recovery remove of %s/%s on osd.%d"
                        " failed (%s)", self.osd_id, pg, oid, osd,
                        "timeout" if reply is None else reply.rc)
                    return
                if shard_key in state.peer_missing:
                    state.peer_missing[shard_key].pop(oid, None)

            removals = list(targets)
            if plan.get("purge"):
                # rolling back an uncommitted entry must also drop the
                # partial shards that DO exist — on acting members AND
                # on strays — or the orphan fragments resurface as
                # below-k candidates on every later read.  The plan
                # phase located the exact holders.
                seen = {(sk if sk >= -1 else -1, osd)
                        for sk, osd in removals}
                for shard, osd in plan.get("purge_locations", []):
                    if (shard, osd) not in seen:
                        removals.append((shard, osd))
            await asyncio.gather(*(remove_peer(sk, osd)
                                   for sk, osd in removals))
            if plan.get("purge") and not i_need:
                # my own partial shard goes too (I may hold data while
                # not being in my own missing set)
                t = Transaction()
                cid = self._cid(pg, my_shard)
                t.remove(cid, ObjectId(oid))
                try:
                    # recovery barrier: drained bypass, never windowed
                    await self.committer.commit_now(t)
                except KeyError:
                    pass
            if i_need:
                t = Transaction()
                cid = self._cid(pg, my_shard)
                t.remove(cid, ObjectId(oid))
                plog.missing.pop(oid, None)
                plog.stage(t, cid)
                try:
                    await self.committer.commit_now(t)
                except KeyError:
                    pass
            return

        payload = plan["payload"]
        obj_attrs = plan["attrs"]
        omap_payload = plan["omap"]

        async def install(shard: int, osd: int,
                          shard_key: Optional[int] = None) -> None:
            buf = payload.get(shard if pool.type == TYPE_ERASURE else -1,
                              b"")
            ops = [ShardOp("create"), ShardOp("truncate", size=0),
                   ShardOp("write", 0, buf)]
            for name, value in obj_attrs.items():
                ops.append(ShardOp("setattr", name=name, value=value))
            if pool.type == TYPE_REPLICATED:
                # authoritative omap REPLACES the target's: clear
                # first or deleted keys resurrect on the recovered copy
                ops.append(ShardOp("omap_clear"))
                if omap_payload:
                    ops.append(ShardOp(
                        "omap_set", data=encode_kv_map(omap_payload)))
            if osd == self.osd_id:
                t = Transaction()
                cid = self._cid(pg, shard)
                self._apply_shard_ops(t, cid, oid, ops)
                plog.missing.pop(oid, None)
                plog.stage(t, cid)
                # recovery install barrier: drained bypass
                await self.committer.commit_now(t)
            else:
                tid = self._next_tid()
                reply = await self._request(
                    osd, MOSDSubWrite(tid, pg, shard, oid, ops,
                                      state.interval_epoch, None,
                                      self.osd_id,
                                      guard=plan.get("guard")), tid)
                if reply is None or reply.rc != 0:
                    # the push did NOT land: leave this target in
                    # peer_missing so the next interval retries it
                    log.warning(
                        "osd.%d: recovery push of %s/%s to osd.%d"
                        " failed (%s)", self.osd_id, pg, oid, osd,
                        "timeout" if reply is None else reply.rc)
                    return
            # mark THIS target recovered as soon as its own push
            # lands: a failed sibling push must not cause successful
            # targets to be re-pushed next interval
            self.perf["recovery_bytes_repaired"] += len(buf)
            if shard_key is not None:
                state.peer_missing.get(shard_key, {}).pop(oid, None)

        jobs = []
        if i_need:
            jobs.append(install(my_shard, self.osd_id))
        for shard_key, osd in targets:
            jobs.append(install(shard_key if shard_key >= -1 else -1,
                                osd, shard_key))
        await asyncio.gather(*jobs)

    # -- client op engine (primary) ----------------------------------------

    async def _handle_client_op(self, conn: Connection,
                                msg: MOSDOp) -> None:
        op_id = self.op_tracker.create(
            f"osd_op({msg.client} {msg.pg} {msg.oid!r} "
            f"{[op.op for op in msg.ops]})")
        # EVERY op gets a root span while tracing is enabled (NULL_SPAN
        # when off): it parents the stage spans fanned out below via
        # contextvar, continues the client's trace when a wire context
        # rides in, and feeds the critical-path stage histograms +
        # tail-exemplar retention at finish.  Head sampling only gates
        # ring retention, never span existence.
        span = self.tracer.start(
            f"osd_op {msg.oid} {'+'.join(o.op for o in msg.ops)}",
            context=msg.trace)
        token = tracing.current_span.set(span) if span else None
        try:
            await self._handle_client_op_tracked(conn, msg, op_id)
        finally:
            op = self.op_tracker.finish(op_id)
            if token is not None:
                tracing.current_span.reset(token)
            self._finish_op_span(span, op)

    def _finish_op_span(self, span, op) -> None:
        """Close an op's root span and run the critical-path pipeline:
        per-stage self-times into the streaming histograms, and — for
        ops in the tail (complaint-time or rolling-p99 breach) — the
        FULL span tree retained as an exemplar (dump_op_trace /
        dump_historic_ops)."""
        if not span:
            return
        # finish() returns the rendered tree when sampling already
        # built one — the tail hook reuses it instead of rendering the
        # same spans twice
        tree = self.tracer.finish(span)
        if op is not None and self.op_tracker.is_tail(op.duration):
            # the tail pays for its full explanation: rendered tree +
            # critical path WITH the per-span path
            if tree is None:
                tree = span.tree_dicts()
            cp = tracing.critical_path(tree)
            self.tracer.record_stages(cp["stages"])
            self.op_tracker.retain_trace(op, {
                "trace_id": f"{span.trace_id:016x}",
                "description": op.description,
                "duration_ms": round((op.duration or 0.0) * 1e3, 3),
                "critical_path": cp,
                "spans": tree,
            })
        else:
            # the bulk pays only the allocation-light reduction: no
            # dict rendering, stages straight into the histograms
            cp = tracing.critical_path_spans(span)
            self.tracer.record_stages(cp["stages"])

    async def _handle_client_op_tracked(self, conn: Connection,
                                        msg: MOSDOp,
                                        op_id: int) -> None:
        if self.osdmap is None:
            await conn.send(MOSDOpReply(msg.tid, EAGAIN))
            return
        pool = self.osdmap.pools.get(msg.pg.pool)
        state = self.pgs.get(msg.pg)
        # placement comes from the PGState cache maintained per epoch by
        # _scan_pgs — recomputing CRUSH per op costs ~ms in the host
        # mapper and is pure waste (the reference's PG lookup is a map)
        primary = state.primary if state is not None else -1
        if pool is None or primary != self.osd_id or state is None:
            await conn.send(MOSDOpReply(
                msg.tid, EAGAIN, replay_epoch=self._epoch()))
            return
        # misdirected-op check (handle_misdirected_op role): a client
        # on a pre-split map addresses the PARENT pg; the parent's
        # acting set may be unchanged, so no fence fires — but
        # executing here would land the object in a PG it no longer
        # maps to (permanently invisible to post-split readers).
        # EAGAIN + replay_epoch makes the client refresh and resend to
        # the child.
        if msg.oid and not is_internal_name(msg.oid) and \
                not any(op.op == "pgls" for op in msg.ops):
            # pgls (and other PG-addressed ops) target the pg itself,
            # with no object name to place
            from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

            raw = PgId(pool.id,
                       ceph_str_hash_rjenkins(msg.oid.encode()))
            if pool.raw_pg_to_pg(raw) != msg.pg:
                await conn.send(MOSDOpReply(
                    msg.tid, EAGAIN, replay_epoch=self._epoch()))
                return
        if state.state != "active":
            # queue until peering completes (waiting_for_active)
            self.op_tracker.mark(op_id, "waiting_for_active")
            try:
                await asyncio.wait_for(state.active_event.wait(), 10.0)
            except asyncio.TimeoutError:
                await conn.send(MOSDOpReply(
                    msg.tid, EAGAIN, replay_epoch=self._epoch()))
                return
            # a parked op must not execute as a zombie in a LATER
            # interval than it was sent for — the client already
            # resent it there (require_same_or_newer_map discipline)
            if msg.epoch < state.interval_epoch:
                await conn.send(MOSDOpReply(
                    msg.tid, EAGAIN, replay_epoch=self._epoch()))
                return
        self.op_tracker.mark(op_id, "started")
        # reqid dedup: a resend of an op this primary already executed
        # gets the stored reply — re-running a non-idempotent op
        # (append, exec) would double-apply it
        reqid = (msg.client, msg.tid)
        cached = self._completed_ops.get(reqid)
        if cached is not None:
            rc, data, out = cached
        else:
            # QoS admit: cost scales with payload so a stream of
            # huge writes is charged accordingly (mClock item cost)
            nbytes = sum(len(op.data) for op in msg.ops)
            cost = 1.0 + nbytes / (1 << 20)
            tenant = getattr(msg, "tenant", "") or ""
            # dmClock piggyback: the client's ServiceTracker counted
            # its completions at OTHER OSDs since its last op here —
            # the tag advance below charges this class for them, so
            # reservation/limit hold cluster-wide (CEPH_TPU_DMCLOCK=0
            # pins both to 1: classic per-OSD mClock)
            qos_delta = qos_rho = 1
            if flags.enabled("CEPH_TPU_DMCLOCK"):
                qos_delta = getattr(msg, "qos_delta", 1)
                qos_rho = getattr(msg, "qos_rho", 1)
            op_class = sched_mod.CLIENT
            admitted = True
            if tenant and self._qos_tenants_enabled:
                op_class = sched_mod.tenant_class(tenant)
                # the admission gate runs BEFORE the op queue: an
                # over-limit tenant is delayed/shed here, before its
                # op consumes a queue slot or any encode-service/
                # hedge/tier resources at the execute stage.  The
                # synchronous fast path carries the common under-
                # limit accept with zero per-op allocation; only a
                # bucket miss awaits the delay/shed slow path.
                decision = self.admission.try_admit(tenant, cost)
                if decision is None:
                    decision = await self.admission.admit(tenant,
                                                          cost)
                if decision == SHED:
                    admitted = False
            try:
                qos_phase = ""
                if not admitted:
                    rc, data, out = EBUSY, b"", {}
                elif self._op_fast_lane_ok(pool, nbytes) and \
                        (qos_phase := self.scheduler.try_acquire(
                            op_class, cost, qos_delta, qos_rho)):
                    # sub-chunk fast lane: the scheduler charges the
                    # class's dmClock tags exactly as run()'s fast
                    # grant would (fairness accounting identical,
                    # over-limit classes refused into the queued
                    # path), minus the per-op lambda/coroutine round
                    # trip the stage histograms priced on tiny writes
                    try:
                        rc, data, out = await self._execute_ops(
                            state, pool, msg, conn)
                    finally:
                        self.scheduler.release()
                else:
                    async def _run_and_stamp():
                        # the grant phase is only visible inside the
                        # granted context; capture it for the reply
                        nonlocal qos_phase
                        qos_phase = sched_mod.current_phase()
                        return await self._execute_ops(state, pool,
                                                       msg, conn)

                    qos_phase = ""
                    rc, data, out = await self.scheduler.run(
                        op_class, cost, _run_and_stamp,
                        qos_delta=qos_delta, qos_rho=qos_rho)
            except asyncio.CancelledError:
                raise
            except sched_mod.QueueFull:
                # bounded-queue overflow: explicit refusal, the
                # client sees EBUSY instead of an unbounded park
                rc, data, out = EBUSY, b"", {}
            except UnfoundObject:
                rc, data, out = EAGAIN, b"", {}
            except Exception:
                log.exception("osd.%d: op %r failed", self.osd_id, msg)
                rc, data, out = EIO, b"", {}
            # dedup-cache replies of non-idempotent MUTATING ops only
            # (the reference tracks reqids for completed writes alone):
            # read-only replays are idempotent, and caching their
            # payloads would pin up to 4096 objects' data in memory.
            # Mutating errors ARE cached — an op vector can partially
            # commit before the failing op (e.g. append ok, omap EIO),
            # so re-executing the resend would double-apply the prefix.
            # EAGAIN alone commits nothing and must re-execute; an
            # EBUSY shed never started, so a resend must get a fresh
            # admission decision, not a cached refusal.
            if rc not in (EAGAIN, EBUSY) and \
                    any(op.op in _MUTATING_CLIENT_OPS
                        for op in msg.ops):
                self._completed_ops[reqid] = (rc, data, out)
                while len(self._completed_ops) > 4096:
                    self._completed_ops.popitem(last=False)
        await conn.send(MOSDOpReply(
            msg.tid, rc, data, out,
            replay_epoch=self._epoch() if rc == EAGAIN else 0,
            qos_phase=qos_phase if cached is None else ""))

    # -- coded compute (MOSDCompute, osd/compute.py) -----------------------

    async def _handle_compute_op(self, conn: Connection,
                                 msg: MOSDCompute) -> None:
        """Client scan op: admission gate first (an over-limit
        tenant's scan is delayed/shed before it consumes anything),
        then the engine fans out.  The dedicated `compute` mClock
        class is charged at the EVAL stage (eval_local_shards), not
        around the whole op — a wave parked on remote sub-computes
        must not occupy in-flight op slots while it waits."""
        op_id = self.op_tracker.create(
            f"compute({msg.client} {msg.kernel} n={len(msg.oids)})")
        span = self.tracer.start(
            f"compute_op {msg.kernel} n{len(msg.oids)}")
        token = tracing.current_span.set(span) if span else None
        try:
            if self.osdmap is None:
                await conn.send(MOSDComputeReply(msg.tid, EAGAIN))
                return
            self.op_tracker.mark(op_id, "started")
            # admission cost on the client-op scale (1.0 ~ one small
            # op): a wave scales sublinearly — per-object work is a
            # lane-width kernel eval, not a payload move
            cost = 1.0 + len(msg.oids) / 256.0
            tenant = getattr(msg, "tenant", "") or ""
            admitted = True
            if tenant and self._qos_tenants_enabled:
                decision = self.admission.try_admit(tenant, cost)
                if decision is None:
                    decision = await self.admission.admit(tenant,
                                                          cost)
                if decision == SHED:
                    admitted = False
            try:
                if not admitted:
                    rc, results, out = EBUSY, {}, {}
                else:
                    rc, results, out = await self.compute.execute(msg)
            except asyncio.CancelledError:
                raise
            except sched_mod.QueueFull:
                rc, results, out = EBUSY, {}, {}
            except Exception:
                log.exception("osd.%d: compute op %r failed",
                              self.osd_id, msg)
                rc, results, out = EIO, {}, {}
            await conn.send(MOSDComputeReply(
                msg.tid, rc, results, out,
                replay_epoch=self._epoch() if rc == EAGAIN else 0))
        finally:
            op = self.op_tracker.finish(op_id)
            if token is not None:
                tracing.current_span.reset(token)
            self._finish_op_span(span, op)

    async def _handle_sub_compute(self, conn: Connection,
                                  msg: MOSDSubCompute) -> None:
        """Shard side of the pushdown: evaluate the kernel over every
        local shard named by the wave — ONE batched plan-cached
        dispatch — and return (rc, version, result) per item.  Only
        kernel results (R bytes each) go back over the wire."""
        from ceph_tpu import compute as compute_mod
        from ceph_tpu.compute import ComputeError
        from ceph_tpu.compute import kernels as compute_kernels

        async def body() -> None:
            kern = compute_mod.get_kernel(msg.kernel)
            # per-kernel capability gate (not blanket linear-only):
            # approx_capable kernels run per-shard too, with the
            # primary doing a result-domain approximate combine
            if kern is None or not (kern.linear or
                                    kern.approx_capable):
                await conn.send(MOSDSubComputeReply(msg.tid, EINVAL))
                return
            try:
                args = compute_kernels.parse_args(msg.args)
            except ComputeError as e:
                await conn.send(MOSDSubComputeReply(msg.tid, e.rc))
                return
            items = [(PgId(pool, ps), shard, oid)
                     for pool, ps, shard, oid in msg.items]
            try:
                results = await self.compute.eval_local_shards(
                    items, kern, args)
            except sched_mod.QueueFull:
                # compute-class overflow: explicit refusal — the
                # primary's hedged gather treats it as a failed
                # flight and recruits a spare
                await conn.send(MOSDSubComputeReply(msg.tid, EBUSY))
                return
            await conn.send(MOSDSubComputeReply(msg.tid, 0, results))

        if msg.trace is not None:
            async with self.tracer.span(
                    f"sub_compute {msg.kernel} x{len(msg.items)}",
                    context=msg.trace):
                await body()
            return
        await body()

    async def _execute_ops(self, state: PGState, pool, msg: MOSDOp,
                           conn: Optional[Connection] = None
                           ) -> Tuple[int, bytes, Dict[str, Any]]:
        rc, data, out = 0, b"", {}
        if is_internal_name(msg.oid):
            # rollback generations and snap clones are internal
            # bookkeeping, not client-addressable objects
            return EINVAL, b"", {}
        # interval the op was admitted under: sub-writes are stamped
        # with this so a demoted primary's parked op cannot pass replica
        # fencing with a fresher live epoch
        state_admit_epoch = state.interval_epoch
        snapc = (msg.snapc_seq, msg.snapc_snaps) \
            if msg.snapc_seq > 0 else None
        read_oid = msg.oid
        if msg.snap_id > 0:
            # snap reads resolve to the head or a clone server-side
            resolved = await self._resolve_read_snap(
                state, pool, msg.oid, msg.snap_id)
            if resolved is None:
                return ENOENT, b"", {}
            read_oid = resolved
        for op in msg.ops:
            if op.op == "write_full":
                rc, out = await self._op_write_full(state, pool,
                                                    msg.oid, op.data,
                                                    state_admit_epoch,
                                                    snapc)
            elif op.op == "write":
                rc = await self._op_write(state, pool, msg.oid,
                                          op.offset, op.data,
                                          state_admit_epoch, snapc)
            elif op.op == "read":
                rc, data = await self._op_read(state, pool, read_oid,
                                               op.offset, op.length)
            elif op.op == "stat":
                rc, out = await self._op_stat(state, pool, read_oid)
            elif op.op == "append":
                rc = await self._op_write(state, pool, msg.oid,
                                          0, op.data,
                                          state_admit_epoch, snapc,
                                          append=True)
            elif op.op == "remove":
                rc = await self._op_remove(state, pool, msg.oid,
                                           state_admit_epoch, snapc)
            elif op.op == "setxattr":
                rc = await self._op_setxattr(state, pool, msg.oid,
                                             op.args["name"], op.data,
                                             state_admit_epoch, snapc)
            elif op.op == "rmxattr":
                rc = await self._op_setxattr(state, pool, msg.oid,
                                             op.args["name"], None,
                                             state_admit_epoch, snapc)
            elif op.op == "getxattr":
                rc, data = await self._op_getxattr(state, pool,
                                                   read_oid,
                                                   op.args["name"])
            elif op.op == "getxattrs":
                rc, out = await self._op_getxattrs(state, pool,
                                                   read_oid)
            elif op.op == "omap_set":
                rc = await self._op_omap_write(state, pool, msg.oid,
                                               "omap_set", op.data,
                                               state_admit_epoch,
                                               snapc)
            elif op.op == "omap_rm":
                rc = await self._op_omap_write(state, pool, msg.oid,
                                               "omap_rm", op.data,
                                               state_admit_epoch,
                                               snapc)
            elif op.op == "omap_get":
                rc, data = await self._op_omap_get(state, pool,
                                                   read_oid)
            elif op.op == "watch":
                rc = self._op_watch(state, pool, msg, conn,
                                    op.args.get("cookie", 0),
                                    op.args.get("unwatch", False))
            elif op.op == "notify":
                rc, out = await self._op_notify(state, pool, msg.oid,
                                                op.data)
            elif op.op == "call":
                rc, data = await self._op_call(
                    state, pool, read_oid, op.args.get("cls", ""),
                    op.args.get("method", ""), op.data,
                    state_admit_epoch, snapc,
                    read_only=msg.snap_id > 0)
            elif op.op == "pgls":
                rc, out = self._op_pgls(state, pool)
            else:
                rc = EINVAL
            if rc < 0:
                break
        return rc, data, out

    def _up_shard_targets(self, state: PGState, pool
                          ) -> List[Tuple[int, int]]:
        """[(shard, osd)] for up acting members; shard=-1 replicated."""
        out = []
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE or not self.osdmap.is_up(osd):
                continue
            shard = idx if pool.type == TYPE_ERASURE else -1
            out.append((shard, osd))
        return out

    def _min_size(self, pool) -> int:
        if pool.type == TYPE_ERASURE:
            codec = self._codec(pool.id)
            return max(pool.min_size, codec.get_data_chunk_count())
        return max(1, pool.min_size or 1)

    async def _submit_shard_writes(
            self, state: PGState, pool, oid: str,
            shard_ops: Dict[int, List[ShardOp]],
            entry: Optional[dict],
            admit_epoch: Optional[int] = None) -> int:
        """Fan out sub-writes to up shards (local applies directly);
        all must ack (sub_write_committed discipline).

        Sub-writes carry admit_epoch — the interval the op was admitted
        under — not the live epoch, so an op parked across an interval
        change can never outrun replica fencing."""
        pg = state.pg
        # EVERY primary mutation funnels through here: drop the
        # decoded-object tier entry BEFORE any shard changes so a
        # concurrent-looking read can never see post-write cached bytes
        self.tier.invalidate(pg, oid)
        if admit_epoch is None:
            admit_epoch = state.interval_epoch
        # fenced by a newer interval (a peering query outran our map, or
        # the interval changed after this op was admitted): stop
        # writing, incl. the local shard apply
        if self._epoch() < state.interval_epoch or \
                admit_epoch < state.interval_epoch:
            log.debug("osd.%d: write %s/%s fenced: admit %d, epoch %d,"
                      " interval %d", self.osd_id, pg, oid, admit_epoch,
                      self._epoch(), state.interval_epoch)
            return EAGAIN
        targets = self._up_shard_targets(state, pool)
        if len(targets) < self._min_size(pool):
            log.debug("osd.%d: write %s/%s: %d up targets < min_size %d",
                      self.osd_id, pg, oid, len(targets),
                      self._min_size(pool))
            return EAGAIN
        plog = self._load_log(state, pool)
        pending = []
        local_task: Optional[asyncio.Task] = None
        for shard, osd in targets:
            ops = shard_ops.get(shard)
            if ops is None:
                continue
            if osd == self.osd_id:
                t = Transaction()
                cid = self._cid(pg, shard)
                self._apply_shard_ops(t, cid, oid, ops,
                                      save_rollback=entry is not None)
                if entry is not None and \
                        ev(entry["version"]) > plog.info.last_update:
                    plog.append(entry)
                    plog.trim_to(
                        int(self.config["osd_min_pg_log_entries"]))
                plog.missing.pop(oid, None)
                plog.stage(t, cid)
                # group commit, concurrent with the remote fan-out:
                # the local barrier and the replica RTTs overlap, and
                # concurrent writers share one fsync.  The task is
                # created here (in the same sync section as the
                # plog.append above) so commit-lane order matches
                # version order.
                local_task = asyncio.get_running_loop().create_task(
                    self.committer.queue_transaction(t))
                # if this op is cancelled mid-gather the commit still
                # completes (as the old inline commit already had);
                # pre-retrieve so an orphaned failure cannot log
                # "exception never retrieved"
                local_task.add_done_callback(
                    lambda tk: None if tk.cancelled()
                    else tk.exception())
            else:
                tid = self._next_tid()
                self.perf["subwrite_bytes"] += sum(
                    len(op.data) for op in ops)
                pending.append(self._traced_subwrite(
                    osd, MOSDSubWrite(tid, pg, shard, oid, ops,
                                      admit_epoch, entry,
                                      self.osd_id), tid))
        replies = await asyncio.gather(*pending) if pending else []
        if local_task is not None:
            # raises what the local apply raised (as the inline call
            # did) — but only after the remote acks are in, so a local
            # failure cannot strand already-sent sub-writes unawaited
            await local_task
        # a shard that failed mid-write recovers via peering on the next
        # interval (its pg log lags); the write succeeds if enough
        # shards committed (min_size durability floor)
        acked = 1 + sum(1 for r in replies
                        if r is not None and r.rc == 0)
        if acked < self._min_size(pool):
            log.debug("osd.%d: write %s/%s: %d acks < min_size %d"
                      " (rcs=%s)", self.osd_id, pg, oid, acked,
                      self._min_size(pool),
                      [None if r is None else r.rc for r in replies])
            return EAGAIN
        full = len([s for s, _o in targets
                    if shard_ops.get(s) is not None])
        if entry is not None and acked == full:
            # every shard committed: the preserved previous generation
            # can never be needed again — trim it (the role of
            # ECBackend's rollback trim as log entries commit).  Awaited
            # (not fire-and-forget) so a sequential client's NEXT
            # overwrite — which clones a fresh rollback — cannot race
            # with this trim and lose its clone.
            await self._trim_rollbacks(state, oid, targets, admit_epoch,
                                       prior=ev(entry["prior"]))
        elif acked < full:
            # a shard missed the write WITHOUT an interval change (an
            # alive-but-slow peer timed out).  The reference's
            # invariant — sub-write failure implies peer death implies
            # re-peer implies log repair — does not hold for a soft
            # timeout, so nothing would fix the mixed-version object
            # until the next remap; EC reads below k would EIO.
            # Repair the object now through the scrub-repair path.
            self._schedule_object_repair(state, pool, oid)
        return 0

    def _schedule_object_repair(self, state: PGState, pool,
                                oid: str) -> None:
        """Deduplicated async single-object repair after a partially
        failed write fan-out."""
        key = (state.pg, oid)
        if key in self._pending_repairs or self._stopping:
            return
        self._pending_repairs.add(key)

        async def repair() -> None:
            try:
                # give straggler sub-writes a moment to land: the slow
                # peer may still apply, making the repair a no-op scan
                await asyncio.sleep(1.0)
                interval = state.interval_epoch
                async with state.obj_lock(oid):
                    if self._stopping or state.state != "active" or \
                            state.interval_epoch != interval or \
                            state.primary != self.osd_id:
                        return  # peering owns repair across intervals
                    run = {"objects": 0, "errors": 0, "repaired": 0}
                    await self._scrub_object(state, pool, oid, run)
                    if run["errors"]:
                        log.info(
                            "osd.%d: post-write repair of %s/%s:"
                            " %d inconsistencies, %d repaired",
                            self.osd_id, state.pg, oid,
                            run["errors"], run["repaired"])
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: post-write repair of %s/%s"
                              " failed", self.osd_id, state.pg, oid)
            finally:
                self._pending_repairs.discard(key)

        asyncio.get_running_loop().create_task(repair())

    async def _trim_rollbacks(self, state: PGState, oid: str,
                              targets: List[Tuple[int, int]],
                              epoch: int,
                              prior: Optional[tuple] = None) -> None:
        """Best-effort removal of each shard's rollback clone.

        guard=prior (the committed entry's previous generation): the
        clone this trim targets captured exactly that generation, so a
        trim that outlives its write — times out, stays in flight, and
        lands after a LATER write preserved a fresh clone — fails the
        replica's guard check instead of eating the fresh clone."""
        pg = state.pg
        rb = RB_PREFIX + oid
        pending = []
        for shard, osd in targets:
            try:
                if osd == self.osd_id:
                    cid = self._cid(pg, shard)
                    t = Transaction()
                    t.remove(cid, ObjectId(rb))
                    # post-ack trim rides the window (FIFO keeps it
                    # ordered before any later overwrite's clone)
                    await self.committer.queue_transaction(t)
                else:
                    tid = self._next_tid()
                    pending.append(self._request(
                        osd, MOSDSubWrite(tid, pg, shard, rb,
                                          [ShardOp("remove")],
                                          epoch, None, self.osd_id,
                                          guard=prior),
                        tid))
            except (KeyError, ConnectionError, OSError):
                pass  # a stale clone is only garbage
        if pending:
            # awaited on the client write path (post-ack, pre-return):
            # a slow peer here must not hide in osd_op self-time
            async with tracing.child_span("rollback_trim"):
                await asyncio.gather(*pending, return_exceptions=True)

    def _next_entry(self, state: PGState, pool, oid: str, op: str,
                    size: int = 0) -> dict:
        plog = self._load_log(state, pool)
        prior = plog.info.last_update
        version = (self._epoch(), state.next_version)
        state.next_version += 1
        return make_entry(version, prior, oid, op, size)

    async def _op_write_full(self, state: PGState, pool, oid: str,
                             data: bytes,
                             admit_epoch: Optional[int] = None,
                             snapc=None) -> Tuple[int, Dict[str, Any]]:
        # per-object lock on EVERY pool type: SnapSet updates are
        # read-modify-write and must not race other writes or trim.
        # Uncontended (the dominant small-write case), the lock is
        # taken synchronously — the PR-10 stage histograms priced the
        # per-op objlock coroutine round trip, and the contended path
        # below is unchanged (span and all)
        ctx = state.obj_lock(oid)
        if ctx.try_enter():
            try:
                if pool.type == TYPE_ERASURE:
                    state.extent_cache.pop(oid, None)
                return await self._op_write_full_locked(
                    state, pool, oid, data, admit_epoch, snapc)
            finally:
                ctx.exit_sync()
        async with ctx:
            if pool.type == TYPE_ERASURE:
                state.extent_cache.pop(oid, None)
            return await self._op_write_full_locked(
                state, pool, oid, data, admit_epoch, snapc)

    async def _op_write_full_locked(
            self, state: PGState, pool, oid: str, data: bytes,
            admit_epoch: Optional[int] = None, snapc=None
    ) -> Tuple[int, Dict[str, Any]]:
        if isinstance(data, bytearray) or (
                isinstance(data, memoryview) and not data.readonly):
            # caller-mutable buffer (possible via the loopback fast
            # path): snapshot BEFORE the stores adopt views of it, or
            # a client reusing its buffer would corrupt durable shards
            # under already-recorded hinfo crcs
            data = bytes(data)
        clone_ops: List[ShardOp] = []
        ss_raw: Optional[bytes] = None
        if snapc is not None:
            clone_ops, ss_raw = await self._snap_clone_prep(
                state, pool, oid, snapc[0], snapc[1])
        out: Dict[str, Any] = {}
        if pool.type == TYPE_ERASURE:
            codec = self._codec(pool.id)
            sinfo = self._sinfo(pool.id)
            width = sinfo.get_stripe_width()
            pad = -len(data) % width
            # data may be a zero-copy memoryview of the op frame; only
            # materialize when padding actually forces a copy — and
            # then exactly ONE copy into a right-sized buffer (the
            # bytes(data) + bytes(pad) concat paid two)
            if pad:
                padbuf = bytearray(len(data) + pad)
                padbuf[:len(data)] = data
                padded = memoryview(padbuf).toreadonly()
            else:
                padded = data
            # awaited BEFORE the version is allocated: concurrent
            # writes batch their encodes into shared device dispatches
            # (encode_service), and no suspension point sits between
            # _next_entry and _submit_shard_writes — log entries still
            # land in version order
            shards, hinfo, data_crc = \
                await self.encode_service.encode_with_hinfo(
                    sinfo, codec, padded,
                    range(codec.get_chunk_count()),
                    logical_len=len(data))
        entry = self._next_entry(state, pool, oid, "modify", len(data))
        oi = json.dumps({"size": len(data),
                         "version": entry["version"]}).encode()
        if pool.type == TYPE_REPLICATED:
            ops = [ShardOp("create"), ShardOp("truncate", size=0),
                   ShardOp("write", 0, data),
                   ShardOp("setattr", name=OI_ATTR, value=oi)]
            shard_ops = {-1: ops}
        else:
            if data_crc is not None:
                # content digest back to the client (the librados
                # returnvec role): a gateway can derive its ETag from
                # this instead of re-reading the whole object
                out["data_crc"] = data_crc
            hinfo_raw = json.dumps(hinfo.to_dict()).encode()
            shard_ops = {}
            for shard in range(codec.get_chunk_count()):
                buf = shards.get(shard, b"")
                shard_ops[shard] = [
                    ShardOp("create"), ShardOp("truncate", size=0),
                    ShardOp("write", 0, buf),
                    ShardOp("setattr", name=OI_ATTR, value=oi),
                    ShardOp("setattr", name=HINFO_ATTR, value=hinfo_raw)]
        self._apply_snap_ops(shard_ops, clone_ops, ss_raw)
        rc = await self._submit_shard_writes(state, pool, oid,
                                             shard_ops, entry,
                                             admit_epoch)
        return rc, out

    @staticmethod
    def _apply_snap_ops(shard_ops: Dict[int, List[ShardOp]],
                        clone_ops: List[ShardOp],
                        ss_raw: Optional[bytes]) -> None:
        """Prepend the clone (captures pre-write state) and append the
        updated SnapSet attr on every shard's op list."""
        for ops in shard_ops.values():
            if clone_ops:
                ops[:0] = list(clone_ops)
            if ss_raw is not None:
                ops.append(ShardOp("setattr", name=SS_ATTR,
                                   value=ss_raw))

    async def _op_write(self, state: PGState, pool, oid: str,
                        offset: int, data: bytes,
                        admit_epoch: Optional[int] = None,
                        snapc=None, append: bool = False) -> int:
        """Partial-extent write.  Replicated: direct range write.
        EC: stripe-level read-modify-write (the start_rmw pipeline).
        Both under the per-object lock (SnapSet RMW must not race).
        append=True resolves the offset to the current object end
        INSIDE the lock so concurrent appends serialize correctly."""
        if isinstance(data, bytearray) or (
                isinstance(data, memoryview) and not data.readonly):
            # snapshot caller-mutable buffers before any store adopts a
            # view of them (same guard as _op_write_full_locked)
            data = bytes(data)
        async with state.obj_lock(oid):
            await self._wait_for_degraded(state, pool, oid)
            if append:
                oi, _ss = await self._head_info(state, pool, oid)
                offset = oi.get("size", 0) \
                    if oi is not None and not oi.get("whiteout") else 0
            if pool.type == TYPE_ERASURE:
                return await self._ec_rmw(state, pool, oid, offset,
                                          data, admit_epoch, snapc)
            clone_ops: List[ShardOp] = []
            ss_raw: Optional[bytes] = None
            if snapc is not None:
                clone_ops, ss_raw = await self._snap_clone_prep(
                    state, pool, oid, snapc[0], snapc[1])
            # stat BEFORE the version allocation: _next_entry consumes
            # state.next_version, and a suspension between allocation
            # and _submit_shard_writes would let a cancellation strand
            # the version (pg-log gap) or a concurrent write submit a
            # LATER version first (out-of-order log append) — the same
            # discipline _op_write_full_locked documents for its
            # encode awaits
            rc, old_size = await self._stat_size(state, pool, oid)
            new_size = max(old_size if rc == 0 else 0,
                           offset + len(data))
            entry = self._next_entry(state, pool, oid, "modify")
            oi = json.dumps({"size": new_size,
                             "version": entry["version"]}).encode()
            ops = [ShardOp("create"),
                   ShardOp("write", offset, data),
                   ShardOp("setattr", name=OI_ATTR, value=oi)]
            shard_ops = {-1: ops}
            self._apply_snap_ops(shard_ops, clone_ops, ss_raw)
            return await self._submit_shard_writes(state, pool, oid,
                                                   shard_ops, entry,
                                                   admit_epoch)

    async def _ec_rmw(self, state: PGState, pool, oid: str,
                      offset: int, data: bytes,
                      admit_epoch: Optional[int],
                      snapc=None) -> int:
        """Stripe-level EC read-modify-write (ECBackend start_rmw ->
        try_state_to_reads -> try_reads_to_commit,
        /root/reference/src/osd/ECBackend.cc:1858-2087, with the
        ExtentCache role played by state.extent_cache).

        Reads ONLY the touched stripes' chunk ranges (served from the
        extent cache when a preceding write on this object covered
        them), merges the new bytes, re-encodes just those stripes in
        one batched dispatch, and writes back per-shard chunk RANGES.
        Cumulative shard hashes cannot survive a mid-stream overwrite,
        so the hinfo drops its chunk hashes (the reference's
        set_total_chunk_size_clear_hash overwrite discipline); version
        agreement carries read consistency, scrub recomputes digests."""
        codec = self._codec(pool.id)
        sinfo = self._sinfo(pool.id)
        width = sinfo.get_stripe_width()
        chunk = sinfo.get_chunk_size()
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()

        clone_ops: List[ShardOp] = []
        ss_raw: Optional[bytes] = None
        if snapc is not None:
            clone_ops, ss_raw = await self._snap_clone_prep(
                state, pool, oid, snapc[0], snapc[1])

        start, span = sinfo.offset_len_to_stripe_bounds(
            (offset, len(data)))
        cache = state.extent_cache.get(oid)

        old_size = None
        merged: Optional[bytearray] = None
        if cache is not None:
            missing_stripes = [
                s for s in range(start, start + span, width)
                if s not in cache["stripes"]]
            old_size = cache["size"]
            old_padded = -(-old_size // width) * width
            if not any(s < old_padded for s in missing_stripes):
                # cache + zero-fill covers the whole span: no reads
                merged = bytearray(span)
                for s in range(start, start + span, width):
                    frag = cache["stripes"].get(s)
                    if frag is not None:
                        merged[s - start:s - start + width] = frag
        if merged is None:
            # read the touched stripes' chunk ranges from the acting
            # shards and reconstruct the span
            chunk_off = (start // width) * chunk
            chunk_len = (span // width) * chunk
            candidates, _complete, version, good, oi = \
                await self._gather_and_select(
                    state, pool, oid, need=k, offset=chunk_off,
                    length=chunk_len)
            # an unfound object must not be zero-filled and overwritten
            # as if it never existed — block the write like the reads
            if not candidates:
                self._block_if_unfound(state, pool, oid)
            merged = bytearray(span)
            if candidates:
                if version is None:
                    self._block_if_unfound(state, pool, oid)
                    self._schedule_object_repair(state, pool, oid)
                    return EAGAIN
                self._require_fresh(state, pool, oid, version)
                old_size = oi.get("size", 0)
                old_padded = -(-old_size // width) * width
                # shards may come back short when the range reaches past
                # the old object end: pad to the span's chunk length
                frag_len = min(
                    chunk_len,
                    max(0, (old_padded // width) * chunk
                        - chunk_off))
                if frag_len > 0:
                    chosen_frags = ec_util.choose_decode_set(
                        codec, good, k,
                        prefer=self._shard_rank(state))
                    if chosen_frags is None:
                        return EIO
                    frags = {}
                    for s, payload in chosen_frags.items():
                        # view of the sub-read frame; pad the short-
                        # shard case with ONE right-sized copy
                        buf = memoryview(payload)[:frag_len]
                        if len(buf) < frag_len:
                            pb = bytearray(frag_len)
                            pb[:len(buf)] = buf
                            buf = memoryview(pb).toreadonly()
                        frags[s] = buf
                    self.perf["decode_dispatches"] += 1
                    decoded = await self.encode_service.decode(
                        sinfo, codec, frags)
                    merged[:len(decoded)] = decoded
            else:
                old_size = 0
        # overlay the client bytes
        rel = offset - start
        merged[rel:rel + len(data)] = data
        new_size = max(old_size or 0, offset + len(data))

        # re-encode awaited BEFORE the version is allocated (same
        # ordering discipline as _op_write_full_locked): concurrent
        # RMWs share a batched dispatch through the encode service.
        # ZERO materializations of the merged span: the local
        # bytearray never escapes or mutates past this point, so a
        # frozen view serves the encode AND the extent cache (it was
        # one full copy, and before PR 12 two).
        self.perf["encode_dispatches"] += 1
        merged_b = memoryview(merged).toreadonly()
        shards = await self.encode_service.encode(
            sinfo, codec, merged_b, range(n))
        entry = self._next_entry(state, pool, oid, "modify", new_size)
        oi_raw = json.dumps({"size": new_size,
                             "version": entry["version"]}).encode()
        hinfo = ec_util.HashInfo(n)
        hinfo.set_total_chunk_size_clear_hash(
            (-(-new_size // width)) * chunk)
        hinfo_raw = json.dumps(hinfo.to_dict()).encode()
        chunk_off = (start // width) * chunk
        shard_ops = {}
        for shard in range(n):
            frag = shards.get(shard, b"")
            shard_ops[shard] = [
                ShardOp("create"),
                ShardOp("write", chunk_off, frag),
                ShardOp("setattr", name=OI_ATTR, value=oi_raw),
                ShardOp("setattr", name=HINFO_ATTR, value=hinfo_raw)]
        self._apply_snap_ops(shard_ops, clone_ops, ss_raw)
        rc = await self._submit_shard_writes(state, pool, oid,
                                             shard_ops, entry,
                                             admit_epoch)
        if rc == 0:
            self._cache_put(state, oid, entry["version"], new_size,
                            start, merged_b, width)
        else:
            state.extent_cache.pop(oid, None)
        return rc

    # extent-cache bookkeeping (bounded; coherent under the per-object
    # lock + single-primary discipline; dropped on interval change)
    _CACHE_MAX_STRIPES = 256

    def _cache_put(self, state: PGState, oid: str, version, size: int,
                   start: int, span_bytes: bytes, width: int) -> None:
        entry = state.extent_cache.get(oid)
        if entry is None or entry.get("version") is None:
            entry = {"version": version, "size": size, "stripes": {}}
        entry["version"] = version
        entry["size"] = size
        for s in range(0, len(span_bytes), width):
            entry["stripes"][start + s] = span_bytes[s:s + width]
        state.extent_cache.pop(oid, None)
        state.extent_cache[oid] = entry
        total = sum(len(e["stripes"])
                    for e in state.extent_cache.values())
        while total > self._CACHE_MAX_STRIPES and state.extent_cache:
            _old_oid, old_e = state.extent_cache.popitem(last=False)
            total -= len(old_e["stripes"])

    async def _stat_size(self, state: PGState, pool, oid: str
                         ) -> Tuple[int, int]:
        rc, out = await self._op_stat(state, pool, oid)
        return rc, out.get("size", 0)

    def _pg_is_clean(self, state: PGState, pool, oid: str) -> bool:
        plog = self._load_log(state, pool)
        if oid in plog.missing:
            return False
        return not any(oid in m for m in state.peer_missing.values())

    async def _wait_for_degraded(self, state: PGState, pool,
                                 oid: str) -> None:
        """wait_for_degraded_object role (PrimaryLogPG.cc): a PARTIAL
        mutation (extent write, EC RMW, xattr, omap) on an object some
        acting member is missing must not proceed — on the missing
        replica it would create a hole-ridden partial object under a
        current-looking version.  Recover the object inline first
        (caller holds the object lock, so background recovery of this
        object cannot interleave); if it stays missing, the data is
        unfound and the op blocks (EAGAIN) rather than inventing state.

        Full-object overwrites (write_full, remove) do NOT come here:
        they supersede every shard's content and double as recovery-by-
        overwrite."""
        if self._pg_is_clean(state, pool, oid):
            return
        await self._recover_object(state, pool, oid,
                                   self._acting_peer_shards(state, pool))
        if not self._pg_is_clean(state, pool, oid):
            raise UnfoundObject(oid)

    def _acting_peer_shards(self, state: PGState, pool
                            ) -> Dict[int, int]:
        """shard_key -> osd for every UP acting member except me (EC:
        positional shard; replicated: unique -(idx+2) key per replica)."""
        peer_shards: Dict[int, int] = {}
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE or osd == self.osd_id or \
                    not self.osdmap.is_up(osd):
                continue
            shard_key = idx if pool.type == TYPE_ERASURE else -(idx + 2)
            peer_shards[shard_key] = osd
        return peer_shards

    def _block_if_unfound(self, state: PGState, pool, oid: str) -> None:
        """Called when an op could not locate/decode an object's data:
        if the PG log still says the object exists (it is in a missing
        set), the acked bytes live on a source that is currently down
        or unprobed — UNFOUND.  Block the op (EAGAIN via UnfoundObject,
        the waiting_for_unreadable_object role) instead of reporting
        ENOENT/EIO or zero-filling — any of those would invent a
        deletion or corruption the log never recorded."""
        if not self._pg_is_clean(state, pool, oid):
            raise UnfoundObject(oid)

    def _acked_version(self, state: PGState, pool, oid: str) -> tuple:
        """Newest version any missing set records as acked for oid."""
        plog = self._load_log(state, pool)
        need = plog.missing.get(oid) or ZERO
        for m in state.peer_missing.values():
            nv = m.get(oid) or ZERO
            if nv > need:
                need = nv
        return need

    def _require_fresh(self, state: PGState, pool, oid: str,
                       version) -> None:
        """Serving a version OLDER than the acked one in a missing set
        would expose a rolled-back write while its real holder is down
        (reads and recovery must agree on the acked-write invariant —
        recovery's need_v guard is the other half)."""
        if version is not None and \
                self._acked_version(state, pool, oid) > version:
            raise UnfoundObject(oid)

    # -- read tier agent (HitSet + PrimaryLogPG agent role) ----------------

    def _persist_sealed_hitsets(self) -> None:
        """Archive sealed hit sets into the pg-meta object's omap
        under the hitset_ key prefix (hit_set persistence role),
        trimming entries that decayed off the stack."""
        for pg, seq, hs in self.tier.pop_sealed():
            state = self.pgs.get(pg)
            pool = self.osdmap.pools.get(pg.pool) \
                if self.osdmap else None
            if state is None or pool is None:
                continue
            shard = state.my_shard(self.osd_id, pool.type)
            cid = self._cid(pg, shard)
            meta = ObjectId(PGMETA_OID)
            t = Transaction()
            t.touch(cid, meta)
            t.omap_setkeys(cid, meta, {
                f"{HITSET_OMAP_PREFIX}{seq:08d}":
                    json.dumps(hs.to_dict()).encode()})
            stale = seq - max(self.tier.hit_set_count - 1, 1)
            if stale >= 1:
                # trim a WINDOW, not just one key: sealed-ring
                # overflow (quiet persisting path) can skip seqs, and
                # a single-key trim would strand their archives in
                # the omap forever
                t.omap_rmkeys(cid, meta, [
                    f"{HITSET_OMAP_PREFIX}{s:08d}"
                    for s in range(max(1, stale - 63), stale + 1)])
            try:
                self.store.queue_transaction(t)
            except (KeyError, IOError):
                pass  # shard collection gone (interval churn)

    def _tier_kick_promote(self, state: PGState, pool,
                           oid: str) -> None:
        """Spawn one deduplicated, inflight-capped promotion task."""
        if self._stopping or \
                not self.tier.begin_promote(state.pg, oid):
            return
        task = asyncio.get_running_loop().create_task(
            self._tier_promote(state, pool, oid))
        self._promote_tasks.add(task)
        task.add_done_callback(self._promote_tasks.discard)

    async def _tier_promote(self, state: PGState, pool,
                            oid: str) -> None:
        """Agent promotion: decode the whole object ONCE through the
        cold read path and install the bytes in the tier.  Runs under
        the mClock background_best_effort class (client reads keep
        their reservation; a promotion storm is throttled, never
        starves I/O) and under the per-object lock, so the install
        cannot race a writer's invalidation."""
        pg = state.pg
        interval = state.interval_epoch
        installed = False
        span = self.tracer.start(f"tier_promote {pg} {oid}")
        # install as current: create_task copied the kicking READ's
        # context, so without this the promotion's queue/objlock stage
        # spans would parent into the CLIENT op's tree and the
        # still-running promote would own the op's critical-path tail
        token = tracing.current_span.set(span if span else None)
        try:
            async def decode_and_install():
                nonlocal installed
                async with state.obj_lock(oid):
                    if self._stopping or state.state != "active" or \
                            state.interval_epoch != interval or \
                            state.primary != self.osd_id:
                        span.event("aborted: interval/teardown")
                        return
                    rc, payload = await self._op_read(
                        state, pool, oid, 0, 0, use_tier=False)
                    if rc != 0:
                        span.event(f"decode rc={rc}")
                        return
                    # the decode awaited: re-check the interval (it
                    # only ever advances) — a map flap during the
                    # decode may have let another primary commit
                    # writes this daemon never saw, and drop_pg has
                    # already run; installing would cache stale bytes
                    # nothing will invalidate
                    if self._stopping or \
                            state.interval_epoch != interval or \
                            state.primary != self.osd_id:
                        span.event("aborted: interval moved mid-decode")
                        return
                    self.tier.end_promote(pg, oid,
                                          buffer_mod.adopt(payload))
                    installed = True
                    span.event(f"promoted {len(payload)}B")
            await self.scheduler.run(sched_mod.BEST_EFFORT, 4.0,
                                     decode_and_install)
        except asyncio.CancelledError:
            pass                      # daemon teardown
        except (RuntimeError, UnfoundObject):
            pass                      # scheduler stopped / degraded
        except Exception:
            log.exception("osd.%d: tier promote %s/%s failed",
                          self.osd_id, pg, oid)
        finally:
            tracing.current_span.reset(token)
            if not installed:
                self.tier.end_promote(pg, oid, None)
            self.tracer.finish(span)

    @staticmethod
    def _tier_slice(data: bytes, offset: int, length: int) -> bytes:
        """Slice a cached decoded object exactly like the cold path
        slices its decode output (same offset/length semantics, so the
        bypass is bit-identical).  Returns a VIEW — the reply encoder
        writes it to the wire without materializing."""
        if offset >= len(data):
            return b""
        view = memoryview(data)
        if length:
            return view[offset:offset + length]
        if offset:
            return view[offset:]
        return data

    async def _op_read(self, state: PGState, pool, oid: str,
                       offset: int, length: int,
                       use_tier: bool = True
                       ) -> Tuple[int, bytes]:
        # hot-set tracking + read tier: record the read, serve a
        # promoted EC object straight from the decoded-object cache
        # (zero EC plan dispatches), and kick an agent promotion when
        # the hit count crosses osd_tier_promote_min_recency.
        # use_tier=False is the promotion decode itself (and the
        # coherency tests' cold-path oracle).
        tracked = (use_tier and self.tier.enabled
                   and not is_internal_name(oid))
        if tracked:
            self.tier.record_read(state.pg, oid)
            if self.tier.sealed_pending():
                self._persist_sealed_hitsets()
            if pool.type == TYPE_ERASURE:
                cached = self.tier.lookup(state.pg, oid)
                if cached is not None:
                    return 0, self._tier_slice(cached, offset, length)
                # promote signal only on a miss: a steady-state tier
                # hit skips the archived-bloom probes entirely
                hit_count = self.tier.hit_count(state.pg, oid)
                if self.tier.wants_promote(state.pg, oid, hit_count):
                    self._tier_kick_promote(state, pool, oid)
        if pool.type == TYPE_REPLICATED:
            # fast path: primary serves from its own copy when the
            # object is fully recovered (the reference's normal read)
            if self._pg_is_clean(state, pool, oid):
                shard = state.my_shard(self.osd_id, pool.type)
                rc, data, at = self._read_shard(state.pg, shard, oid)
                if rc == 0 and OI_ATTR in at:
                    oi = json.loads(at[OI_ATTR])
                    if oi.get("whiteout"):
                        return ENOENT, b""
                    # view slices end to end: the reply encoder
                    # writes the range straight from the store buffer
                    view = memoryview(data)[:oi.get("size",
                                                    len(data))]
                    if length:
                        view = view[offset:offset + length]
                    elif offset:
                        view = view[offset:]
                    return 0, view
                if rc == ENOENT:
                    return ENOENT, b""
            candidates, _complete, version, chosen, oi = \
                await self._gather_and_select(state, pool, oid,
                                              need=1, record=tracked)
            if not candidates:
                self._block_if_unfound(state, pool, oid)
                return ENOENT, b""
            if version is None:
                self._block_if_unfound(state, pool, oid)
                return EIO, b""
            self._require_fresh(state, pool, oid, version)
            if oi.get("whiteout"):
                return ENOENT, b""
            # view slices over the sub-read reply's frame buffer
            view = memoryview(chosen[next(iter(chosen))])
            view = view[:oi.get("size", len(view))]
            if length:
                view = view[offset:offset + length]
            elif offset:
                view = view[offset:]
            return 0, view
        codec = self._codec(pool.id)
        sinfo = self._sinfo(pool.id)
        k = codec.get_data_chunk_count()
        width = sinfo.get_stripe_width()
        chunk = sinfo.get_chunk_size()
        if length > 0:
            # ranged read: fetch ONLY the touched stripes' chunk ranges
            # (get_want_to_read_shards, ECBackend.cc:2380) — a 4 KiB
            # read of a large object moves O(stripe), not O(object).
            # Consistency rides version agreement; the whole-shard crc
            # cannot be checked on a fragment (scrub's job).
            start, span = sinfo.offset_len_to_stripe_bounds(
                (offset, length))
            chunk_off = (start // width) * chunk
            chunk_len = (span // width) * chunk
            candidates, _complete, version, good, oi = \
                await self._gather_and_select(
                    state, pool, oid, need=k, offset=chunk_off,
                    length=chunk_len, record=tracked)
            if not candidates:
                self._block_if_unfound(state, pool, oid)
                return ENOENT, b""
            if version is None:
                self._block_if_unfound(state, pool, oid)
                # clean PG but no k-agreement: a soft-failed write
                # left mixed generations — repair + client retry
                self._schedule_object_repair(state, pool, oid)
                return EAGAIN, b""
            self._require_fresh(state, pool, oid, version)
            if oi.get("whiteout"):
                return ENOENT, b""
            size = oi.get("size", 0)
            if offset >= size:
                return 0, b""
            padded = -(-size // width) * width
            frag_len = min(chunk_len,
                           max(0, (padded // width) * chunk - chunk_off))
            if frag_len <= 0:
                return 0, b""
            chosen_frags = ec_util.choose_decode_set(
                codec, good, k, prefer=self._shard_rank(state))
            if chosen_frags is None:
                return EIO, b""
            frags = {}
            for s, payload in chosen_frags.items():
                # view of the sub-read frame; the short-shard case
                # (reads past the object end) pads with ONE
                # right-sized copy
                buf = memoryview(payload)[:frag_len]
                if len(buf) < frag_len:
                    pb = bytearray(frag_len)
                    pb[:len(buf)] = buf
                    buf = memoryview(pb).toreadonly()
                frags[s] = buf
            self.perf["decode_dispatches"] += 1
            data = await self.encode_service.decode(sinfo, codec,
                                                    frags)
            rel = offset - start
            return 0, memoryview(data)[
                rel:rel + min(length, size - offset)]
        # newest version with >= k intact same-version shards wins;
        # hinfo crc drops corrupt shards (handle_sub_read's verify)
        candidates, _complete, version, good, oi = \
            await self._gather_and_select(state, pool, oid, need=k,
                                          verify_hinfo=True,
                                          record=tracked)
        if not candidates:
            self._block_if_unfound(state, pool, oid)
            return ENOENT, b""
        if version is None:
            self._block_if_unfound(state, pool, oid)
            self._schedule_object_repair(state, pool, oid)
            return EAGAIN, b""
        self._require_fresh(state, pool, oid, version)
        if oi.get("whiteout"):
            return ENOENT, b""
        size = oi.get("size", 0)
        frags = ec_util.choose_decode_set(
            codec, good, k, prefer=self._shard_rank(state))
        if frags is None:
            return EIO, b""
        self.perf["decode_dispatches"] += 1
        data = await self.encode_service.decode(sinfo, codec, frags)
        # view slices over the decode output
        view = memoryview(data)[:size]
        if length:
            view = view[offset:offset + length]
        elif offset:
            view = view[offset:]
        return 0, view

    async def _op_stat(self, state: PGState, pool, oid: str
                       ) -> Tuple[int, Dict[str, Any]]:
        # stat needs attrs + version agreement only: fetch one byte per
        # shard, not the whole payload — and only the first `need`
        # consistent answers (hedged)
        need = self._codec(pool.id).get_data_chunk_count() \
            if pool.type == TYPE_ERASURE else 1
        candidates, _complete, version, _chosen, oi = \
            await self._gather_and_select(state, pool, oid,
                                          need=need, length=1)
        if not candidates:
            self._block_if_unfound(state, pool, oid)
            return ENOENT, {}
        if version is None:
            self._block_if_unfound(state, pool, oid)
            return EIO, {}
        self._require_fresh(state, pool, oid, version)
        if oi.get("whiteout"):
            return ENOENT, {}
        return 0, {"size": oi.get("size", 0),
                   "version": oi.get("version")}

    async def _op_remove(self, state: PGState, pool, oid: str,
                         admit_epoch: Optional[int] = None,
                         snapc=None) -> int:
        async with state.obj_lock(oid):
            state.extent_cache.pop(oid, None)
            # the whiteout decision depends on the HEAD's SnapSet, not
            # on whether the deleting client supplied a snap context: a
            # snapless client's remove must never orphan live clones
            oi, ss = await self._head_info(state, pool, oid)
            if oi is None or oi.get("whiteout"):
                return ENOENT
            clone_ops: List[ShardOp] = []
            ss_raw: Optional[bytes] = None
            if snapc is not None:
                clone_ops, ss_raw = await self._snap_clone_prep(
                    state, pool, oid, snapc[0], snapc[1],
                    head=(oi, ss))
                if ss_raw is not None:
                    ss = json.loads(ss_raw)
            if pool.type == TYPE_REPLICATED:
                shards = [-1]
            else:
                shards = list(
                    range(self._codec(pool.id).get_chunk_count()))
            if clone_ops or ss.get("clones"):
                # snapshots still reference this object's data: the
                # head becomes a WHITEOUT carrying the SnapSet until
                # every clone is trimmed (the snapdir/whiteout role)
                entry = self._next_entry(state, pool, oid, "modify")
                oi_raw = json.dumps(
                    {"size": 0, "whiteout": True,
                     "version": entry["version"]}).encode()
                ops = [ShardOp("truncate", size=0),
                       ShardOp("setattr", name=OI_ATTR, value=oi_raw)]
                shard_ops = {s: list(ops) for s in shards}
                self._apply_snap_ops(shard_ops, clone_ops,
                                     ss_raw or json.dumps(ss).encode())
                return await self._submit_shard_writes(
                    state, pool, oid, shard_ops, entry, admit_epoch)
            entry = self._next_entry(state, pool, oid, "delete")
            shard_ops = {s: [ShardOp("remove")] for s in shards}
            return await self._submit_shard_writes(state, pool, oid,
                                                   shard_ops, entry,
                                                   admit_epoch)

    # -- xattr / omap client ops (the ObjectOperation attr surface) --------

    async def _op_setxattr(self, state: PGState, pool, oid: str,
                           name: str, value: Optional[bytes],
                           admit_epoch: Optional[int],
                           snapc=None) -> int:
        """Set (value) or remove (value=None) a USER xattr — a logged,
        versioned write on every shard (attrs are object metadata and
        ride with the object through snapshots and recovery)."""
        async with state.obj_lock(oid):
            await self._wait_for_degraded(state, pool, oid)
            oi, _ss = await self._head_info(state, pool, oid)
            if oi is None or oi.get("whiteout"):
                return ENOENT
            clone_ops: List[ShardOp] = []
            ss_raw: Optional[bytes] = None
            if snapc is not None:
                clone_ops, ss_raw = await self._snap_clone_prep(
                    state, pool, oid, snapc[0], snapc[1],
                    head=(oi, _ss))
            entry = self._next_entry(state, pool, oid, "modify",
                                     oi.get("size", 0))
            oi_raw = json.dumps({"size": oi.get("size", 0),
                                 "version": entry["version"]}).encode()
            key = USER_ATTR_PREFIX + name
            if value is None:
                attr_op = ShardOp("rmattr", name=key)
            else:
                attr_op = ShardOp("setattr", name=key, value=value)
            ops = [attr_op,
                   ShardOp("setattr", name=OI_ATTR, value=oi_raw)]
            if pool.type == TYPE_REPLICATED:
                shard_ops = {-1: list(ops)}
            else:
                n = self._codec(pool.id).get_chunk_count()
                shard_ops = {s: list(ops) for s in range(n)}
            self._apply_snap_ops(shard_ops, clone_ops, ss_raw)
            return await self._submit_shard_writes(state, pool, oid,
                                                   shard_ops, entry,
                                                   admit_epoch)

    async def _op_getxattr(self, state: PGState, pool, oid: str,
                           name: str) -> Tuple[int, bytes]:
        rc, attrs = await self._gather_user_attrs(state, pool, oid)
        if rc != 0:
            return rc, b""
        value = attrs.get(name)
        if value is None:
            return -61, b""  # ENODATA
        return 0, value

    async def _op_getxattrs(self, state: PGState, pool, oid: str
                            ) -> Tuple[int, Dict[str, Any]]:
        rc, attrs = await self._gather_user_attrs(state, pool, oid)
        if rc != 0:
            return rc, {}
        # JSON reply surface: values as latin-1-safe strings
        return 0, {"xattrs": {k: v.decode("latin-1")
                              for k, v in attrs.items()}}

    async def _gather_user_attrs(self, state: PGState, pool, oid: str
                                 ) -> Tuple[int, Dict[str, bytes]]:
        need = self._codec(pool.id).get_data_chunk_count() \
            if pool.type == TYPE_ERASURE else 1
        candidates, _complete, version, chosen, oi = \
            await self._gather_and_select(state, pool, oid,
                                          need=need, length=1)
        if not candidates:
            self._block_if_unfound(state, pool, oid)
            return ENOENT, {}
        if version is None:
            self._block_if_unfound(state, pool, oid)
            return EIO, {}
        self._require_fresh(state, pool, oid, version)
        if oi.get("whiteout"):
            return ENOENT, {}
        src = next(iter(chosen))
        for shard, _payload, at in candidates:
            if shard == src and self._oi_version(at) == version:
                return 0, {k[len(USER_ATTR_PREFIX):]: v
                           for k, v in at.items()
                           if k.startswith(USER_ATTR_PREFIX)}
        return 0, {}

    async def _op_omap_write(self, state: PGState, pool, oid: str,
                             kind: str, payload: bytes,
                             admit_epoch: Optional[int],
                             snapc=None) -> int:
        """omap set/rm — REPLICATED pools only, like the reference
        (EC pools reject omap: PrimaryLogPG EOPNOTSUPP).  Honors the
        write snap context like data writes do (make_writeable clones
        before ANY mutation, omap included — the store-level clone op
        copies omap, so snap reads of the clone see the old keys)."""
        if pool.type == TYPE_ERASURE:
            return -95  # EOPNOTSUPP
        async with state.obj_lock(oid):
            await self._wait_for_degraded(state, pool, oid)
            oi, ss = await self._head_info(state, pool, oid)
            clone_ops: List[ShardOp] = []
            ss_raw: Optional[bytes] = None
            if snapc is not None:
                clone_ops, ss_raw = await self._snap_clone_prep(
                    state, pool, oid, snapc[0], snapc[1],
                    head=(oi, ss))
            size = oi.get("size", 0) \
                if oi is not None and not oi.get("whiteout") else 0
            entry = self._next_entry(state, pool, oid, "modify", size)
            oi_raw = json.dumps({"size": size,
                                 "version": entry["version"]}).encode()
            ops = [ShardOp("create"),
                   ShardOp(kind, data=payload),
                   ShardOp("setattr", name=OI_ATTR, value=oi_raw)]
            shard_ops = {-1: ops}
            self._apply_snap_ops(shard_ops, clone_ops, ss_raw)
            return await self._submit_shard_writes(state, pool, oid,
                                                   shard_ops, entry,
                                                   admit_epoch)

    async def _op_omap_get(self, state: PGState, pool, oid: str
                           ) -> Tuple[int, bytes]:
        if pool.type == TYPE_ERASURE:
            return -95, b""
        # existence/whiteout gate first: stores differ on whether a
        # never-created object's omap read errors, and a
        # snapshot-deleted (whiteout) head must read as gone
        oi, _ss = await self._head_info(state, pool, oid)
        if oi is None or oi.get("whiteout"):
            return ENOENT, b""
        # omap is identical on every replica; serve locally when clean,
        # else from any up replica via a want_omap sub-read
        if self._pg_is_clean(state, pool, oid):
            cid = self._cid(state.pg, -1)
            try:
                omap = self.store.omap_get(cid, ObjectId(oid))
            except (KeyError, IOError):
                return ENOENT, b""
            return 0, _encode_kv_map(omap)
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE or not self.osdmap.is_up(osd) \
                    or osd == self.osd_id:
                continue
            tid = self._next_tid()
            reply = await self._request(
                osd, MOSDSubRead(tid, state.pg, -1, oid, 0, 1,
                                 want_omap=True), tid)
            if reply is not None and reply.rc == 0:
                return 0, _encode_kv_map(reply.omap)
        return EAGAIN, b""

    # -- watch / notify (linger op surface, Objecter linger role) ----------

    def _op_watch(self, state: PGState, pool, msg: MOSDOp,
                  conn: Optional[Connection], cookie: int,
                  unwatch: bool) -> int:
        """Register/unregister this connection as a watcher of the
        object.  Watch state is primary-local and in-memory — clients
        re-register on map changes (the Objecter linger resend role)."""
        key = (pool.id, msg.oid)
        table = self.watchers.setdefault(key, {})
        if unwatch:
            table.pop((msg.client, cookie), None)
            if not table:
                self.watchers.pop(key, None)
            return 0
        if conn is None:
            return EINVAL
        table[(msg.client, cookie)] = conn
        return 0

    async def _op_call(self, state: PGState, pool, oid: str,
                       cls: str, method: str, data: bytes,
                       admit_epoch: int, snapc,
                       read_only: bool = False) -> Tuple[int, bytes]:
        """`exec` op: run a registered object-class method
        (ClassHandler::ClassMethod::exec, PrimaryLogPG::do_osd_ops
        CEPH_OSD_OP_CALL).  Concurrent calls on one object serialize
        on a per-object cls lock, so read-modify-write methods
        (numops, lock) are atomic against each other; each inner op
        additionally takes the normal object lock on its own."""
        from ceph_tpu.cls import ClsError, MethodContext

        # method input stays the wire decode's zero-copy view: class
        # methods parse through cls.as_text (str() decodes any
        # buffer) or take bytes() themselves where they genuinely
        # need to own the payload
        entry = self.class_handler.lookup(cls, method)
        if entry is None:
            return EINVAL, b""
        fn, flags = entry
        from ceph_tpu.cls import WR as CLS_WR

        if read_only and flags & CLS_WR:
            # a WR method at a snap would mutate the immutable clone
            # the read resolved to (the reference's -EROFS for writes
            # at a non-head snapid)
            return -30, b""  # EROFS
        ctx = MethodContext(self, state, pool, oid, admit_epoch,
                            snapc, flags)
        async with state.obj_lock(f"_cls_\x00{oid}"):
            try:
                return 0, await fn(ctx, data)
            except ClsError as e:
                return e.rc, b""
            except UnfoundObject:
                raise
            except Exception:
                log.exception("osd.%d: cls %s.%s on %r failed",
                              self.osd_id, cls, method, oid)
                return EIO, b""

    async def _op_notify(self, state: PGState, pool, oid: str,
                         payload: bytes
                         ) -> Tuple[int, Dict[str, Any]]:
        """Fan the notify out to every live watcher and wait for acks
        (watch_notify timeout discipline)."""
        key = (pool.id, oid)
        table = dict(self.watchers.get(key, {}))
        live = {k: c for k, c in table.items() if not c.closed}
        self._notify_seq += 1
        notify_id = self._notify_seq
        if not live:
            return 0, {"acked": [], "missed": []}
        event = asyncio.Event()
        pending = {"want": set(live), "acks": set(), "event": event}
        self._pending_notifies[notify_id] = pending
        try:
            for (client, cookie), wconn in live.items():
                try:
                    await wconn.send(MWatchNotify(
                        notify_id, pool.id, oid, payload, cookie))
                except (ConnectionError, OSError):
                    pending["want"].discard((client, cookie))
            # acks may have landed during the sends (each send is a
            # yield point), and failed sends shrink the want set — only
            # wait if someone is still outstanding
            if pending["want"] - pending["acks"]:
                try:
                    await asyncio.wait_for(
                        event.wait(),
                        float(self.config.get("osd_notify_timeout",
                                              5.0)))
                except asyncio.TimeoutError:
                    pass
            # watchers are identified by (client, cookie): cookies are
            # per-client counters and collide across clients
            acked = sorted([cl, c] for cl, c in pending["acks"])
            missed = sorted([cl, c] for cl, c in
                            pending["want"] - pending["acks"])
            return 0, {"acked": acked, "missed": missed}
        finally:
            self._pending_notifies.pop(notify_id, None)

    def _handle_notify_ack(self, conn: Connection,
                           msg: MWatchNotifyAck) -> None:
        pending = self._pending_notifies.get(msg.notify_id)
        if pending is None:
            return
        for who in list(pending["want"]):
            if who[1] == msg.cookie and \
                    who[0] == conn.peer_name:
                pending["acks"].add(who)
        if pending["acks"] >= pending["want"]:
            pending["event"].set()

    def _op_pgls(self, state: PGState, pool
                 ) -> Tuple[int, Dict[str, Any]]:
        shard = state.my_shard(self.osd_id, pool.type)
        cid = self._cid(state.pg, shard)
        names = []
        try:
            for o in self.store.list_objects(cid):
                name = str(o)
                if name == PGMETA_OID or is_internal_name(name):
                    continue
                try:  # whiteouts (deleted heads kept for snaps) hidden
                    oi = json.loads(self.store.getattr(
                        cid, o, OI_ATTR))
                    if oi.get("whiteout"):
                        continue
                except (KeyError, ValueError):
                    pass
                names.append(name)
        except KeyError:
            pass
        return 0, {"objects": sorted(names)}

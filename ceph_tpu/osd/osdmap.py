"""OSDMap: the epoch-versioned cluster map.

Reference parity: OSDMap (/root/reference/src/osd/OSDMap.{h,cc}) and
pg_pool_t (src/osd/osd_types.{h,cc}):

- pools carry type (replicated/erasure), size/min_size, pg_num/pgp_num
  with the stable-mod masks, crush rule, and an erasure-code-profile name;
  EC profiles are cluster data stored in the map (SURVEY.md §5.6);
- placement: raw_pg_to_pps (hashpspool mixing, osd_types.cc:1793) ->
  crush do_rule with the in/out weight vector (OSDMap.cc:2436-2454) ->
  upmap overrides -> up filtering (shift for replicated, NONE-holes for
  EC) -> primary affinity -> pg_temp/primary_temp overrides
  (_pg_to_up_acting_osds, OSDMap.cc:2668);
- Incremental: per-epoch deltas (new_state is XOR), applied in order;
- OSDMapMapping: whole-map bulk placement — here the pps of every PG in a
  pool feed one vmapped straw2 TPU dispatch (the ParallelPGMapper role,
  src/osd/OSDMapMapping.h:18,173).
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.crush.map import CRUSH_ITEM_NONE, CrushMap
from ceph_tpu.crush import mapper as crush_mapper
from ceph_tpu.ops import rjenkins

log = logging.getLogger("ceph_tpu.osdmap")

# osd state bits (ceph_osd_state)
CEPH_OSD_EXISTS = 1
CEPH_OSD_UP = 2
CEPH_OSD_DESTROYED = 4

CEPH_OSD_IN = 0x10000
CEPH_OSD_OUT = 0
CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000

# pool types (pg_pool_t)
TYPE_REPLICATED = 1
TYPE_ERASURE = 3

# pg_pool_t flags
FLAG_HASHPSPOOL = 1 << 2

# cluster flags (OSDMap CEPH_OSDMAP_*)
FLAG_NAMES = {
    "pauserd": 1 << 0, "pausewr": 1 << 1, "noup": 1 << 5,
    "nodown": 1 << 6, "noout": 1 << 7, "noin": 1 << 8,
    "nobackfill": 1 << 9, "norebalance": 1 << 18, "norecover": 1 << 10,
    "noscrub": 1 << 11, "nodeep-scrub": 1 << 12,
}


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo for smooth pg_num growth (include/ceph_hash-adjacent)."""
    return x & bmask if (x & bmask) < b else x & (bmask >> 1)


def _calc_mask(n: int) -> int:
    return (1 << max(n - 1, 1).bit_length()) - 1


@dataclass(frozen=True)
class PgId:
    """pg_t: (pool, seed)."""

    pool: int
    ps: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.ps:x}"

    @staticmethod
    def parse(s: str) -> "PgId":
        pool_s, ps_s = s.split(".")
        return PgId(int(pool_s), int(ps_s, 16))


class PgPool:
    def __init__(self, pool_id: int, name: str,
                 type_: int = TYPE_REPLICATED, size: int = 3,
                 min_size: int = 0, pg_num: int = 32,
                 crush_rule: int = 0, erasure_code_profile: str = "",
                 flags: int = FLAG_HASHPSPOOL):
        self.id = pool_id
        self.name = name
        self.type = type_
        self.size = size
        # reference defaults: replicated size - size/2; EC pools get k+1
        # from the profile at creation (OSDMap.create_pool does that)
        self.min_size = min_size or max(size - size // 2, 1)
        self.pg_num = pg_num
        self.pgp_num = pg_num
        self.crush_rule = crush_rule
        self.erasure_code_profile = erasure_code_profile
        self.flags = flags
        self.opts: Dict[str, object] = {}  # pool_opts_t (csum/compression)
        self.last_change = 0
        # self-managed snapshots (pg_pool_t snap_seq / removed_snaps):
        # snap ids are allocated by the mon from snap_seq; removed ids
        # accumulate until every OSD has trimmed them
        self.snap_seq = 0
        self.removed_snaps: List[int] = []

    @property
    def pg_num_mask(self) -> int:
        return _calc_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _calc_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        return self.type == TYPE_REPLICATED

    def is_erasure(self) -> bool:
        return self.type == TYPE_ERASURE

    def raw_pg_to_pg(self, pg: PgId) -> PgId:
        return PgId(pg.pool,
                    ceph_stable_mod(pg.ps, self.pg_num, self.pg_num_mask))

    def raw_pg_to_pps(self, pg: PgId) -> int:
        if self.flags & FLAG_HASHPSPOOL:
            return int(rjenkins.hash32_2(
                ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask),
                pg.pool))
        return ceph_stable_mod(
            pg.ps, self.pgp_num, self.pgp_num_mask) + pg.pool

    # -- encoding ----------------------------------------------------------

    def encode(self, enc: Encoder) -> None:
        # v2 changes the meaning of opts values (str -> JSON), so compat
        # is 2 as well: a v1-only decoder must reject, not misread.
        # v3 appends snap_seq/removed_snaps (readable by v2 logic? no —
        # appended fields are version-gated below, compat stays 2).
        enc.start(3, 2)
        enc.s64(self.id)
        enc.string(self.name)
        enc.u8(self.type)
        enc.u32(self.size)
        enc.u32(self.min_size)
        enc.u32(self.pg_num)
        enc.u32(self.pgp_num)
        enc.s32(self.crush_rule)
        enc.string(self.erasure_code_profile)
        enc.u64(self.flags)
        enc.u32(self.last_change)
        # JSON-encode opt values so typed pool opts (ints/floats for
        # csum/compression settings) survive an encode/decode round-trip
        enc.map(self.opts, Encoder.string,
                lambda e, v: e.string(json.dumps(v)))
        enc.u64(self.snap_seq)
        enc.list(self.removed_snaps, Encoder.u64)
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "PgPool":
        struct_v = dec.start(3)
        pool = cls(dec.s64(), dec.string())
        pool.type = dec.u8()
        pool.size = dec.u32()
        pool.min_size = dec.u32()
        pool.pg_num = dec.u32()
        pool.pgp_num = dec.u32()
        pool.crush_rule = dec.s32()
        pool.erasure_code_profile = dec.string()
        pool.flags = dec.u64()
        pool.last_change = dec.u32()
        raw_opts = dec.map(Decoder.string, Decoder.string)
        if struct_v >= 2:
            pool.opts = {k: json.loads(v) for k, v in raw_opts.items()}
        else:  # v1 encoded opts as bare str(v); values stay strings
            pool.opts = raw_opts
        if struct_v >= 3:
            pool.snap_seq = dec.u64()
            pool.removed_snaps = dec.list(Decoder.u64)
        dec.finish()
        return pool


class OSDMap:
    def __init__(self) -> None:
        self.epoch = 0
        self.fsid = ""
        self.max_osd = 0
        self.osd_state: List[int] = []
        self.osd_weight: List[int] = []          # 16.16 in/out weight
        self.osd_addrs: Dict[int, str] = {}
        self.osd_primary_affinity: Optional[List[int]] = None
        self.pools: Dict[int, PgPool] = {}
        self.crush = CrushMap()
        self.erasure_code_profiles: Dict[str, Dict[str, str]] = {}
        self.flags = 0
        self.pg_temp: Dict[PgId, List[int]] = {}
        self.primary_temp: Dict[PgId, int] = {}
        self.pg_upmap: Dict[PgId, List[int]] = {}
        self.pg_upmap_items: Dict[PgId, List[Tuple[int, int]]] = {}
        self.pool_max = 0  # monotone pool-id counter; ids never reused
        # placement memo (the OSDMapMapping precompute role,
        # /root/reference/src/osd/OSDMapMapping.h:18 — the reference
        # caches every PG's mapping per epoch).  OPT-IN: off on a raw
        # map (tests and tools freely poke osd_state/pg_temp between
        # queries); daemons and clients that mutate their map ONLY
        # through apply_incremental / whole-map install set
        # enable_placement_cache() after each map change.  Entries
        # key on (epoch, pg) and the store resets on epoch change.
        self._cache_placement = False
        self._pcache: Dict[PgId, Tuple] = {}
        self._pcache_epoch = -1

    def enable_placement_cache(self) -> None:
        """Owner promises mutation-through-incrementals (or whole-map
        install) from here on — daemons/clients call this after every
        map change; raw maps in tools/tests stay uncached so direct
        state surgery between queries stays safe."""
        self._cache_placement = True

    def _invalidate_placement(self) -> None:
        self._pcache.clear()
        self._pcache_epoch = self.epoch

    # -- osd state ---------------------------------------------------------

    def set_max_osd(self, n: int) -> None:
        self._invalidate_placement()
        self.max_osd = n
        while len(self.osd_state) < n:
            self.osd_state.append(0)
            self.osd_weight.append(CEPH_OSD_OUT)

    def exists(self, osd: int) -> bool:
        return (0 <= osd < self.max_osd
                and self.osd_state[osd] & CEPH_OSD_EXISTS != 0)

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_state[osd] & CEPH_OSD_UP != 0

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_destroyed(self, osd: int) -> bool:
        """Data declared permanently gone (`osd lost` / destroy —
        OSDMap.h is_destroyed): probes may treat this OSD as
        definitively absent rather than merely unreachable."""
        return (self.exists(osd)
                and self.osd_state[osd] & CEPH_OSD_DESTROYED != 0)

    def is_in(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_weight[osd] > 0

    def is_out(self, osd: int) -> bool:
        return not self.is_in(osd)

    def get_weight(self, osd: int) -> int:
        return self.osd_weight[osd]

    def get_up_osds(self) -> List[int]:
        return [o for o in range(self.max_osd) if self.is_up(o)]

    def get_primary_affinity(self, osd: int) -> int:
        if self.osd_primary_affinity is None:
            return CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
        return self.osd_primary_affinity[osd]

    def test_flag(self, name: str) -> bool:
        return bool(self.flags & FLAG_NAMES[name])

    # -- pools ---------------------------------------------------------------

    def lookup_pool(self, name: str) -> int:
        for pid, pool in self.pools.items():
            if pool.name == name:
                return pid
        return -1

    def get_pg_pool(self, pool_id: int) -> Optional[PgPool]:
        return self.pools.get(pool_id)

    # -- placement (OSDMap.cc:2436-2750) -------------------------------------

    def _find_rule(self, pool: PgPool) -> int:
        return (pool.crush_rule
                if 0 <= pool.crush_rule < len(self.crush.rules) else -1)

    def _pg_to_raw_osds(self, pool: PgPool, pg: PgId
                        ) -> Tuple[List[int], int]:
        pps = pool.raw_pg_to_pps(pg)
        ruleno = self._find_rule(pool)
        raw: List[int] = []
        if ruleno >= 0:
            raw = list(crush_mapper.crush_do_rule(
                self.crush, ruleno, pps, pool.size, self.osd_weight,
                self.crush.choose_args or None))
        self._remove_nonexistent(pool, raw)
        return raw, pps

    def _remove_nonexistent(self, pool: PgPool, osds: List[int]) -> None:
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            osds[:] = [o if self.exists(o) else CRUSH_ITEM_NONE
                       for o in osds]

    def _apply_upmap(self, pool: PgPool, raw_pg: PgId,
                     raw: List[int]) -> None:
        pg = pool.raw_pg_to_pg(raw_pg)
        explicit = self.pg_upmap.get(pg)
        if explicit is not None:
            if any(o != CRUSH_ITEM_NONE and 0 <= o < self.max_osd
                   and self.osd_weight[o] == 0 for o in explicit):
                # a marked-out target rejects the whole explicit mapping
                # AND short-circuits pg_upmap_items
                # (OSDMap::_apply_upmap early return, OSDMap.cc:2466-2476)
                return
            raw[:] = list(explicit)
            # applied mapping falls through to pg_upmap_items
            # (OSDMap.cc:2478-2481 "continue to check and apply")
        for src, dst in self.pg_upmap_items.get(pg, []):
            exists = False
            pos = -1
            for i, osd in enumerate(raw):
                if osd == dst:
                    exists = True
                    break
                if osd == src and pos < 0 and not (
                        dst != CRUSH_ITEM_NONE and 0 <= dst < self.max_osd
                        and self.osd_weight[dst] == 0):
                    pos = i
            if not exists and pos >= 0:
                raw[pos] = dst

    def _raw_to_up(self, pool: PgPool, raw: List[int]) -> List[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and self.is_up(o)]
        return [o if (o != CRUSH_ITEM_NONE and self.exists(o)
                      and self.is_up(o)) else CRUSH_ITEM_NONE
                for o in raw]

    @staticmethod
    def _pick_primary(osds: List[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, seed: int, pool: PgPool,
                                osds: List[int], primary: int
                                ) -> Tuple[List[int], int]:
        pa = self.osd_primary_affinity
        if pa is None:
            return osds, primary
        if all(o == CRUSH_ITEM_NONE
               or pa[o] == CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
               for o in osds):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = pa[o]
            if a < CEPH_OSD_MAX_PRIMARY_AFFINITY and (
                    int(rjenkins.hash32_2(seed, o)) >> 16) >= a:
                if pos < 0:
                    pos = i  # fallback; keep looking
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [osds[pos]] + osds[:pos] + osds[pos + 1:]
        return osds, primary

    def _get_temp_osds(self, pool: PgPool, raw_pg: PgId
                       ) -> Tuple[List[int], int]:
        pg = pool.raw_pg_to_pg(raw_pg)
        temp: List[int] = []
        for o in self.pg_temp.get(pg, []):
            if not self.exists(o) or self.is_down(o):
                if not pool.can_shift_osds():
                    temp.append(CRUSH_ITEM_NONE)
            else:
                temp.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp:
            temp_primary = self._pick_primary(temp)
        return temp, temp_primary

    def pg_to_up_acting_osds(self, pg: PgId
                             ) -> Tuple[List[int], int, List[int], int]:
        """-> (up, up_primary, acting, acting_primary)."""
        if not self._cache_placement:
            return self._pg_to_up_acting_uncached(pg)
        if self._pcache_epoch != self.epoch:
            self._invalidate_placement()
        hit = self._pcache.get(pg)
        if hit is not None:
            up, upp, acting, actp = hit
            return list(up), upp, list(acting), actp
        out = self._pg_to_up_acting_uncached(pg)
        self._pcache[pg] = (tuple(out[0]), out[1], tuple(out[2]), out[3])
        return out

    def _pg_to_up_acting_uncached(self, pg: PgId
                                  ) -> Tuple[List[int], int, List[int], int]:
        pool = self.get_pg_pool(pg.pool)
        if pool is None or pg.ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def pg_to_acting_osds(self, pg: PgId) -> Tuple[List[int], int]:
        _up, _upp, acting, primary = self.pg_to_up_acting_osds(pg)
        return acting, primary

    # -- map building ------------------------------------------------------

    @classmethod
    def build_simple(cls, num_osds: int, osds_per_host: int = 4,
                     epoch: int = 1, fsid: str = "tpu-fsid") -> "OSDMap":
        from ceph_tpu.crush.map import build_flat_cluster

        m = cls()
        m.epoch = epoch
        m.fsid = fsid
        m.crush = build_flat_cluster(num_osds, osds_per_host=osds_per_host)
        m.set_max_osd(num_osds)
        for o in range(num_osds):
            m.osd_state[o] = CEPH_OSD_EXISTS | CEPH_OSD_UP
            m.osd_weight[o] = CEPH_OSD_IN
        if not m.crush.rules:
            m.crush.add_simple_rule("replicated_rule", "default", "host")
        return m

    def create_pool(self, name: str, type_: int = TYPE_REPLICATED,
                    size: int = 3, pg_num: int = 32, crush_rule: int = 0,
                    erasure_code_profile: str = "") -> PgPool:
        pool_id = max(self.pool_max, max(self.pools, default=0)) + 1
        self.pool_max = pool_id
        min_size = 0
        if type_ == TYPE_ERASURE:
            profile = self.erasure_code_profiles.get(
                erasure_code_profile, {})
            min_size = int(profile.get("k", max(size - 1, 1))) + 1
        pool = PgPool(pool_id, name, type_=type_, size=size,
                      min_size=min_size, pg_num=pg_num,
                      crush_rule=crush_rule,
                      erasure_code_profile=erasure_code_profile)
        pool.last_change = self.epoch
        self.pools[pool_id] = pool
        self._invalidate_placement()
        return pool

    # -- incrementals (OSDMap::Incremental) --------------------------------

    def apply_incremental(self, inc: "Incremental") -> None:
        assert inc.epoch == self.epoch + 1, \
            f"incremental {inc.epoch} does not follow {self.epoch}"
        self.epoch = inc.epoch
        if inc.new_max_osd is not None:
            self.set_max_osd(inc.new_max_osd)
        if inc.new_flags is not None:
            self.flags = inc.new_flags
        for name, profile in inc.new_erasure_code_profiles.items():
            self.erasure_code_profiles[name] = dict(profile)
        for name in inc.old_erasure_code_profiles:
            self.erasure_code_profiles.pop(name, None)
        for pool_id, pool in inc.new_pools.items():
            self.pools[pool_id] = pool
        for pool_id in inc.old_pools:
            self.pools.pop(pool_id, None)
            for d in (self.pg_temp, self.primary_temp, self.pg_upmap,
                      self.pg_upmap_items):
                for pg in [pg for pg in d if pg.pool == pool_id]:
                    del d[pg]
        for osd, addr in inc.new_up_osds.items():
            self.osd_state[osd] |= CEPH_OSD_EXISTS | CEPH_OSD_UP
            self.osd_addrs[osd] = addr
        for osd, xor_bits in inc.new_state.items():
            self.osd_state[osd] ^= xor_bits
        for osd, weight in inc.new_weight.items():
            self.osd_state[osd] |= CEPH_OSD_EXISTS
            self.osd_weight[osd] = weight
        for pg, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pg] = list(osds)
            else:
                self.pg_temp.pop(pg, None)
        for pg, primary in inc.new_primary_temp.items():
            if primary >= 0:
                self.primary_temp[pg] = primary
            else:
                self.primary_temp.pop(pg, None)
        for pg, osds in inc.new_pg_upmap.items():
            self.pg_upmap[pg] = list(osds)
        for pg in inc.old_pg_upmap:
            self.pg_upmap.pop(pg, None)
        for pg, items in inc.new_pg_upmap_items.items():
            self.pg_upmap_items[pg] = list(items)
        for pg in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pg, None)
        if inc.new_crush is not None:
            self.crush = inc.new_crush

    # -- encoding -----------------------------------------------------------

    def encode(self) -> bytes:
        import json as _json

        from ceph_tpu.crush.serialize import to_json

        enc = Encoder()
        enc.start(1, 1)
        enc.u32(self.epoch)
        enc.string(self.fsid)
        enc.u32(self.max_osd)
        enc.list(self.osd_state, Encoder.u32)
        enc.list(self.osd_weight, Encoder.u32)
        enc.map(self.osd_addrs, Encoder.s32, Encoder.string)
        enc.optional(self.osd_primary_affinity,
                     lambda e, v: e.list(v, Encoder.u32))
        enc.u32(len(self.pools))
        for pool in self.pools.values():
            pool.encode(enc)
        enc.map(self.erasure_code_profiles, Encoder.string,
                lambda e, p: e.map(p, Encoder.string, Encoder.string))
        enc.u64(self.flags)
        enc.map(self.pg_temp, _enc_pg,
                lambda e, v: e.list(v, Encoder.s32))
        enc.map(self.primary_temp, _enc_pg, Encoder.s32)
        enc.map(self.pg_upmap, _enc_pg,
                lambda e, v: e.list(v, Encoder.s32))
        enc.map(self.pg_upmap_items, _enc_pg,
                lambda e, v: e.list(
                    v, lambda e2, p: (e2.s32(p[0]), e2.s32(p[1]))))
        enc.bytes(_json.dumps(to_json(self.crush)).encode())
        enc.finish()
        return enc.to_bytes()

    @classmethod
    def decode(cls, data: bytes) -> "OSDMap":
        import json as _json

        from ceph_tpu.crush.serialize import from_json

        dec = Decoder(data)
        dec.start(1)
        m = cls()
        m.epoch = dec.u32()
        m.fsid = dec.string()
        max_osd = dec.u32()
        m.osd_state = dec.list(Decoder.u32)
        m.osd_weight = dec.list(Decoder.u32)
        m.max_osd = max_osd
        m.osd_addrs = dec.map(Decoder.s32, Decoder.string)
        m.osd_primary_affinity = dec.optional(
            lambda d: d.list(Decoder.u32))
        n_pools = dec.u32()
        for _ in range(n_pools):
            pool = PgPool.decode(dec)
            m.pools[pool.id] = pool
        m.erasure_code_profiles = dec.map(
            Decoder.string,
            lambda d: d.map(Decoder.string, Decoder.string))
        m.flags = dec.u64()
        m.pg_temp = dec.map(_dec_pg, lambda d: d.list(Decoder.s32))
        m.primary_temp = dec.map(_dec_pg, Decoder.s32)
        m.pg_upmap = dec.map(_dec_pg, lambda d: d.list(Decoder.s32))
        m.pg_upmap_items = dec.map(
            _dec_pg, lambda d: d.list(lambda d2: (d2.s32(), d2.s32())))
        m.crush = from_json(_json.loads(dec.bytes()))
        dec.finish()
        return m


def _enc_pg(enc: Encoder, pg: PgId) -> None:
    enc.s64(pg.pool)
    enc.u32(pg.ps)


def _dec_pg(dec: Decoder) -> PgId:
    return PgId(dec.s64(), dec.u32())


@dataclass
class Incremental:
    epoch: int
    new_max_osd: Optional[int] = None
    new_flags: Optional[int] = None
    new_pools: Dict[int, PgPool] = field(default_factory=dict)
    old_pools: List[int] = field(default_factory=list)
    new_erasure_code_profiles: Dict[str, Dict[str, str]] = field(
        default_factory=dict)
    old_erasure_code_profiles: List[str] = field(default_factory=list)
    new_up_osds: Dict[int, str] = field(default_factory=dict)
    new_state: Dict[int, int] = field(default_factory=dict)   # XOR bits
    new_weight: Dict[int, int] = field(default_factory=dict)
    new_pg_temp: Dict[PgId, List[int]] = field(default_factory=dict)
    new_primary_temp: Dict[PgId, int] = field(default_factory=dict)
    new_pg_upmap: Dict[PgId, List[int]] = field(default_factory=dict)
    old_pg_upmap: List[PgId] = field(default_factory=list)
    new_pg_upmap_items: Dict[PgId, List[Tuple[int, int]]] = field(
        default_factory=dict)
    old_pg_upmap_items: List[PgId] = field(default_factory=list)
    new_crush: Optional[CrushMap] = None

    def encode(self) -> bytes:
        """Wire codec (OSDMap::Incremental encode role) — lets the mon
        keep an incremental log and daemons replay the map stream epoch
        by epoch (interval detection depends on seeing EVERY epoch)."""
        import json as _json

        from ceph_tpu.crush.serialize import to_json

        enc = Encoder()
        enc.start(1, 1)
        enc.u32(self.epoch)
        enc.optional(self.new_max_osd, Encoder.u32)
        enc.optional(self.new_flags, Encoder.u64)
        enc.u32(len(self.new_pools))
        for pool in self.new_pools.values():
            pool.encode(enc)
        enc.list(self.old_pools, Encoder.s64)
        enc.map(self.new_erasure_code_profiles, Encoder.string,
                lambda e, p: e.map(p, Encoder.string, Encoder.string))
        enc.list(self.old_erasure_code_profiles, Encoder.string)
        enc.map(self.new_up_osds, Encoder.s32, Encoder.string)
        enc.map(self.new_state, Encoder.s32, Encoder.u32)
        enc.map(self.new_weight, Encoder.s32, Encoder.u32)
        enc.map(self.new_pg_temp, _enc_pg,
                lambda e, v: e.list(v, Encoder.s32))
        enc.map(self.new_primary_temp, _enc_pg, Encoder.s32)
        enc.map(self.new_pg_upmap, _enc_pg,
                lambda e, v: e.list(v, Encoder.s32))
        enc.list(self.old_pg_upmap, _enc_pg)
        enc.map(self.new_pg_upmap_items, _enc_pg,
                lambda e, v: e.list(
                    v, lambda e2, p: (e2.s32(p[0]), e2.s32(p[1]))))
        enc.list(self.old_pg_upmap_items, _enc_pg)
        enc.optional(self.new_crush,
                     lambda e, c: e.bytes(
                         _json.dumps(to_json(c)).encode()))
        enc.finish()
        return enc.to_bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Incremental":
        import json as _json

        from ceph_tpu.crush.serialize import from_json

        dec = Decoder(data)
        dec.start(1)
        inc = cls(epoch=dec.u32())
        inc.new_max_osd = dec.optional(Decoder.u32)
        inc.new_flags = dec.optional(Decoder.u64)
        for _ in range(dec.u32()):
            pool = PgPool.decode(dec)
            inc.new_pools[pool.id] = pool
        inc.old_pools = dec.list(Decoder.s64)
        inc.new_erasure_code_profiles = dec.map(
            Decoder.string,
            lambda d: d.map(Decoder.string, Decoder.string))
        inc.old_erasure_code_profiles = dec.list(Decoder.string)
        inc.new_up_osds = dec.map(Decoder.s32, Decoder.string)
        inc.new_state = dec.map(Decoder.s32, Decoder.u32)
        inc.new_weight = dec.map(Decoder.s32, Decoder.u32)
        inc.new_pg_temp = dec.map(_dec_pg,
                                  lambda d: d.list(Decoder.s32))
        inc.new_primary_temp = dec.map(_dec_pg, Decoder.s32)
        inc.new_pg_upmap = dec.map(_dec_pg,
                                   lambda d: d.list(Decoder.s32))
        inc.old_pg_upmap = dec.list(_dec_pg)
        inc.new_pg_upmap_items = dec.map(
            _dec_pg, lambda d: d.list(lambda d2: (d2.s32(), d2.s32())))
        inc.old_pg_upmap_items = dec.list(_dec_pg)
        raw = dec.optional(Decoder.bytes)
        if raw is not None:
            inc.new_crush = from_json(_json.loads(raw))
        dec.finish()
        return inc


class OSDMapMapping:
    """Bulk whole-map placement (OSDMapMapping + ParallelPGMapper).

    Where the reference shards PGs over a thread pool, the TPU build feeds
    every PG's pps of a pool through one vmapped straw2 dispatch.
    """

    def __init__(self, osdmap: OSDMap, use_tpu: bool = True):
        self._map = osdmap
        self._by_pool: Dict[int, List[Tuple[List[int], int, List[int], int]]] = {}
        self._update(use_tpu)

    def _update(self, use_tpu: bool) -> None:
        from ceph_tpu.common import circuit
        from ceph_tpu.ops import gf

        m = self._map
        device_ok = use_tpu and gf.backend_available() \
            and not m.crush.choose_args \
            and not circuit.degraded("crush-batch")
        # compile probe hoisted out of the per-pool walk: each
        # (ruleno, result_max) compiles at most once per update, an
        # unsupported ruleno is remembered so sibling pools skip the
        # probe entirely, and the pools that fell back to the scalar
        # mapper are logged instead of silently pinned
        compiled: Dict[Tuple[int, int], Optional[object]] = {}
        unsupported_rules: set = set()
        fallback_pools: List[int] = []
        for pool_id, pool in m.pools.items():
            entries = []
            raw_rows: Optional[np.ndarray] = None
            ruleno = m._find_rule(pool)
            pps = np.array(
                [pool.raw_pg_to_pps(PgId(pool_id, ps))
                 for ps in range(pool.pg_num)], dtype=np.int64)
            if device_ok and ruleno >= 0 and \
                    ruleno not in unsupported_rules:
                key = (ruleno, pool.size)
                if key not in compiled:
                    try:
                        from ceph_tpu.crush import kernel as ck

                        compiled[key] = ck.compile_rule(
                            m.crush, ruleno, result_max=pool.size,
                            weight=m.osd_weight)
                    except NotImplementedError:
                        compiled[key] = None
                        unsupported_rules.add(ruleno)
                run = compiled[key]
                if run is not None:
                    # guarded vmapped straw2 dispatch: a wedged or
                    # faulting device degrades THIS pool to the
                    # scalar mapper below (identical placement, more
                    # host time) instead of failing the map update
                    status, rows = circuit.device_call(
                        "crush-batch",
                        lambda: np.asarray(run(pps)),
                        batch=len(pps),
                        label=f"crush r{ruleno}", oom_to_fail=True,
                        benign=(NotImplementedError,))
                    raw_rows = rows if status == "ok" else None
            if raw_rows is None and device_ok and ruleno >= 0:
                fallback_pools.append(pool_id)
            for ps in range(pool.pg_num):
                pg = PgId(pool_id, ps)
                if raw_rows is not None:
                    raw = [int(v) for v in raw_rows[ps]]
                    m._remove_nonexistent(pool, raw)
                    m._apply_upmap(pool, pg, raw)
                    up = m._raw_to_up(pool, raw)
                    up_primary = m._pick_primary(up)
                    up, up_primary = m._apply_primary_affinity(
                        int(pps[ps]), pool, up, up_primary)
                    acting, acting_primary = m._get_temp_osds(pool, pg)
                    if not acting:
                        acting = list(up)
                        if acting_primary == -1:
                            acting_primary = up_primary
                    entries.append((up, up_primary, acting, acting_primary))
                else:
                    entries.append(m.pg_to_up_acting_osds(pg))
            self._by_pool[pool_id] = entries
        if fallback_pools:
            log.info(
                "OSDMapMapping: pools %s fell back to the scalar"
                " mapper (CRUSH rule unsupported by the vectorized"
                " kernel)", fallback_pools)

    def get(self, pg: PgId) -> Tuple[List[int], int, List[int], int]:
        return self._by_pool[pg.pool][pg.ps]

    def pgs_by_osd(self) -> Dict[int, List[PgId]]:
        out: Dict[int, List[PgId]] = {}
        for pool_id, entries in self._by_pool.items():
            for ps, (up, _upp, _acting, _ap) in enumerate(entries):
                for o in up:
                    if o != CRUSH_ITEM_NONE:
                        out.setdefault(o, []).append(PgId(pool_id, ps))
        return out

"""Per-PG op log: crash consistency + divergence repair.

Reference parity: PGLog (/root/reference/src/osd/PGLog.h) — the per-PG
replicated journal that lets a crashed/partitioned shard rejoin: the
primary elects the authoritative log (max last_update — GetLog,
PeeringState.h:249), peers merge it (`merge_log` PGLog.h:1247), entries
the authoritative log does not contain are divergent and rewound
(`rewind_divergent_log` PGLog.h:1241 — here: the touched object is
marked missing and recovered to the authoritative state), and objects
written past a peer's last_update form its missing set, driving
log-based recovery.  A peer whose last_update predates the log tail
cannot be caught up by log replay and needs backfill (whole-PG scan).

Design: entries are JSON-friendly dicts (they ride MPGLogMsg / sub-op
messages); the log and pg info persist in the pgmeta object's omap of
the shard's collection, committed in the SAME ObjectStore transaction as
the data mutation they journal — the store's transactional atomicity
gives the log its WAL semantics.

eversion_t = (epoch, version), ordered lexicographically.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.os import ObjectId, Transaction

PGMETA_OID = "_pgmeta_"
K_INFO = "info"
K_LOG = "log"
K_MISSING = "missing"

Ever = Tuple[int, int]


def ev(v) -> Ever:
    """Coerce a wire-form [epoch, version] to a comparable tuple."""
    return (int(v[0]), int(v[1]))


ZERO: Ever = (0, 0)


def make_entry(version: Ever, prior: Ever, oid: str, op: str,
               size: int = 0) -> Dict[str, Any]:
    """op: 'modify' (incl. create) | 'delete'."""
    return {"version": list(version), "prior": list(prior),
            "oid": oid, "op": op, "size": size}


class PGInfo:
    """pg_info_t role: identity + log bounds of one shard's PG state."""

    def __init__(self, last_update: Ever = ZERO, log_tail: Ever = ZERO,
                 same_interval_since: int = 0, last_epoch_started: int = 0):
        self.last_update = last_update
        self.log_tail = log_tail
        self.same_interval_since = same_interval_since
        self.last_epoch_started = last_epoch_started

    def to_dict(self) -> Dict[str, Any]:
        return {"last_update": list(self.last_update),
                "log_tail": list(self.log_tail),
                "same_interval_since": self.same_interval_since,
                "last_epoch_started": self.last_epoch_started}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PGInfo":
        return cls(ev(d["last_update"]), ev(d["log_tail"]),
                   int(d.get("same_interval_since", 0)),
                   int(d.get("last_epoch_started", 0)))


class PGLog:
    """Ordered entries (oldest first) + info, with merge/rewind."""

    def __init__(self, info: Optional[PGInfo] = None,
                 entries: Optional[List[Dict[str, Any]]] = None,
                 missing: Optional[Dict[str, Ever]] = None):
        self.info = info or PGInfo()
        self.entries: List[Dict[str, Any]] = entries or []
        # objects whose on-disk state lags the log head (pg_missing_t):
        # oid -> version needed ((0,0) = unknown, recover to auth state).
        # Persisted so a shard that crashes mid-recovery still knows what
        # it must not serve.
        self.missing: Dict[str, Ever] = missing or {}

    # -- append / trim -----------------------------------------------------

    def append(self, entry: Dict[str, Any]) -> None:
        version = ev(entry["version"])
        assert version > self.info.last_update, \
            f"log entry {version} <= head {self.info.last_update}"
        self.entries.append(entry)
        self.info.last_update = version

    def trim_to(self, keep: int) -> None:
        """Keep at most `keep` entries; advances log_tail."""
        if len(self.entries) > keep:
            cut = self.entries[:len(self.entries) - keep]
            self.entries = self.entries[len(cut):]
            self.info.log_tail = ev(cut[-1]["version"])

    # -- queries -----------------------------------------------------------

    def versions(self) -> Dict[Ever, Dict[str, Any]]:
        return {ev(e["version"]): e for e in self.entries}

    def objects_newer_than(self, bound: Ever) -> Dict[str, Ever]:
        """oid -> latest version, over entries with version > bound.
        `delete` entries count too (the peer must learn the delete)."""
        out: Dict[str, Ever] = {}
        for e in self.entries:
            if ev(e["version"]) > bound:
                out[e["oid"]] = ev(e["version"])
        return out

    # -- merge (merge_log + rewind_divergent_log) --------------------------

    def merge(self, auth_info: PGInfo,
              auth_entries: List[Dict[str, Any]]) -> Dict[str, Ever]:
        """Adopt the authoritative log; returns this shard's missing set
        {oid: version needed}.

        Divergence point = the newest local version that also appears in
        the authoritative log.  Local entries past it are divergent ->
        their objects are missing (to be recovered to auth state);
        authoritative entries past it are ops this shard never saw ->
        missing too.  If the local head predates the auth log tail, log
        replay can't catch up: every object in the auth log window is
        missing and the caller should treat the peer as backfill.
        """
        auth_versions = {ev(e["version"]) for e in auth_entries}
        missing: Dict[str, Ever] = {}

        # divergence point: newest local version the auth log also knows
        # (in its entries, or at/before its tail = in its trimmed past)
        common: Ever = ZERO
        divergent: List[Dict[str, Any]] = []
        if not self.entries:
            common = self.info.last_update
        else:
            for e in reversed(self.entries):
                version = ev(e["version"])
                if version in auth_versions or \
                        version <= auth_info.log_tail:
                    common = version
                    break
                divergent.append(e)
            # no break -> common stays ZERO: whole local log divergent

        for e in divergent:  # rewind_divergent_log
            missing[e["oid"]] = ZERO  # unknown good version yet

        # adopt auth entries newer than the divergence point
        for e in auth_entries:
            version = ev(e["version"])
            if version > common:
                missing[e["oid"]] = version

        # divergent objects with no auth entry: roll back to whatever the
        # auth primary holds now (recovery source resolves it); keep ZERO
        self.entries = [dict(e) for e in auth_entries]
        self.info.last_update = auth_info.last_update
        self.info.log_tail = auth_info.log_tail
        return missing

    # -- persistence -------------------------------------------------------

    def stage(self, t: Transaction, cid: str) -> None:
        """Write info+log+missing into the transaction (same txn as the
        data mutation it journals)."""
        t.omap_setkeys(cid, ObjectId(PGMETA_OID), {
            K_INFO: json.dumps(self.info.to_dict()).encode(),
            K_LOG: json.dumps(self.entries).encode(),
            K_MISSING: json.dumps(
                {k: list(v) for k, v in self.missing.items()}).encode(),
        })

    @classmethod
    def load(cls, store, cid: str) -> "PGLog":
        try:
            omap = store.omap_get(cid, ObjectId(PGMETA_OID))
        except KeyError:
            return cls()
        if K_INFO not in omap:
            return cls()
        missing = {k: ev(v) for k, v in json.loads(
            omap.get(K_MISSING, b"{}")).items()}
        return cls(PGInfo.from_dict(json.loads(omap[K_INFO])),
                   json.loads(omap.get(K_LOG, b"[]")), missing)

"""Straggler-tolerant hedged scheduling for EC sub-reads.

At scale the tail, not the median, is the product: an EC read that
`asyncio.gather`s ALL acting shards inherits the latency of the
slowest OSD, so one degraded peer sets p99 for the whole pool.  Coded
computation treats stragglers as the normal case — over-provision the
fan-out and complete from the first k arrivals (rateless/coded
redundancy scheduling, arXiv:1804.10331, arXiv:1811.02144).  The
any-k decode matrices already ride the plan cache as runtime operands
(PR 2), so completing from an arbitrary k-subset costs nothing on the
decode side; this module supplies the scheduling side:

* **PeerStats** — per-peer response-time EWMA + exponentially
  weighted variance, fed from every sub-read round trip.  Idle time
  decays both toward the prior with a configurable half-life, so an
  OSD that was slow (or down) re-earns trust instead of carrying a
  stale penalty forever.  Each peer also carries its own
  `common.circuit.CircuitBreaker` (the PR-5 state machine, one
  instance per peer rather than the global per-family registry):
  consecutive sub-read failures trip it, and a degraded peer ranks
  LAST in fan-out choice instead of being hedged against repeatedly.
* **HedgeTracker.gather** — the hedged-gather primitive: issue the k
  fastest-ranked sub-reads plus Δ speculative extras
  (`osd_hedge_delta`, escalating by one while the EWMA spread across
  peers is high), return as soon as the caller's `sufficient`
  predicate holds (any k DISTINCT shards landing on one version),
  fire a delayed hedge — the next-ranked spare sub-read — when a
  flight outlives its peer's p95-EWMA mark, and cancel stragglers
  cleanly: every spawned task is awaited before return, so a
  cancelled sub-read can neither leak nor corrupt connection framing
  (frame seq numbers are allocated under the connection send lock —
  see msg.Connection._send_signed).

Kill switches: CEPH_TPU_HEDGE=0 (env) or osd_hedge_enable=false both
restore the all-shard gather bit for bit; hedged and unhedged reads
return identical bytes either way — hedging only changes WHEN enough
arrivals exist, never what is decoded from them.
"""

from __future__ import annotations

import asyncio
import logging
import math
import os

from ceph_tpu.common import flags
import time
from typing import (
    Any, Awaitable, Callable, Dict, List, Optional, Sequence, Tuple,
)

from ceph_tpu.common import tracing
from ceph_tpu.common.circuit import CLOSED, CircuitBreaker

log = logging.getLogger("osd.hedge")

__all__ = ["HedgeTracker", "PeerStats", "env_enabled"]

# z-score of the 95th percentile under the normal approximation of the
# RTT distribution (mean = EWMA, var = EW variance)
_Z95 = 1.645


def env_enabled() -> bool:
    return flags.enabled("CEPH_TPU_HEDGE")


class PeerStats:
    """One peer's response-time model: EWMA + EW variance + breaker."""

    __slots__ = ("osd", "alpha", "halflife", "prior", "ewma", "var",
                 "samples", "failures", "last_at", "breaker", "_clock")

    def __init__(self, osd: int, alpha: float, halflife: float,
                 prior: float,
                 clock: Callable[[], float] = time.monotonic):
        self.osd = osd
        self.alpha = alpha
        self.halflife = halflife
        self.prior = prior
        self.ewma = prior
        self.var = 0.0
        self.samples = 0
        self.failures = 0
        self.last_at = clock()
        self._clock = clock
        # the PR-5 breaker state machine, one instance per peer: short
        # base backoff — a sub-read peer recovers on network timescales,
        # not accelerator-runtime ones
        self.breaker = CircuitBreaker(f"peer.{osd}", base_backoff=1.0,
                                      max_backoff=30.0, clock=clock)

    def _decay(self, now: float) -> None:
        """Drift the model toward the prior over idle time: trust is
        re-earned with a half-life, in both directions — a recovered
        OSD stops ranking last, a long-idle fast peer stops looking
        better than it currently is."""
        dt = now - self.last_at
        if dt <= 0:
            return
        self.last_at = now
        f = 0.5 ** (dt / self.halflife) if self.halflife > 0 else 0.0
        self.ewma = self.prior + (self.ewma - self.prior) * f
        self.var *= f

    def observe(self, rtt_s: float, ok: bool = True) -> None:
        now = self._clock()
        self._decay(now)
        self.samples += 1
        if ok:
            self.breaker.record_success()
        else:
            if self.breaker.state != CLOSED:
                # a sub-read reaching a peer whose backoff expired IS
                # its half-open probe: claim the probe slot so this
                # failure RE-trips with an escalated backoff.
                # (record_failure is a no-op in expired-OPEN — without
                # this a persistently dead peer is degraded for one
                # base backoff window and then reported healthy
                # forever.)
                self.breaker.allow()
            self.failures += 1
            self.breaker.record_failure()
        # failures still feed the RTT model: the timeout a failed
        # sub-read cost IS this peer's current response time
        self._feed(rtt_s)

    def observe_censored(self, elapsed_s: float) -> None:
        """A flight cancelled at `elapsed_s` is a RIGHT-CENSORED
        sample: the peer's RTT is AT LEAST that, and nothing more is
        known.  It may only move the model UP — a straggler cancelled
        the moment faster peers complete must not be taught the
        winners' latency (it would then rank among the fastest and
        tax every subsequent read).  The breaker is NOT fed: a cancel
        is the race being lost, not evidence of peer health either
        way."""
        self._decay(self._clock())
        if elapsed_s <= self.ewma:
            return
        self.samples += 1
        self._feed(elapsed_s)

    def _feed(self, rtt_s: float) -> None:
        d = rtt_s - self.ewma
        self.ewma += self.alpha * d
        self.var = max(0.0,
                       (1.0 - self.alpha) * (self.var
                                             + self.alpha * d * d))

    def ewma_now(self) -> float:
        """The decayed-as-of-now EWMA — ranking must see re-earned
        trust, not the estimate frozen at the last observation."""
        self._decay(self._clock())
        return self.ewma

    def p95(self) -> float:
        self._decay(self._clock())
        return self.ewma + _Z95 * math.sqrt(self.var)

    def degraded(self) -> bool:
        return self.breaker.degraded()

    def snapshot(self) -> Dict[str, Any]:
        self._decay(self._clock())
        return {
            "ewma_ms": round(self.ewma * 1e3, 3),
            "p95_ms": round(self.p95() * 1e3, 3),
            "samples": self.samples,
            "failures": self.failures,
            "state_code": self.breaker.stats()["state_code"],
        }


async def _traced_job(factory, span):
    """Run one sub-read job under its per-peer span (installed as the
    task's current span, so the wire context a _request stamps onto
    MOSDSubRead parents the replica's span to THIS sub-read, not to
    the whole op): cancellation (a straggler cut loose) is annotated
    so the critical-path reducer keeps the span off the path — the op
    never waited for it."""
    token = tracing.current_span.set(span) if span else None
    try:
        return await factory()
    except asyncio.CancelledError:
        span.set_attr("cancelled", True)
        span.event("cancelled straggler")
        raise
    finally:
        if token is not None:
            tracing.current_span.reset(token)
        span.finish()


class _Flight:
    """One in-flight hedgeable sub-read task's bookkeeping."""

    __slots__ = ("peer", "t0", "deadline", "is_hedge", "hedge_fired",
                 "span")

    def __init__(self, peer: int, t0: float, deadline: float,
                 is_hedge: bool, span=tracing.NULL_SPAN):
        self.peer = peer
        self.t0 = t0
        self.deadline = deadline
        self.is_hedge = is_hedge
        self.hedge_fired = False
        self.span = span


class HedgeTracker:
    """Per-daemon peer latency model + the hedged-gather primitive."""

    def __init__(self, who: str = "osd",
                 config: Optional[Dict[str, Any]] = None,
                 clock: Callable[[], float] = time.monotonic):
        cfg = config or {}
        self.who = who
        self.enabled = env_enabled() and bool(
            cfg.get("osd_hedge_enable", True))
        self.delta = int(cfg.get("osd_hedge_delta", 1))
        self.alpha = float(cfg.get("osd_hedge_ewma_alpha", 0.25))
        self.halflife = float(cfg.get("osd_hedge_decay_halflife", 30.0))
        self.prior_s = float(
            cfg.get("osd_hedge_rtt_prior_ms", 10.0)) / 1e3
        self.delay_floor_s = float(
            cfg.get("osd_hedge_delay_floor_ms", 2.0)) / 1e3
        self.delay_cap_s = float(
            cfg.get("osd_hedge_delay_cap_ms", 1000.0)) / 1e3
        self.spread_escalate = float(
            cfg.get("osd_hedge_spread_escalate", 4.0))
        self._clock = clock
        self.peers: Dict[int, PeerStats] = {}
        self.counters: Dict[str, int] = {
            "gathers": 0, "hedged_gathers": 0, "early_completions": 0,
            "hedges_fired": 0, "hedge_wins": 0,
            "cancelled_subreads": 0, "escalations": 0,
        }

    # -- the latency model -------------------------------------------------

    def peer(self, osd: int) -> PeerStats:
        st = self.peers.get(osd)
        if st is None:
            st = self.peers[osd] = PeerStats(
                osd, self.alpha, self.halflife, self.prior_s,
                clock=self._clock)
        return st

    def observe(self, osd: int, rtt_s: float, ok: bool = True) -> None:
        self.peer(osd).observe(rtt_s, ok=ok)

    def rank_key(self, osd: int) -> tuple:
        """Sort key for fan-out choice: healthy peers by decayed EWMA,
        breaker-degraded peers last (they are probed only when the
        faster ranks cannot complete the read — never hedged against
        repeatedly), osd id as the deterministic tiebreak."""
        st = self.peers.get(osd)
        if st is None:
            return (0, self.prior_s, osd)
        return (1 if st.degraded() else 0, st.ewma_now(), osd)

    def hedge_delay_s(self, osd: int) -> float:
        """How long a flight to this peer may run before it is treated
        as straggling and a spare sub-read is recruited: the peer's
        p95-EWMA mark, clamped to [floor, cap]."""
        st = self.peers.get(osd)
        p95 = st.p95() if st is not None else self.prior_s
        return min(max(p95, self.delay_floor_s), self.delay_cap_s)

    def spread(self) -> float:
        """Max-p95 over min-EWMA across non-degraded sampled peers — a
        high ratio means the tail is currently wide and Δ should
        escalate."""
        ewmas = []
        p95s = []
        for st in self.peers.values():
            if st.samples == 0 or st.degraded():
                continue
            ewmas.append(max(st.ewma_now(), 1e-9))
            p95s.append(st.p95())
        if len(ewmas) < 2:
            return 1.0
        return max(p95s) / min(ewmas)

    def effective_delta(self) -> int:
        """Δ speculative extras beyond k, +1 while the EWMA spread is
        high (the rateless over-provisioning knob, demand-driven)."""
        if self.spread() > self.spread_escalate:
            self.counters["escalations"] += 1
            return self.delta + 1
        return self.delta

    # -- the gather primitive ----------------------------------------------

    async def gather(
            self,
            jobs: Sequence[Tuple[int, Callable[[], Awaitable[Any]]]],
            need: Optional[int] = None,
            sufficient: Optional[Callable[[List[Any]], bool]] = None,
            failed: Optional[Callable[[Any], bool]] = None,
            label: str = "subread",
    ) -> Tuple[List[Any], bool]:
        """Run (peer, job-factory) pairs; return (results, ran_all).

        need=None (or hedging disabled, or no spare fan-out) runs every
        job concurrently and awaits them all — the all-shard mode, bit
        identical to a bare gather but with named, cancellation-safe
        tasks.  With need=k and spare jobs available, jobs launch in
        EWMA rank order (k + Δ initially), a flight that outlives its
        peer's p95 recruits the next-ranked spare, a job the `failed`
        predicate rejects (transport fault / no candidates) recruits a
        spare immediately, and the call returns as soon as `sufficient`
        accepts the collected results — stragglers are cancelled AND
        awaited, so no task outlives the call.

        ran_all is True only when every job ran to completion: an
        early (hedged) exit can never masquerade as an exhaustive
        probe.

        label names the per-flight stage spans ("subread" for the EC
        read fan-out, "subcompute" for coded-compute sub-ops) so each
        workload class gets its own row in the critical-path stage
        histograms."""
        jobs = list(jobs)
        if not jobs:
            return [], True
        self.counters["gathers"] += 1
        loop = asyncio.get_running_loop()
        hedged = (self.enabled and need is not None and 0 < need
                  and sufficient is not None and len(jobs) > need)
        if not hedged:
            tasks = [loop.create_task(
                _traced_job(factory,
                            tracing.start_child(
                                f"{label} osd.{peer}")),
                name=f"hedge:{self.who}:all:{peer}")
                for peer, factory in jobs]
            try:
                results = await asyncio.gather(*tasks)
            except BaseException:
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                raise
            return list(results), True

        self.counters["hedged_gathers"] += 1
        order = sorted(jobs, key=lambda j: self.rank_key(j[0]))
        flights: Dict[asyncio.Task, _Flight] = {}
        results: List[Any] = []
        next_i = 0
        ran_all = True
        early_exit = False

        def launch(is_hedge: bool) -> Optional[asyncio.Task]:
            nonlocal next_i
            if next_i >= len(order):
                return None
            peer, factory = order[next_i]
            next_i += 1
            span = tracing.start_child(f"{label} osd.{peer}",
                                       hedge=is_hedge)
            task = loop.create_task(
                _traced_job(factory, span),
                name=f"hedge:{self.who}:{peer}:{next_i}")
            now = loop.time()
            flights[task] = _Flight(
                peer, now, now + self.hedge_delay_s(peer), is_hedge,
                span=span)
            if is_hedge:
                self.counters["hedges_fired"] += 1
                tracing.event(f"hedge fired -> osd.{peer}")
            return task

        for _ in range(min(len(order), need + self.effective_delta())):
            launch(False)
        try:
            while flights:
                timeout = None
                if next_i < len(order):
                    now = loop.time()
                    unfired = [fl.deadline - now
                               for fl in flights.values()
                               if not fl.hedge_fired]
                    if unfired:
                        timeout = max(0.0, min(unfired))
                done, _pending = await asyncio.wait(
                    set(flights), timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    # hedge timer: every overdue flight recruits one
                    # spare (ranked next) exactly once
                    now = loop.time()
                    for fl in list(flights.values()):
                        if not fl.hedge_fired and now >= fl.deadline:
                            fl.hedge_fired = True
                            if launch(True) is None:
                                break
                    continue
                for task in done:
                    fl = flights.pop(task)
                    try:
                        res = task.result()
                    except asyncio.CancelledError:
                        ran_all = False
                        continue
                    except Exception:
                        # a sub-read job that RAISES (they normally
                        # report transport faults in-band) still only
                        # costs its slot: recruit the next spare.
                        # Logged loudly — with the kill switch off the
                        # same raise would propagate, and a swallowed
                        # error must not make hedged mode the mode
                        # where bugs hide
                        log.exception(
                            "%s: hedged sub-read job to osd.%d "
                            "raised (recruiting a spare)",
                            self.who, fl.peer)
                        ran_all = False
                        launch(False)
                        continue
                    results.append(res)
                    if failed is not None and failed(res):
                        # transport fault or no candidates from that
                        # shard: recruit a spare now instead of
                        # waiting for a hedge timer
                        fl.span.set_attr("failed", True)
                        launch(False)
                    elif fl.is_hedge:
                        self.counters["hedge_wins"] += 1
                        fl.span.set_attr("hedge_win", True)
                        tracing.event(f"hedge win osd.{fl.peer}")
                if sufficient(results):
                    if flights or next_i < len(order):
                        self.counters["early_completions"] += 1
                        ran_all = False
                    early_exit = True
                    return results, ran_all
                if not flights:
                    # every flight completed yet the results are
                    # still insufficient — candidates `failed` does
                    # not reject (hinfo-corrupt payloads, version-
                    # divergent generations) satisfy nothing: go
                    # WIDE, like the all-shard gather would.  This
                    # wave proved the ranked prefix insufficient;
                    # recruiting spares one per wave would serialize
                    # the residual probes into O(n) round trips on
                    # exactly the degraded reads hedging exists to
                    # speed up.
                    while launch(False) is not None:
                        pass
            return results, ran_all and next_i >= len(order)
        finally:
            if flights:
                self.counters["cancelled_subreads"] += len(flights)
                now = loop.time()
                for task, fl in flights.items():
                    task.cancel()
                    if early_exit:
                        # a straggler cancelled by EARLY COMPLETION
                        # feeds its elapsed time as a right-censored
                        # sample (observe_censored: moves the model
                        # up only, breaker untouched) — a peer whose
                        # flights always out-live their hedge mark
                        # ratchets upward and drops out of the
                        # fan-out, while one cancelled the instant
                        # faster peers answered learns nothing.
                        # EXTERNAL cancellation (the client op / the
                        # daemon dying) charges nobody: that elapsed
                        # time is the canceller's impatience, not the
                        # peer's latency.
                        self.peer(fl.peer).observe_censored(
                            max(now - fl.t0, 0.0))
                # awaiting the cancelled tasks is the no-leak
                # guarantee: nothing spawned here outlives the gather
                await asyncio.gather(*flights, return_exceptions=True)

    # -- observability -----------------------------------------------------

    def perf(self) -> Dict[str, Any]:
        """Numeric-only nested snapshot for `perf dump` (the
        prometheus flattener turns the `peers` map into peer-labeled
        rows)."""
        return {
            "enabled": int(self.enabled),
            **self.counters,
            "peers": {f"osd.{osd}": st.snapshot()
                      for osd, st in sorted(self.peers.items())},
        }

    def status(self) -> Dict[str, Any]:
        """The hedge_status admin/tell surface: config + counters +
        the live per-peer model with breaker states."""
        peers = {}
        for osd, st in sorted(self.peers.items()):
            snap = st.snapshot()
            snap["breaker"] = st.breaker.stats()["state"]
            peers[f"osd.{osd}"] = snap
        return {
            "enabled": self.enabled,
            "delta": self.delta,
            "spread": round(self.spread(), 3),
            "spread_escalate": self.spread_escalate,
            "delay_floor_ms": self.delay_floor_s * 1e3,
            "delay_cap_ms": self.delay_cap_s * 1e3,
            "decay_halflife_s": self.halflife,
            "counters": dict(self.counters),
            "peers": peers,
        }

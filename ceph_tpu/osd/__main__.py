"""Run an OSD daemon as a real process: python -m ceph_tpu.osd

With --store-path the OSD hosts a persistent TPUStore (survives the
process, like an OSD's disk); without it, an in-memory MemStore.
Prints `OSD_ADDR <host:port>` once booted into the map.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os

from ceph_tpu.common import flags
import sys

from ceph_tpu.os.memstore import MemStore
from ceph_tpu.osd.daemon import OSDDaemon


async def _main() -> None:
    if flags.get("CEPH_TPU_DEBUG"):
        logging.basicConfig(level=logging.DEBUG)
    ap = argparse.ArgumentParser()
    ap.add_argument("--id", type=int, required=True)
    ap.add_argument("--mon", type=str, required=True)
    ap.add_argument("--store-path", type=str, default="")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--config", type=str, default="{}",
                    help="JSON osd config overrides")
    args = ap.parse_args()
    try:
        if args.store_path:
            from ceph_tpu.os.tpustore import TPUStore

            store = TPUStore(args.store_path)
            if not os.path.exists(os.path.join(args.store_path,
                                               "block")):
                os.makedirs(args.store_path, exist_ok=True)
                store.mkfs()
            store.mount()
        else:
            store = MemStore()
            store.mkfs()
            store.mount()
        osd = OSDDaemon(args.id, args.mon, store=store,
                        config=json.loads(args.config))
        addr = await osd.start(port=args.port)
    except (KeyboardInterrupt, asyncio.CancelledError):
        raise
    except BaseException as e:
        # boot died (bad store, bind failure, mount corruption): post
        # a crash report before exiting (the ceph-crash role) —
        # best-effort over a FRESH connection, never masks the error
        from ceph_tpu.common.crash import post_crash

        await post_crash(args.mon, f"osd.{args.id}", e)
        raise
    print(f"OSD_ADDR {addr}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await osd.stop()
        store.umount()


if __name__ == "__main__":
    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        sys.exit(0)

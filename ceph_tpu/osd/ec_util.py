"""EC stripe arithmetic and shard hashing.

Reference parity: ECUtil (/root/reference/src/osd/ECUtil.{h,cc}):

- stripe_info_t — pure logical<->chunk offset maps over
  stripe_width = k * chunk_size rows (ECUtil.h:27-80);
- ECUtil::encode/decode — adapt whole-object buffers to the per-stripe
  codec (ECUtil.cc);
- HashInfo — cumulative per-shard crc32c kept in an object xattr
  (hinfo_key), the bit-exactness ledger updated on append
  (ECUtil.h:101-160).

TPU-first deviation: where the reference loops stripes calling the codec
once per stripe, `encode`/`decode` here stack all stripes into one
(B, k, chunk) batch and make a single device dispatch through the codec's
batched entry points when available — host<->TPU latency is amortized over
the whole object (SURVEY.md §7 hard part #4).
"""

from __future__ import annotations

import os

from ceph_tpu.common import flags
from typing import Dict, Iterable, List, Mapping, Optional, Set, Tuple

import numpy as np

from ceph_tpu.ops import checksum as cks

HINFO_KEY = "hinfo_key"


def is_hinfo_key_string(key: str) -> bool:
    return key == HINFO_KEY


class StripeInfo:
    """stripe_info_t: stripe_width = stripe_size (k) x chunk_size."""

    def __init__(self, stripe_size: int, stripe_width: int):
        assert stripe_width % stripe_size == 0
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def get_stripe_width(self) -> int:
        return self.stripe_width

    def get_chunk_size(self) -> int:
        return self.chunk_size

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return (-(-offset // self.stripe_width)) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - offset % self.stripe_width

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset + (self.stripe_width - rem) if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def aligned_offset_len_to_chunk(self, off_len: Tuple[int, int]
                                    ) -> Tuple[int, int]:
        off, length = off_len
        return (self.aligned_logical_offset_to_chunk_offset(off),
                self.aligned_logical_offset_to_chunk_offset(length))

    def offset_len_to_stripe_bounds(self, off_len: Tuple[int, int]
                                    ) -> Tuple[int, int]:
        off, length = off_len
        start = self.logical_to_prev_stripe_offset(off)
        end_len = self.logical_to_next_stripe_offset((off - start) + length)
        return start, end_len


def encode(sinfo: StripeInfo, ec_impl, data: bytes,
           want: Iterable[int]) -> Dict[int, bytes]:
    """Whole-object encode: (stripes x width) -> per-shard chunk streams.

    Input must be stripe-aligned (callers zero-pad, as the reference tool
    does).  All stripes go through the codec in one batched dispatch when
    the codec exposes encode_batch (the ec_jax path).
    """
    logical_size = len(data)
    assert logical_size % sinfo.get_stripe_width() == 0
    want = set(want)
    out: Dict[int, bytes] = {}
    if logical_size == 0:
        return out

    width = sinfo.get_stripe_width()
    chunk = sinfo.get_chunk_size()
    n_stripes = logical_size // width
    k = width // chunk
    n = ec_impl.get_chunk_count()

    if ec_impl.get_chunk_size(width) != chunk:
        from ceph_tpu.ec.interface import ErasureCodeError

        raise ErasureCodeError(
            22, f"stripe unit {chunk} is incompatible with the codec's"
            f" alignment: a {width}-byte stripe encodes to"
            f" {ec_impl.get_chunk_size(width)}-byte chunks")

    if hasattr(ec_impl, "encode_batch") and not ec_impl.get_chunk_mapping():
        arr = np.frombuffer(data, dtype=np.uint8).reshape(n_stripes, k, chunk)
        # shard-STREAM layout: one contiguous transpose up front, then
        # every downstream step (the matmul, the per-shard bytes) works
        # on contiguous rows — per-stripe dispatch and strided copies
        # both cost more than the whole encode
        streams = np.ascontiguousarray(np.moveaxis(arr, 1, 0))
        # shards leave as FROZEN zero-copy row views (the fused-path
        # discipline): nothing mutates them after the encode, frozen
        # OWNERS are store-adoptable (buffer.is_immutable walks the
        # base chain), and the per-shard tobytes copy was the whole
        # object's size over again.  Freeze before reshaping so the
        # row views' base is the frozen owner.
        streams.setflags(write=False)
        streams = streams.reshape(k, n_stripes * chunk)
        parity = ec_impl.encode_batch(streams[None])[0]  # (m, B*chunk)
        parity = np.ascontiguousarray(parity)
        if parity.base is not None:
            # e.g. a wrapper over a device buffer: own the memory so
            # the frozen-owner contract holds (cost parity with the
            # tobytes this path used to pay)
            parity = parity.copy()
        parity.setflags(write=False)
        for i in range(n):
            if i not in want:
                continue
            out[i] = streams[i].data if i < k else parity[i - k].data
        return out

    # generic path: per-stripe through the interface (array codes, mappings)
    parts: Dict[int, List[bytes]] = {i: [] for i in want}
    mv = memoryview(data) if not isinstance(data, memoryview) else data
    for s in range(n_stripes):
        encoded = ec_impl.encode(want, mv[s * width:(s + 1) * width])
        for i, buf in encoded.items():
            assert len(buf) == chunk
            parts[i].append(buf)
    return {i: b"".join(bufs) for i, bufs in parts.items()}


def encode_with_hinfo(sinfo: StripeInfo, ec_impl, data,
                      want: Iterable[int],
                      logical_len: Optional[int] = None
                      ) -> Tuple[Dict[int, object], "HashInfo",
                                 Optional[int]]:
    """Whole-object encode + per-shard cumulative crc32c in one step.

    Matches ECTransaction::generate_transactions followed by
    HashInfo::append (ECBackend.cc:2000, ECUtil.h:132-147) but fused:
    on the host tier the parity accumulate and every crc run inside
    ONE cache-resident native pass (native/src/datapath.cc), data
    shards come back as zero-copy StridedBuf views of the caller's
    buffer, and the logical content crc32c over data[:logical_len]
    (when asked for) rides along for the write reply's data-digest.
    """
    from ceph_tpu import native

    n = ec_impl.get_chunk_count()
    matrix = getattr(ec_impl, "matrix", None)
    lib = native.get_lib()
    use_device = bool(getattr(ec_impl, "use_tpu", False)) and \
        len(data) >= getattr(ec_impl, "tpu_min_bytes", 1)
    if use_device and matrix is not None \
            and not ec_impl.get_chunk_mapping():
        fused = _encode_with_hinfo_device(sinfo, ec_impl, data, want,
                                          logical_len)
        if fused is not None:
            return fused
    if (matrix is None or ec_impl.get_chunk_mapping() or lib is None
            or use_device
            or not hasattr(lib, "ceph_tpu_ec_encode_noT")):
        from ceph_tpu.common.buffer import as_buffer

        data = as_buffer(data)
        shards = encode(sinfo, ec_impl, data, want)
        hinfo = HashInfo(n)
        hinfo.append(0, shards)
        crc = None
        if logical_len is not None:
            crc = cks.crc32c(0xFFFFFFFF, memoryview(data)[:logical_len])
        return shards, hinfo, crc

    import ctypes

    from ceph_tpu.common.buffer import StridedBuf

    width = sinfo.get_stripe_width()
    chunk = sinfo.get_chunk_size()
    assert len(data) % width == 0
    n_stripes = len(data) // width
    k = width // chunk
    m = n - k
    stream = n_stripes * chunk
    tables = getattr(ec_impl, "_mul_tables", None)
    if tables is None:
        from ceph_tpu.ops import gf

        tables = np.ascontiguousarray(gf.gf_mul_tables(matrix))
        ec_impl._mul_tables = tables
    src = np.frombuffer(data, dtype=np.uint8)
    parity_out = np.empty((max(m, 1), stream), dtype=np.uint8)
    crcs = np.full(n, 0xFFFFFFFF, dtype=np.uint32)
    lcrc = np.full(1, 0xFFFFFFFF, dtype=np.uint32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.ceph_tpu_ec_encode_noT(
        tables.ctypes.data_as(u8p), m, k,
        src.ctypes.data_as(u8p), n_stripes, chunk,
        parity_out.ctypes.data_as(u8p), crcs.ctypes.data_as(u32p),
        0 if logical_len is None else logical_len,
        lcrc.ctypes.data_as(u32p) if logical_len is not None else None)
    # data shards stay strided views of the adopted source buffer —
    # no transpose copy is ever made (StridedBuf docstring).  Both
    # shard kinds are frozen read-only: nothing mutates them after the
    # kernel, and only immutable buffers are store-adoptable.
    if src.flags.writeable:
        src.setflags(write=False)
    parity_out.setflags(write=False)
    stripes = src.reshape(n_stripes, k, chunk)
    want = set(want)
    out: Dict[int, object] = {}
    for i in range(n):
        if i not in want:
            continue
        out[i] = StridedBuf(stripes[:, i, :]) if i < k \
            else parity_out[i - k].data
    hinfo = HashInfo(n)
    hinfo.cumulative_shard_hashes = [int(c) for c in crcs]
    hinfo.total_chunk_size = stream
    return out, hinfo, (int(lcrc[0]) if logical_len is not None else None)


def _fuse_min_bytes() -> Optional[int]:
    """Object-size floor for the fused device encode+crc path; None
    disables it.  CEPH_TPU_FUSE_MIN_BYTES overrides (tests set 0).
    Default: 1 MiB on a real TPU backend — that is where fusing the
    parity and hinfo-CRC round-trips into one dispatch pays; on the
    CPU tier the fused path is the native noT kernel below."""
    env = flags.get("CEPH_TPU_FUSE_MIN_BYTES")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            import sys

            # a typo'd knob must not silently disable the fused tier:
            # warn and fall through to the default policy
            print(f"# CEPH_TPU_FUSE_MIN_BYTES={env!r} is not an "
                  "integer; using the default policy",
                  file=sys.stderr)
    from ceph_tpu.ec import plan

    return (1 << 20) if plan.device_platform() == "tpu" else None


def _fused_result(sinfo: StripeInfo, ec_impl, src: np.ndarray,
                  arr: np.ndarray, parity, crc0,
                  want: Iterable[int], logical_len: Optional[int],
                  data) -> Tuple[Dict[int, object], "HashInfo",
                                 Optional[int]]:
    """Assemble one object's (shards, hinfo, data_crc) from the fused
    device outputs: the per-stripe zero-seeded chunk crcs fold into
    the cumulative per-shard ledger on host with the streaming
    identity crc(c, chunk) = crc32c_zeros(c, len) ^ crc32c(0, chunk).
    Zero-copy contract (same as the native tier): data shards are
    strided views of the caller's buffer, parity rows read-only
    memoryviews — the stores adopt immutable buffers, no transpose or
    defensive copies on the hot path."""
    from ceph_tpu.common.buffer import StridedBuf

    n = ec_impl.get_chunk_count()
    chunk = sinfo.get_chunk_size()
    n_stripes, k, _ = arr.shape
    hinfo = HashInfo(n)
    hashes = []
    for i in range(n):
        c = 0xFFFFFFFF
        for s in range(n_stripes):
            c = cks.crc32c_zeros(c, chunk) ^ int(crc0[s, i])
        hashes.append(c & 0xFFFFFFFF)
    hinfo.cumulative_shard_hashes = hashes
    hinfo.total_chunk_size = n_stripes * chunk
    if src.flags.writeable:
        src.setflags(write=False)
    want = set(want)
    shards: Dict[int, object] = {}
    for i in range(n):
        if i not in want:
            continue
        if i < k:
            shards[i] = StridedBuf(arr[:, i, :])
        else:
            row = np.ascontiguousarray(parity[:, i - k, :]).reshape(-1)
            row.setflags(write=False)
            shards[i] = row.data
    crc = None
    if logical_len is not None:
        crc = cks.crc32c(0xFFFFFFFF, memoryview(data)[:logical_len])
    return shards, hinfo, crc


def _encode_with_hinfo_device(sinfo: StripeInfo, ec_impl, data,
                              want: Iterable[int],
                              logical_len: Optional[int]):
    """Fused DEVICE tier of encode_with_hinfo: stripes batch into one
    (B, k, chunk) plan-cached dispatch that returns parity AND every
    chunk's zero-seeded crc32c (ec/plan.encode_with_crc); the crcs
    fold into the cumulative ledger in _fused_result.  Returns None
    when the fused plan does not apply (callers fall through to the
    host tiers)."""
    fmin = _fuse_min_bytes()
    if fmin is None or len(data) < max(fmin, 1) \
            or not hasattr(ec_impl, "encode_batch_with_crc"):
        return None
    from ceph_tpu.common.buffer import as_buffer

    data = as_buffer(data)
    width = sinfo.get_stripe_width()
    chunk = sinfo.get_chunk_size()
    if len(data) % width or ec_impl.get_chunk_size(width) != chunk:
        return None  # the generic path owns the incompatibility error
    n_stripes = len(data) // width
    k = width // chunk
    src = np.frombuffer(data, dtype=np.uint8)
    arr = src.reshape(n_stripes, k, chunk)
    out = ec_impl.encode_batch_with_crc(arr, init=0)
    if out is None:
        return None
    parity, crc0 = out          # (B, m, chunk), (B, k+m) zero-seeded
    return _fused_result(sinfo, ec_impl, src, arr, parity, crc0,
                         want, logical_len, data)


def device_fused_available(ec_impl) -> bool:
    """True when the fused device encode tier can engage for this
    codec — the encode service's batching gate.  Requires a real
    policy floor (``_fuse_min_bytes()`` is None on the CPU-only
    default, which keeps the service fully inline there), a
    device-enabled codec, and the fused batched entry points."""
    return (_fuse_min_bytes() is not None
            and bool(getattr(ec_impl, "use_tpu", False))
            and not ec_impl.get_chunk_mapping()
            and hasattr(ec_impl, "encode_many_with_crc"))


def encode_many_with_hinfo(sinfo: StripeInfo, ec_impl,
                           items) -> List[Tuple[Dict[int, object],
                                                "HashInfo",
                                                Optional[int]]]:
    """N whole-object encodes of one codec profile in ONE dispatch.

    ``items`` is a sequence of ``(data, want, logical_len)`` tuples;
    returns per-item ``(shards, hinfo, data_crc)`` exactly as
    encode_with_hinfo would produce.  The device tier folds every
    item's stripes into a single fused encode+crc plan call (the
    encode service's flush path); when the fused plan does not apply
    the items run the inline tiers one by one — results are
    bit-identical either way."""
    items = list(items)
    if not items:
        return []
    fused = _encode_many_device(sinfo, ec_impl, items)
    if fused is not None:
        return fused
    packed = _encode_many_bitmatrix(sinfo, ec_impl, items)
    if packed is not None:
        return packed
    return [encode_with_hinfo(sinfo, ec_impl, d, w, logical_len=l)
            for d, w, l in items]


def bitmatrix_native_available(ec_impl) -> bool:
    """True when the packed multi-object NATIVE tape tier can engage
    for this codec — the encode service's batching gate for the
    bitmatrix family (the device gate is device_fused_available).
    Requires the fused native executor (built + CEPH_TPU_NATIVE_XSCHED
    up), the schedule compiler (CEPH_TPU_XSCHED up, matrix within the
    serving-path compile bound), and an identity chunk mapping."""
    from ceph_tpu.ec import xsched

    bm = getattr(ec_impl, "bitmatrix", None)
    return (bm is not None
            and getattr(ec_impl, "_sig", None) is not None
            and not ec_impl.get_chunk_mapping()
            and xsched.enabled()
            and xsched.native_available()
            and xsched.host_compile_allowed(bm))


def _encode_many_bitmatrix(sinfo: StripeInfo, ec_impl, items):
    """Packed multi-object tier for the bitmatrix family: EVERY stripe
    of every item becomes one object of a single native region arena,
    so a flushed bucket of thousands of tiny writes runs as ONE
    compiled XOR tape call, and the per-shard HashInfo crc32c ledger
    folds natively over arena spans in a second call.  Requires
    single-block chunks (chunk == w * packetsize — a chunk's bytes ARE
    its w input regions back to back, so packing is one flat copy per
    item); anything else returns None and the caller runs the items
    inline, bit-identically."""
    if not bitmatrix_native_available(ec_impl):
        return None
    from ceph_tpu.common.buffer import StridedBuf, as_buffer
    from ceph_tpu.ec import xsched

    width = sinfo.get_stripe_width()
    chunk = sinfo.get_chunk_size()
    w, ps = ec_impl.w, ec_impl.packetsize
    n = ec_impl.get_chunk_count()
    k = width // chunk
    if chunk != w * ps or ec_impl.get_chunk_size(width) != chunk \
            or k != ec_impl.k:
        return None
    datas = []
    stripes_of = []
    for d, _want, _l in items:
        d = as_buffer(d)
        if len(d) == 0 or len(d) % width:
            return None
        datas.append(d)
        stripes_of.append(len(d) // width)
    sched = xsched.compile_matrix(ec_impl.bitmatrix, sig=ec_impl._sig)
    prog = xsched.lower_program(sched)
    n_regions, out_base = prog.n_regions, prog.out_base
    total = sum(stripes_of)
    arena = np.empty((total, n_regions, ps), dtype=np.uint8)
    s0 = 0
    for d, ns in zip(datas, stripes_of):
        arena[s0:s0 + ns, :k * w, :] = \
            np.frombuffer(d, dtype=np.uint8).reshape(ns, k * w, ps)
        s0 += ns
    xsched.execute_native(prog, arena)
    # per-shard cumulative crc ledger: one span per (stripe, shard),
    # stripe-ordered so multi-stripe shards fold like HashInfo.append
    m = n - k
    offs = np.concatenate([np.arange(k, dtype=np.int64) * w,
                           out_base + np.arange(m, dtype=np.int64) * w])
    rows = np.arange(total, dtype=np.int64)[:, None] * n_regions
    item_of = np.repeat(np.arange(len(items), dtype=np.int64),
                        stripes_of)
    spans = np.empty((total * n, 3), dtype=np.int32)
    spans[:, 0] = (rows + offs[None, :]).reshape(-1)
    spans[:, 1] = w
    spans[:, 2] = (item_of[:, None] * n
                   + np.arange(n, dtype=np.int64)[None, :]).reshape(-1)
    crcs = np.full(len(items) * n, 0xFFFFFFFF, dtype=np.uint32)
    xsched.crc_regions_native(arena, spans, crcs)
    results = []
    s0 = 0
    for (item, d, ns) in zip(items, datas, stripes_of):
        _data, want, logical_len = item
        src = np.frombuffer(d, dtype=np.uint8)
        if src.flags.writeable:
            src.setflags(write=False)
        grid = src.reshape(ns, k, chunk)
        it = len(results)
        want = set(want)
        shards: Dict[int, object] = {}
        for i in range(n):
            if i not in want:
                continue
            if i < k:
                shards[i] = StridedBuf(grid[:, i, :])
            else:
                row = np.ascontiguousarray(
                    arena[s0:s0 + ns,
                          out_base + (i - k) * w:out_base + (i - k + 1) * w,
                          :]).reshape(-1)
                row.setflags(write=False)
                shards[i] = row.data
        hinfo = HashInfo(n)
        hinfo.cumulative_shard_hashes = [
            int(c) for c in crcs[it * n:(it + 1) * n]]
        hinfo.total_chunk_size = ns * chunk
        crc = None
        if logical_len is not None:
            crc = cks.crc32c(0xFFFFFFFF, memoryview(d)[:logical_len])
        results.append((shards, hinfo, crc))
        s0 += ns
    return results


def _encode_many_device(sinfo: StripeInfo, ec_impl, items):
    """Batched twin of _encode_with_hinfo_device: the fuse-bytes floor
    applies to the TOTAL batch (aggregating small concurrent writes
    past the floor is the service's whole point).  Returns None when
    any item cannot ride the fused plan — the caller then runs all of
    them inline."""
    fmin = _fuse_min_bytes()
    if fmin is None or not getattr(ec_impl, "use_tpu", False) \
            or not hasattr(ec_impl, "encode_many_with_crc") \
            or ec_impl.get_chunk_mapping():
        return None
    width = sinfo.get_stripe_width()
    chunk = sinfo.get_chunk_size()
    if ec_impl.get_chunk_size(width) != chunk:
        return None
    from ceph_tpu.common.buffer import as_buffer

    datas = []
    total = 0
    for d, _w, _l in items:
        d = as_buffer(d)
        if len(d) == 0 or len(d) % width:
            return None
        datas.append(d)
        total += len(d)
    if total < max(fmin, 1) or \
            total < getattr(ec_impl, "tpu_min_bytes", 1):
        return None
    k = width // chunk
    srcs = [np.frombuffer(d, dtype=np.uint8) for d in datas]
    arrs = [s.reshape(-1, k, chunk) for s in srcs]
    out = ec_impl.encode_many_with_crc(arrs, init=0)
    if out is None:
        return None
    results = []
    for (item, d, src, arr, (parity, crc0)) in zip(
            items, datas, srcs, arrs, out):
        _data, want, logical_len = item
        results.append(_fused_result(sinfo, ec_impl, src, arr,
                                     parity, crc0, want, logical_len,
                                     d))
    return results


def encode_many(sinfo: StripeInfo, ec_impl, datas,
                wants) -> List[Dict[int, bytes]]:
    """N plain whole-object encodes (same profile) in one dispatch.

    Shard streams are chunk-aligned, so cross-object batching is
    concatenation along the stripe axis (the recovery-path fold,
    generalized): ONE ``encode`` of the joined bytes, then each
    object's shard slices come back out.  Per-object fallback keeps
    one malformed object from failing the rest."""
    datas = list(datas)
    wants = [set(w) for w in wants]
    assert len(datas) == len(wants)
    width = sinfo.get_stripe_width()
    chunk = sinfo.get_chunk_size()

    def one(d, w) -> Dict[int, bytes]:
        from ceph_tpu.common.buffer import as_buffer

        return encode(sinfo, ec_impl, as_buffer(d), w)

    if len(datas) <= 1 or any(len(d) % width for d in datas):
        return [one(d, w) for d, w in zip(datas, wants)]
    union = set().union(*wants)
    try:
        # join straight off the buffer protocol: b"".join accepts
        # memoryview/bytearray parts, so wrapping each in bytes()
        # first would copy every payload TWICE per batched encode
        # (hot-path-copy worklist fix: ~10.3ms -> ~0.16ms for a
        # 32x256KiB batch join, measured JAX_PLATFORMS=cpu)
        joined = b"".join(datas)
        full = encode(sinfo, ec_impl, joined, union)
    except Exception:
        return [one(d, w) for d, w in zip(datas, wants)]
    out: List[Dict[int, bytes]] = []
    offsets = {s: 0 for s in union}
    for d, w in zip(datas, wants):
        shard_len = (len(d) // width) * chunk
        shards = {}
        # offsets advance for EVERY union shard — each item owns a
        # shard_len slice of every joined stream whether or not it
        # asked for that shard
        for s in union:
            if s in w:
                shards[s] = full.get(s, b"")[
                    offsets[s]:offsets[s] + shard_len]
            offsets[s] += shard_len
        out.append(shards)
    return out


def fastest_survivors(ec_impl, have: Mapping[int, bytes], k: int,
                      prefer=None) -> Dict[int, bytes]:
    """Choose a decodable subset of survivor shard streams.

    The payloads are already fetched, so decode COST dominates the
    choice: available data shards always rank first (all-data decode
    is a free interleave — no GF dispatch), and only the erasure
    fill-ins among parity shards follow the caller's rank order
    (fastest peers first — the hedge tracker's EWMA ranking feeds
    `prefer`; the fetch-side fan-out is where EWMAs buy latency).

    Grows the candidate set in that order until the codec's
    minimum_to_decode accepts it, then returns exactly the minimum
    streams.  Deterministic for a fixed rank, so objects decoded in
    the same wave keep sharing survivor sets (the decode_many
    batching key).  Raises the codec's error when even the full
    survivor set cannot decode — the caller's below-k handling owns
    that, same as a direct minimum_to_decode call."""
    if not have:
        raise ValueError("no survivors")
    want = {ec_impl.chunk_index(i) for i in range(k)}
    rank = prefer if prefer is not None else (lambda s: (s,))
    order = sorted(have, key=lambda s: (s not in want, rank(s)))
    for j in range(min(k, len(order)), len(order) + 1):
        try:
            minimum = ec_impl.minimum_to_decode(want, set(order[:j]))
        except Exception:
            if j >= len(order):
                raise
            continue
        return {i: have[i] for i in minimum}
    raise AssertionError("unreachable")  # loop returns or re-raises


def choose_decode_set(ec_impl, have: Mapping[int, bytes], k: int,
                      prefer=None, first_k: bool = False,
                      ) -> Optional[Dict[int, bytes]]:
    """fastest_survivors plus the daemon's standard failure policy —
    one idiom instead of a try/rank/fallback copy at every call site.

    Returns the minimal decodable survivor map.  When no subset
    decodes: the first k shards by index if `first_k` (recovery paths
    that defer below-k adjudication to the decode attempt itself),
    else None (read paths that answer EIO)."""
    try:
        return fastest_survivors(ec_impl, have, k, prefer=prefer)
    except Exception:
        if first_k:
            return {s: have[s] for s in sorted(have)[:k]}
        return None


def decode_many(sinfo: StripeInfo, ec_impl,
                maps) -> List[bytes]:
    """N decode requests (same profile) -> logical byte streams.

    Requests sharing a survivor-shard set concatenate their per-shard
    streams and decode in ONE dispatch (the recovery-wave fold, shared
    with the read path); a failed group retries per request so one
    malformed object cannot poison its group."""
    maps = list(maps)
    out: List[Optional[bytes]] = [None] * len(maps)
    groups: Dict[tuple, List[int]] = {}
    for i, m in enumerate(maps):
        groups.setdefault(tuple(sorted(m)), []).append(i)
    chunk = sinfo.get_chunk_size()
    width = sinfo.get_stripe_width()
    for key, idxs in groups.items():
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = decode(sinfo, ec_impl, maps[i])
            continue
        try:
            # same zero-copy join as encode_many: the sub-read reply
            # payloads are bytes-like already
            streams = {s: b"".join(maps[i][s] for i in idxs)
                       for s in key}
            folded = memoryview(decode(sinfo, ec_impl, streams))
            off = 0
            for i in idxs:
                stream_len = len(next(iter(maps[i].values())))
                span = (stream_len // chunk) * width
                # view per request: the fold's output is sliced, not
                # re-copied, on its way back to each caller
                out[i] = folded[off:off + span]
                off += span
        except Exception:
            for i in idxs:
                out[i] = decode(sinfo, ec_impl, maps[i])
    return out  # type: ignore[return-value]


def decode(sinfo: StripeInfo, ec_impl,
           to_decode: Mapping[int, bytes]) -> bytes:
    """Per-shard chunk streams -> the original logical byte stream."""
    assert to_decode
    chunk = sinfo.get_chunk_size()
    width = sinfo.get_stripe_width()
    k = width // chunk
    total = len(next(iter(to_decode.values())))
    assert total % chunk == 0
    for buf in to_decode.values():
        assert len(buf) == total
    if total == 0:
        return b""
    n_stripes = total // chunk

    have = tuple(sorted(to_decode))
    want = tuple(range(k))
    erased = tuple(i for i in want if i not in to_decode)
    if not erased and not ec_impl.get_chunk_mapping():
        cols = [np.frombuffer(to_decode[i], dtype=np.uint8).reshape(
            n_stripes, chunk) for i in range(k)]
        # the stack IS the interleave; hand out a frozen view of it
        # instead of paying tobytes (a second whole-object pass)
        full = np.stack(cols, axis=1)
        full.setflags(write=False)
        return full.reshape(-1).data
    if hasattr(ec_impl, "decode_batch") and not ec_impl.get_chunk_mapping() \
            and len(have) >= k:
        survivors = np.stack([
            np.frombuffer(to_decode[i], dtype=np.uint8).reshape(
                n_stripes, chunk)
            for i in have[:k]], axis=1)             # (B, k, chunk)
        recovered = ec_impl.decode_batch(have[:k], erased, survivors)
        cols = []
        for i in range(k):
            if i in to_decode:
                cols.append(np.frombuffer(
                    to_decode[i], dtype=np.uint8).reshape(n_stripes, chunk))
            else:
                cols.append(np.asarray(recovered[:, erased.index(i), :]))
        full = np.stack(cols, axis=1)
        full.setflags(write=False)
        return full.reshape(-1).data

    from ceph_tpu.common.buffer import as_buffer

    out = []
    # slice views, not byte ranges: one memoryview per stream, every
    # per-stripe chunk a zero-copy window of it (as_buffer adapts
    # StridedBuf shards with their one cached materialization)
    views = {i: memoryview(as_buffer(buf))
             for i, buf in to_decode.items()}
    for s in range(n_stripes):
        chunks = {i: mv[s * chunk:(s + 1) * chunk]
                  for i, mv in views.items()}
        row = ec_impl.decode_concat(chunks)
        assert len(row) == width
        out.append(row)
    return b"".join(out)


class HashInfo:
    """Cumulative per-shard crc32c ledger (ECUtil.h:101-160)."""

    def __init__(self, num_chunks: int = 0):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes: List[int] = [0xFFFFFFFF] * num_chunks
        self.projected_total_chunk_size = 0

    def append(self, old_size: int, to_append: Mapping[int, bytes]) -> None:
        assert old_size == self.total_chunk_size
        appended = 0
        for shard, buf in to_append.items():
            appended = len(buf)
            if self.has_chunk_hash():
                assert shard < len(self.cumulative_shard_hashes)
                self.cumulative_shard_hashes[shard] = cks.crc32c(
                    self.cumulative_shard_hashes[shard], buf)
        self.total_chunk_size += appended

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [
            0xFFFFFFFF] * len(self.cumulative_shard_hashes)

    def get_chunk_hash(self, shard: int) -> int:
        assert shard < len(self.cumulative_shard_hashes)
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def get_total_logical_size(self, sinfo: StripeInfo) -> int:
        return self.total_chunk_size * (
            sinfo.get_stripe_width() // sinfo.get_chunk_size())

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def set_total_chunk_size_clear_hash(self, new_chunk_size: int) -> None:
        self.cumulative_shard_hashes = []
        self.total_chunk_size = new_chunk_size

    # -- wire/xattr form --------------------------------------------------

    def to_dict(self) -> dict:
        return {"total_chunk_size": self.total_chunk_size,
                "cumulative_shard_hashes": list(self.cumulative_shard_hashes)}

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        hi = cls(0)
        hi.total_chunk_size = int(d["total_chunk_size"])
        hi.cumulative_shard_hashes = [
            int(x) for x in d["cumulative_shard_hashes"]]
        return hi

"""Primary-side coded-compute engine (the MOSDCompute op body).

The hedged-read pattern applied to computation itself (ROADMAP item
5; ceph_tpu/compute has the algebra): a client names a kernel + many
oids, and the primary

* groups the wave per PG and, for GF-LINEAR kernels on codecs whose
  shards satisfy the position-wise code relation
  (`supports_result_decode`), fans ONE sub-compute op per acting OSD
  covering every object in the wave.  Each OSD evaluates the kernel
  over ALL its local shards of the wave in one plan-cached device
  dispatch (`compute` plan kind) and returns R bytes per shard — the
  payloads never move.  The fan-out rides the PR-6 HedgeTracker with
  need=k: the FIRST k same-version shard-results complete each
  object, stragglers recruit spares at their p95 mark and are
  cancelled cleanly, and the decode happens in the RESULT DOMAIN — a
  tiny GF combine of k R-byte vectors through the same
  ec_util.decode path the data plane uses, at lane width.

* for NONLINEAR kernels (record aggregates, entropy/dot scoring) —
  and for codecs/pools outside the commutation gate — takes the
  FULL-DECODE FALLBACK: reconstruct each object through the normal
  hedged first-k read and evaluate on the logical bytes.  Results,
  not payloads, still cross the client wire.

Lock order: the fallback evaluates under the per-object CLS lock and
THEN the object lock — the same `osd.clslock` -> `osd.objlock` order
`_op_call`'s registered methods take dynamically.  Taking it here, in
statically visible nesting, puts the edge in the lint-time lock-order
graph (ceph_tpu/analysis/lockgraph.py) so the runtime⊆static
cross-check needs no dynamic-dispatch baseline entry for it.

Scheduling: compute ops run under the dedicated `compute` mClock
class and the tenant admission gate (the daemon wires both before
`execute`), so a scan storm cannot starve client I/O.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu import compute as compute_mod
from ceph_tpu.common import tracing
from ceph_tpu.compute import ComputeError, ComputeKernel
from ceph_tpu.compute import kernels as compute_kernels
from ceph_tpu.crush.map import CRUSH_ITEM_NONE
from ceph_tpu.msg.messages import MOSDSubCompute
from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.osdmap import PgId, TYPE_ERASURE
from ceph_tpu.osd.pg_log import ZERO, ev
from ceph_tpu.rados.embedded import OI_ATTR

log = logging.getLogger("osd.compute")

EAGAIN = -11
ENOENT = -2
EIO = -5
EBUSY = -16
EINVAL = -22

#: concurrent full-decode evaluations per wave (each one is a hedged
#: EC read; unbounded fan-out would monopolize the sub-read paths)
FALLBACK_CONCURRENCY = 8


def _codec_pushdown_ok(codec) -> bool:
    fn = getattr(codec, "supports_result_decode", None)
    return bool(fn()) if callable(fn) else False


class ComputeEngine:
    """One per daemon: wave orchestration + local shard evaluation."""

    def __init__(self, daemon):
        self.d = daemon
        self.counters: Dict[str, int] = {
            "ops": 0, "objects": 0, "pushdown_objects": 0,
            "fallback_objects": 0, "waves": 0, "result_bytes": 0,
            "subcompute_items": 0, "errors": 0,
        }

    def perf(self) -> Dict[str, Any]:
        return dict(self.counters)

    # -- client op body (runs under the compute mClock class) --------------

    async def execute(self, msg) -> Tuple[int, Dict[str, Tuple[int, bytes]],
                                          Dict[str, Any]]:
        d = self.d
        kern = compute_mod.get_kernel(msg.kernel)
        if kern is None:
            return EINVAL, {}, {"error": f"unknown kernel {msg.kernel!r}"}
        try:
            args = compute_kernels.parse_args(msg.args)
            kern.validate_args(args)
        except ComputeError as e:
            return e.rc, {}, {"error": str(e)}
        pool = d.osdmap.pools.get(msg.pool) if d.osdmap else None
        if pool is None:
            return EAGAIN, {}, {}
        self.counters["ops"] += 1
        results: Dict[str, Tuple[int, bytes]] = {}
        by_pg: Dict[PgId, List[str]] = {}
        from ceph_tpu.osd.daemon import is_internal_name

        for oid in dict.fromkeys(msg.oids):
            if not oid or is_internal_name(oid):
                results[oid] = (EINVAL, b"")
                continue
            raw = PgId(pool.id, ceph_str_hash_rjenkins(oid.encode()))
            by_pg.setdefault(pool.raw_pg_to_pg(raw), []).append(oid)
        pushdown = fallback = 0

        async def run_pg(pg: PgId, oids: List[str]
                         ) -> Tuple[bool, Dict[str, Tuple[int,
                                                          bytes]]]:
            state = d.pgs.get(pg)
            if state is None or state.primary != d.osd_id:
                return False, {oid: (EAGAIN, b"") for oid in oids}
            if state.state != "active":
                try:
                    await asyncio.wait_for(state.active_event.wait(),
                                           10.0)
                except asyncio.TimeoutError:
                    return False, {oid: (EAGAIN, b"")
                                   for oid in oids}
            # per-kernel capability, not a blanket nonlinear gate:
            # GF-linear kernels push down exactly; approx_capable
            # kernels (inference/) push down with a result-domain
            # approximate combine of their own
            use_push = False
            if pool.type == TYPE_ERASURE and (
                    kern.linear or kern.approx_capable):
                use_push = _codec_pushdown_ok(d._codec(pool.id))
            self.counters["waves"] += 1
            if use_push and not kern.linear:
                # approx_capable pushdown: the inference engine owns
                # the per-shard fan-out and the Fisher result-domain
                # combine (a GF decode of nonlinear results would be
                # meaningless)
                return True, await d.inference.wave(
                    state, pool, oids, kern, msg.args, args)
            if use_push:
                return True, await self._wave_pushdown(
                    state, pool, oids, kern, msg.args, args)
            return False, await self._wave_fallback(
                state, pool, oids, kern, args)

        # per-PG waves run concurrently: each wave's sub-compute
        # fan-out is already parallel across its acting set, and
        # overlapping the waves hides the per-PG round trips (the
        # scan is one op — it must not serialize on PG count)
        groups = sorted(by_pg.items(),
                        key=lambda kv: (kv[0].pool, kv[0].ps))
        waves = await asyncio.gather(
            *(run_pg(pg, oids) for pg, oids in groups))
        for pushed, wave in waves:
            good = sum(1 for rc, _r in wave.values() if rc == 0)
            if pushed:
                pushdown += good
            else:
                fallback += good
            results.update(wave)
        self.counters["objects"] += len(results)
        self.counters["pushdown_objects"] += pushdown
        self.counters["fallback_objects"] += fallback
        self.counters["errors"] += sum(1 for rc, _r in results.values()
                                       if rc not in (0, ENOENT))
        self.counters["result_bytes"] += sum(
            len(r) for rc, r in results.values() if rc == 0)
        out = {"kernel": msg.kernel, "pushdown": pushdown,
               "fallback": fallback,
               "result_bytes": sum(len(r) for rc, r in results.values()
                                   if rc == 0)}
        return 0, results, out

    # -- the pushdown wave (linear kernels over coded shards) --------------

    async def _wave_pushdown(self, state, pool, oids: List[str],
                             kern: ComputeKernel, args_raw: str,
                             args: Dict[str, Any]
                             ) -> Dict[str, Tuple[int, bytes]]:
        d = self.d
        pg = state.pg
        codec = d._codec(pool.id)
        k = codec.get_data_chunk_count()
        jobs: List[Tuple[int, Any]] = []
        for idx, osd in enumerate(state.acting):
            if osd == CRUSH_ITEM_NONE or not d.osdmap.is_up(osd):
                continue

            def job(shard=idx, osd=osd):
                return self._shard_job(pg, shard, osd, oids,
                                       kern, args_raw, args)

            jobs.append((osd, job))
        if len(jobs) < k:
            # below-k up members can never complete an object: an
            # explicit retry, not a false ENOENT
            return {oid: (EAGAIN, b"") for oid in oids}

        def collate(raw) -> Dict[str, Dict[str, Dict[int, bytes]]]:
            """(shard, ok, items) results -> oid -> version ->
            {shard: result}."""
            acc: Dict[str, Dict[str, Dict[int, bytes]]] = {}
            for shard, ok, items in raw:
                if not ok:
                    continue
                for oid, (rc, ver, res) in zip(oids, items):
                    if rc == 0:
                        acc.setdefault(oid, {}).setdefault(
                            ver, {})[shard] = res
            return acc

        def indefinite(raw) -> Tuple[bool, set]:
            """(any flight failed, oids with a non-ENOENT shard
            error): evidence that an empty candidate set proves
            NOTHING about absence — those oids answer EAGAIN, never
            ENOENT (the MissingLoc have-vs-unfound distinction)."""
            any_fail = False
            problem: set = set()
            for _shard, ok, items in raw:
                if not ok:
                    any_fail = True
                    continue
                for oid, (rc, _ver, _res) in zip(oids, items):
                    if rc not in (0, ENOENT):
                        problem.add(oid)
            return any_fail, problem

        def sufficient(raw) -> bool:
            acc = collate(raw)
            return all(
                any(len(shards) >= k for shards in acc.get(
                    oid, {}).values())
                for oid in oids)

        raw, ran_all = await d.hedge.gather(
            jobs, need=k, sufficient=sufficient,
            failed=lambda res: not res[1], label="subcompute")
        acc = collate(raw)
        any_fail, problem = indefinite(raw)
        rsinfo = ec_util.StripeInfo(k, k * kern.lanes)
        out: Dict[str, Tuple[int, bytes]] = {}
        picked: List[Tuple[str, Dict[int, bytes]]] = []
        for oid in oids:
            groups = {v: shards for v, shards in
                      acc.get(oid, {}).items() if len(shards) >= k}
            if not groups:
                # absence must be PROVEN: a failed flight, an early
                # (hedged) exit, or any shard-level error leaves the
                # question open — the client retries instead of
                # recording a live object as missing
                definite = ran_all and not any_fail and \
                    oid not in problem and not acc.get(oid)
                out[oid] = (ENOENT if definite else EAGAIN, b"")
                continue
            ver = max(groups, key=self._ver_key)
            try:
                # acked-write invariant: a k-group at a version older
                # than the newest acked one (its holders down, stale
                # shards answering) must not serve — same guard as
                # the read path's _require_fresh
                d._require_fresh(state, pool, oid, self._ver_key(ver))
            except Exception:
                out[oid] = (EAGAIN, b"")
                continue
            try:
                picked.append((oid, ec_util.fastest_survivors(
                    codec, groups[ver], k,
                    prefer=d._shard_rank(state))))
            except Exception:
                out[oid] = (EIO, b"")
        # ONE result-domain decode per survivor-set group, not per
        # object: decode_many concatenates same-survivor-set result
        # vectors and GF-combines the whole wave in one dispatch (the
        # recovery-wave fold, at lane width) — a per-object decode
        # would pay a guarded device round trip per 32-byte vector
        async with tracing.child_span(
                f"compute decode x{len(picked)}"):
            decoded = await asyncio.to_thread(
                self._result_decode_many, rsinfo, codec,
                [chosen for _oid, chosen in picked])
        for (oid, _chosen), dec in zip(picked, decoded):
            if dec is None:
                log.error("osd.%d: result-domain decode failed for "
                          "%s/%s", d.osd_id, pg, oid)
                out[oid] = (EIO, b"")
                continue
            view = memoryview(dec)
            parts = [view[i * kern.lanes:(i + 1) * kern.lanes]
                     for i in range(k)]
            out[oid] = (0, kern.combine(parts))
        return out

    @staticmethod
    def _result_decode_many(rsinfo, codec, maps: List[Dict[int,
                                                           bytes]]
                            ) -> List[Optional[bytes]]:
        """Batched result-domain decode with per-object isolation: a
        wave-level failure retries each object alone, and a single
        bad object costs only its own result."""
        if not maps:
            return []
        try:
            return list(ec_util.decode_many(rsinfo, codec, maps))
        except Exception:
            out: List[Optional[bytes]] = []
            for m in maps:
                try:
                    out.append(ec_util.decode(rsinfo, codec, m))
                except Exception:
                    out.append(None)
            return out

    @staticmethod
    def _ver_key(ver: str):
        try:
            return ev(ver)
        except Exception:
            return ZERO

    async def _shard_job(self, pg: PgId, shard: int, osd: int,
                         oids: List[str], kern: ComputeKernel,
                         args_raw: str, args: Dict[str, Any]
                         ) -> Tuple[int, bool, List[Tuple[int, str,
                                                          bytes]]]:
        """One acting member's sub-compute: local shards evaluate in
        process (same batched path the remote handler uses); remote
        shards ride MOSDSubCompute.  Returns (shard, ok, items) —
        ok=False is a transport fault the hedged gather treats as a
        failed flight (recruit a spare now)."""
        import time as _time

        d = self.d
        t0 = _time.monotonic()
        if osd == d.osd_id:
            items = [(pg, shard, oid) for oid in oids]
            out = await self.eval_local_shards(items, kern, args)
            # the local eval feeds the EWMA too: self ranks by its
            # actual store+eval latency, not a synthetic zero
            d.hedge.observe(osd, _time.monotonic() - t0)
            return shard, True, out
        tid = d._next_tid()
        msg = MOSDSubCompute(
            tid, kern.name, args_raw,
            [(pg.pool, pg.ps, shard, oid) for oid in oids],
            d._epoch())
        reply = await d._request(osd, msg, tid)
        # every sub-compute round trip feeds the per-peer latency
        # model (sub-compute jobs cost eval time, not just payload
        # RTT — without this the p95 marks stay at the sub-read
        # prior and every wave hedges spuriously)
        ok = reply is not None and reply.rc == 0 and \
            len(reply.results) == len(oids)
        d.hedge.observe(osd, _time.monotonic() - t0, ok=ok)
        if not ok:
            return shard, False, []
        self.counters["subcompute_items"] += len(reply.results)
        # results stay views of the reply frame (lane-width each)
        return shard, True, list(reply.results)

    def _shard_missing(self, pg: PgId, shard: int, oid: str) -> bool:
        """True when this OSD's CURRENT shard of the object is in its
        own pg-log missing set (a behind/backfilling copy whose
        on-disk bytes predate acked writes)."""
        d = self.d
        state = d.pgs.get(pg)
        pool = d.osdmap.pools.get(pg.pool) if d.osdmap else None
        if state is None or pool is None:
            return False
        if shard != state.my_shard(d.osd_id, pool.type):
            return False
        try:
            return oid in d._load_log(state, pool).missing
        except Exception:
            return False

    # -- local shard evaluation (primary's own shard AND the replica
    #    handler's body) ----------------------------------------------------

    async def eval_local_shards(
            self, items: List[Tuple[PgId, int, str]],
            kern: ComputeKernel, args: Dict[str, Any]
    ) -> List[Tuple[int, str, bytes]]:
        """Kernel-evaluate every locally held shard of a wave: reads
        stay on the event loop (store reads are memory-speed), the
        batched kernel dispatch runs off-loop — ONE plan-cached
        device call for all same-length shards of the wave."""
        d = self.d
        metas: List[Tuple[int, str]] = []
        payloads: List[Any] = []
        rows: List[Optional[int]] = []
        for pg, shard, oid in items:
            if self._shard_missing(pg, shard, oid):
                # the missing guard of _handle_sub_read_inner: my
                # CURRENT shard of an object in my missing set is
                # known-stale on disk — serving its kernel result
                # could complete the object at a rolled-back version
                metas.append((ENOENT, ""))
                rows.append(None)
                continue
            rc, data, at = d._read_shard(pg, shard, oid)
            ver = ""
            if rc == 0:
                try:
                    oi = json.loads(at[OI_ATTR])
                    ver = str(oi.get("version") or "")
                    if oi.get("whiteout"):
                        rc = ENOENT
                except (KeyError, ValueError):
                    rc = EIO
            if rc != 0:
                metas.append((rc, ""))
                rows.append(None)
                continue
            metas.append((0, ver))
            rows.append(len(payloads))
            payloads.append(data)
        if payloads:
            # the mClock grant covers exactly the batched eval — the
            # stage that contends with client I/O for CPU/device time.
            # An op slot is NOT held across the wave's remote round
            # trips (a parked scan must never occupy the op queue's
            # in-flight slots while it waits on peers).
            async with tracing.child_span(
                    f"compute eval {kern.name} x{len(payloads)}"):
                evaluated = await d.scheduler.run(
                    kern.qos_class, 1.0 + len(payloads) / 256.0,
                    lambda: asyncio.to_thread(
                        kern.shard_eval, payloads, args))
        else:
            evaluated = []
        out: List[Tuple[int, str, bytes]] = []
        for (rc, ver), row in zip(metas, rows):
            out.append((rc, ver,
                        evaluated[row] if row is not None else b""))
        return out

    # -- the full-decode fallback (nonlinear kernels / unsupported
    #    codecs) -------------------------------------------------------------

    async def _wave_fallback(self, state, pool, oids: List[str],
                             kern: ComputeKernel,
                             args: Dict[str, Any]
                             ) -> Dict[str, Tuple[int, bytes]]:
        d = self.d
        if pool.type == TYPE_ERASURE:
            sinfo = d._sinfo(pool.id)
            k = d._codec(pool.id).get_data_chunk_count()
            chunk = sinfo.get_chunk_size()
        else:
            k, chunk = 1, 0
        sem = asyncio.Semaphore(FALLBACK_CONCURRENCY)

        async def one(oid: str) -> Tuple[int, bytes]:
            async with sem:
                # cls-ordered locking: serialize against object-class
                # RMW methods (cls lock) and in-flight writes (object
                # lock) so the kernel sees ONE committed version —
                # and the clslock -> objlock order is statically
                # visible here (see module docstring)
                async with state.obj_lock(f"_cls_\x00{oid}"):
                    async with state.obj_lock(oid):
                        rc, data = await d._op_read(state, pool, oid,
                                                    0, 0)
                        if rc != 0:
                            return rc, b""
                        from ceph_tpu.osd import (
                            scheduler as sched_mod,
                        )

                        async with tracing.child_span(
                                f"compute eval {kern.name}"):
                            try:
                                # the eval charges the kernel's mClock
                                # class (the CPU stage; the hedged
                                # read above holds no op slot)
                                res = await d.scheduler.run(
                                    kern.qos_class, 1.0,
                                    lambda: asyncio.to_thread(
                                        kern.reference, data, args,
                                        k, chunk))
                            except ComputeError as e:
                                return e.rc, b""
                            except asyncio.CancelledError:
                                raise
                            except sched_mod.QueueFull:
                                return EBUSY, b""
                            except Exception:
                                log.exception(
                                    "osd.%d: kernel %s failed on %r",
                                    d.osd_id, kern.name, oid)
                                return EIO, b""
                        return 0, res

        done = await asyncio.gather(*(one(oid) for oid in oids))
        return dict(zip(oids, done))

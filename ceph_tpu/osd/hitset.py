"""HitSet: hot-set tracking for the read tier.

Reference parity: HitSet (/root/reference/src/osd/HitSet.h:35) — a
probabilistic set of recently-touched objects, persisted per PG as a
decaying stack of N sets rotated on a period, consumed by the tiering
agent's promote/evict decisions (PrimaryLogPG::hit_set_* and the agent
in PrimaryLogPG.cc).  Two implementations, like the reference:

- BloomHitSet   (compressible_bloom_filter role): fixed false-positive
  budget, constant memory;
- ExplicitHashHitSet: exact 32-bit hash set (the small-PG fallback).

The substrate twist: bloom insert/contains run over the SAME
vectorized rjenkins kernels CRUSH placement uses (ops/rjenkins.py
`hash32_2(..., xp)`), so a batch of object hashes maps to its k bloom
bit positions in ONE device dispatch (`xp=jnp`, jitted through the
plan cache's tracked_jit for retrace observability), with the numpy
host path (`xp=np`) producing bit-identical positions — uint32
wraparound math is exact on both.  Off-device (no jax) everything runs
on the host path.

Object names enter as the same 32-bit Jenkins string hash the PG
mapper uses (`ceph_str_hash_rjenkins`), so the hot-set key space is
the reference's hobject hash space.
"""

from __future__ import annotations

import base64
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ceph_tpu.ops import rjenkins

try:  # pragma: no cover - exercised via the device path tests
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

# batches below this hash on the host: a device dispatch per handful
# of oids costs more latency than it saves lanes
DEVICE_MIN_BATCH = 8

_LN2 = float(np.log(2.0))


def hash_oid(oid: str) -> int:
    """Object name -> the 32-bit Jenkins hash the PG mapper uses
    (hobject_t::get_hash role): one hash space for placement and
    hot-set tracking."""
    return rjenkins.ceph_str_hash_rjenkins(oid.encode())


def bloom_geometry(target_size: int, fpp: float) -> tuple:
    """(nbits, nhash) for `target_size` insertions at false-positive
    probability `fpp` (the standard Bloom sizing the reference's
    bloom_filter::compute_optimal_parameters performs)."""
    n = max(int(target_size), 1)
    p = min(max(float(fpp), 1e-9), 0.5)
    nbits = int(np.ceil(-n * np.log(p) / (_LN2 * _LN2)))
    nbits = max(nbits, 8)
    nhash = max(1, int(round(nbits / n * _LN2)))
    return nbits, min(nhash, 32)


def bloom_positions(hashes, nbits: int, nhash: int, xp=np):
    """[B] uint32 oid hashes -> [B, nhash] uint32 bloom bit positions.

    Every op is elementwise uint32-lane work through the rjenkins mix,
    so with xp=jnp the whole batch maps in one fused device dispatch;
    xp=np is the bit-exact host oracle.  Position i uses seed i (the
    per-probe salt), mixed through the same hash32_2 kernel CRUSH
    bulk placement vmaps."""
    h = xp.asarray(hashes).astype(xp.uint32).reshape(-1, 1)
    seeds = xp.arange(nhash, dtype=xp.uint32).reshape(1, -1)
    return (rjenkins.hash32_2(h, seeds, xp=xp)
            % xp.uint32(nbits)).astype(xp.uint32)


_device_fns: Dict[tuple, Any] = {}


def _device_positions(hashes: np.ndarray, nbits: int,
                      nhash: int) -> Optional[np.ndarray]:
    """Device-batched positions: one jitted dispatch per pow2-bucketed
    batch (shape churn would retrace per unique batch size).  The
    dispatch rides the hitset-hash breaker guard; None means the
    device tier is degraded and the caller hashes on the host — the
    xp=np path is bit-identical, so a tripped breaker costs lanes,
    never correctness."""
    from ceph_tpu.common import circuit
    from ceph_tpu.ec import plan

    key = (nbits, nhash)
    fn = _device_fns.get(key)
    if fn is None:
        def impl(h):
            return bloom_positions(h, nbits, nhash, xp=jnp)

        fn = plan.tracked_jit(f"hitset_bloom_b{nbits}_k{nhash}", impl)
        _device_fns[key] = fn
    n = len(hashes)
    cap = plan.bucket_batch(n)
    if cap > n:
        # pad with the last element: duplicate inserts/queries are
        # idempotent and the tail is sliced off below
        hashes = np.concatenate(
            [hashes, np.full(cap - n, hashes[-1], dtype=np.uint32)])

    def run(h):
        return np.asarray(fn(jnp.asarray(h)))

    status, out = circuit.device_call(
        "hitset-hash", run, hashes, batch=cap,
        label=f"hitset b{nbits} k{nhash}", oom_to_fail=True)
    return out[:n] if status == "ok" else None


def positions_for(hashes, nbits: int, nhash: int,
                  device: Optional[bool] = None) -> np.ndarray:
    """Dispatch policy: device for real batches when jax is present
    and the hitset-hash breaker is closed, host otherwise.  Both
    paths are bit-exact."""
    arr = np.asarray(hashes, dtype=np.uint32).reshape(-1)
    if arr.size == 0:
        return np.zeros((0, nhash), dtype=np.uint32)
    if device is None:
        device = HAVE_JAX and arr.size >= DEVICE_MIN_BATCH
    if device and HAVE_JAX:
        from ceph_tpu.common import circuit

        if not circuit.degraded("hitset-hash"):
            out = _device_positions(arr, nbits, nhash)
            if out is not None:
                return out
        else:
            circuit.breaker("hitset-hash").note_fallback()
    return bloom_positions(arr, nbits, nhash, xp=np)


class BloomHitSet:
    """Bloom-filter hit set (HitSet.h:117 BloomHitSet role)."""

    kind = "bloom"

    def __init__(self, target_size: int = 1024, fpp: float = 0.05,
                 nbits: Optional[int] = None,
                 nhash: Optional[int] = None):
        self.target_size = int(target_size)
        self.fpp = float(fpp)
        if nbits is None or nhash is None:
            nbits, nhash = bloom_geometry(target_size, fpp)
        self.nbits = int(nbits)
        self.nhash = int(nhash)
        self.bits = np.zeros((self.nbits + 7) // 8, dtype=np.uint8)
        self.count = 0  # insertions (unique-ish; callers dedup)

    # -- insert / query ----------------------------------------------------

    def insert_batch(self, hashes,
                     device: Optional[bool] = None) -> None:
        arr = np.asarray(hashes, dtype=np.uint32).reshape(-1)
        if arr.size == 0:
            return
        pos = positions_for(arr, self.nbits, self.nhash,
                            device=device).reshape(-1)
        # scatter-OR on the host bitset (reads must answer
        # synchronously; the device's job was the hashing lanes)
        np.bitwise_or.at(self.bits, pos >> 3,
                         (1 << (pos & 7)).astype(np.uint8))
        self.count += int(arr.size)

    def insert(self, h: int) -> None:
        self.insert_batch([h], device=False)

    def contains_batch(self, hashes,
                       device: Optional[bool] = None) -> np.ndarray:
        arr = np.asarray(hashes, dtype=np.uint32).reshape(-1)
        if arr.size == 0:
            return np.zeros(0, dtype=bool)
        pos = positions_for(arr, self.nbits, self.nhash, device=device)
        got = (self.bits[pos >> 3] >> (pos & 7)) & 1
        return got.all(axis=1)

    def contains(self, h: int) -> bool:
        return bool(self.contains_batch([h], device=False)[0])

    # -- wire --------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "target_size": self.target_size,
                "fpp": self.fpp, "nbits": self.nbits,
                "nhash": self.nhash, "count": self.count,
                "bits": base64.b64encode(self.bits.tobytes()).decode()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BloomHitSet":
        hs = cls(d.get("target_size", 1024), d.get("fpp", 0.05),
                 nbits=d["nbits"], nhash=d["nhash"])
        hs.count = int(d.get("count", 0))
        raw = np.frombuffer(base64.b64decode(d["bits"]),
                            dtype=np.uint8)
        hs.bits = raw.copy()
        return hs


class ExplicitHashHitSet:
    """Exact 32-bit-hash hit set (HitSet.h ExplicitHashHitSet role)."""

    kind = "explicit_hash"

    def __init__(self, target_size: int = 1024, fpp: float = 0.0):
        self.target_size = int(target_size)
        self.hashes: set = set()

    @property
    def count(self) -> int:
        return len(self.hashes)

    def insert_batch(self, hashes,
                     device: Optional[bool] = None) -> None:
        arr = np.asarray(hashes, dtype=np.uint32).reshape(-1)
        self.hashes.update(int(x) for x in arr)

    def insert(self, h: int) -> None:
        self.hashes.add(int(np.uint32(h)))

    def contains_batch(self, hashes,
                       device: Optional[bool] = None) -> np.ndarray:
        arr = np.asarray(hashes, dtype=np.uint32).reshape(-1)
        return np.array([int(x) in self.hashes for x in arr],
                        dtype=bool)

    def contains(self, h: int) -> bool:
        return int(np.uint32(h)) in self.hashes

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "target_size": self.target_size,
                "count": self.count,
                "hashes": sorted(self.hashes)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExplicitHashHitSet":
        hs = cls(d.get("target_size", 1024))
        hs.hashes = {int(x) for x in d.get("hashes", ())}
        return hs


_KINDS = {BloomHitSet.kind: BloomHitSet,
          ExplicitHashHitSet.kind: ExplicitHashHitSet}


def hitset_from_dict(d: Dict[str, Any]):
    return _KINDS[d["kind"]].from_dict(d)


class HitSetStack:
    """Per-PG decaying stack of hit sets (pg_pool_t hit_set_count /
    hit_set_period role).

    The OPEN period keeps exact per-hash read counts (this doubles as
    the read-frequency histogram source); `rotate()` seals it into a
    bloom/explicit set via ONE device-batched insert and pushes it on
    the archive, discarding the oldest beyond `count` (the decay).
    `hit_count()` answers "in how many recent periods was this object
    read" — the promote signal — as open-presence + archived
    membership."""

    def __init__(self, count: int = 4, period: float = 10.0,
                 target_size: int = 1024, fpp: float = 0.05,
                 kind: str = "bloom"):
        self.count = max(int(count), 1)
        self.period = float(period)
        self.target_size = int(target_size)
        self.fpp = float(fpp)
        self.kind = kind if kind in _KINDS else "bloom"
        self.open_counts: Dict[int, int] = {}
        self.archived: List[Any] = []
        self.opened = time.monotonic()
        self.seq = 0          # rotation sequence (persistence key)

    # -- recording ---------------------------------------------------------

    def insert(self, h: int) -> None:
        h = int(np.uint32(h))
        self.open_counts[h] = self.open_counts.get(h, 0) + 1

    def due(self, now: Optional[float] = None) -> bool:
        if self.period <= 0:
            return False
        return (now if now is not None
                else time.monotonic()) - self.opened >= self.period

    def rotate(self) -> Any:
        """Seal the open period into an archived set (one batched
        device insert for every unique hash of the period) and reset.
        Returns the sealed set (caller persists it)."""
        sealed = _KINDS[self.kind](self.target_size, self.fpp)
        if self.open_counts:
            sealed.insert_batch(
                np.fromiter(self.open_counts.keys(), dtype=np.uint32,
                            count=len(self.open_counts)))
        self.archived.append(sealed)
        # keep count-1 archived: open + archived = count sets total
        # (count=1 keeps NO archive — the open set is the whole window)
        while len(self.archived) > max(self.count - 1, 0):
            self.archived.pop(0)
        self.open_counts = {}
        self.opened = time.monotonic()
        self.seq += 1
        return sealed

    # -- queries -----------------------------------------------------------

    def open_count(self, h: int) -> int:
        return self.open_counts.get(int(np.uint32(h)), 0)

    def hit_count(self, h: int) -> int:
        """Recency: number of sets (open + archived) containing h.
        The open set contributes its exact read count so a burst of
        reads inside one period still registers as hot — on this flat
        substrate the tier's job is absorbing skew, not aging data
        across hours (the COVERAGE.md redesign note)."""
        h = int(np.uint32(h))
        n = self.open_counts.get(h, 0)
        for s in self.archived:
            if s.contains(h):
                n += 1
        return n

    def read_frequencies(self) -> List[int]:
        """Per-object read counts of the open period (histogram feed)."""
        return list(self.open_counts.values())

    def dump(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "count": self.count,
            "period": self.period,
            "seq": self.seq,
            "open": {"objects": len(self.open_counts),
                     "reads": sum(self.open_counts.values()),
                     "age": round(time.monotonic() - self.opened, 3)},
            "archived": [{"kind": s.kind, "count": s.count}
                         for s in self.archived],
        }

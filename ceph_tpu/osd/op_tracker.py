"""OpTracker: in-flight op registry + historic ring + slow-op warnings.

Reference parity: TrackedOp/OpTracker
(/root/reference/src/common/TrackedOp.h) — every client op is wrapped
in a tracked record with an event timeline; `dump_ops_in_flight` and
`dump_historic_ops` are served over the admin socket, and ops older
than the warn threshold raise slow-op warnings (the
`osd_op_complaint_time` discipline).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

log = logging.getLogger("osd")


class TrackedOp:
    __slots__ = ("description", "start", "events", "warned")

    def __init__(self, description: str):
        self.description = description
        self.start = time.monotonic()
        self.events: List[tuple] = [(self.start, "initiated")]
        self.warned = False

    def mark(self, event: str) -> None:
        self.events.append((time.monotonic(), event))

    def age(self) -> float:
        return time.monotonic() - self.start

    def dump(self) -> Dict[str, Any]:
        return {
            "description": self.description,
            "age": round(self.age(), 6),
            "duration": round(self.events[-1][0] - self.start, 6),
            "events": [{"time": round(t - self.start, 6), "event": e}
                       for t, e in self.events],
        }


class OpTracker:
    """Bounded registry: live ops by id + a historic ring of completed
    ops (osd_op_history_size role)."""

    def __init__(self, history_size: int = 20,
                 complaint_time: float = 30.0,
                 who: str = "osd"):
        self._live: Dict[int, TrackedOp] = {}
        self._seq = 0
        self._history: deque = deque(maxlen=history_size)
        self.complaint_time = complaint_time
        self.who = who
        self.slow_ops = 0  # lifetime count of ops that breached
        # the admin-socket serve THREAD dumps while the event loop
        # mutates: every structural access takes this lock
        self._lock = threading.Lock()

    def create(self, description: str) -> int:
        with self._lock:
            self._seq += 1
            self._live[self._seq] = TrackedOp(description)
            return self._seq

    def mark(self, op_id: int, event: str) -> None:
        op = self._live.get(op_id)
        if op is not None:
            op.mark(event)

    def finish(self, op_id: int, event: str = "done") -> None:
        with self._lock:
            op = self._live.pop(op_id, None)
            if op is not None:
                op.mark(event)
                self._history.append(op)

    def check_slow(self) -> List[TrackedOp]:
        """Warn once per op that breaches the complaint threshold
        (the OpTracker check_ops_in_flight role)."""
        slow = []
        with self._lock:
            live = list(self._live.values())
        for op in live:
            if not op.warned and op.age() > self.complaint_time:
                op.warned = True
                self.slow_ops += 1
                slow.append(op)
                log.warning("%s: slow op (%.1fs >= %.1fs): %s",
                            self.who, op.age(), self.complaint_time,
                            op.description)
        return slow

    def dump_in_flight(self) -> Dict[str, Any]:
        with self._lock:
            ops = [op.dump() for op in list(self._live.values())]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic(self) -> Dict[str, Any]:
        with self._lock:
            ops = [op.dump() for op in list(self._history)]
        return {"num_ops": len(ops), "ops": ops,
                "slow_ops_total": self.slow_ops}

"""OpTracker: in-flight op registry + historic ring + slow-op warnings
+ tail-exemplar trace retention.

Reference parity: TrackedOp/OpTracker
(/root/reference/src/common/TrackedOp.h) — every client op is wrapped
in a tracked record with an event timeline; `dump_ops_in_flight` and
`dump_historic_ops` are served over the admin socket, and ops older
than the warn threshold raise slow-op warnings (the
`osd_op_complaint_time` discipline).

Tail-exemplar retention (the tracing layer's retention policy): ops
whose duration breaches `osd_op_complaint_time` OR the tracker's own
rolling p99 keep their FULL span tree + critical-path breakdown — in
the historic entry (dump_historic_ops shows the per-stage self-times)
and in a bounded by-trace-id ring served by `dump_op_trace`.  Head
sampling can be 0 and the tail still explains itself.

Locking: the admin-socket serve THREAD dumps while the event loop
mutates — every structural OR per-op mutation (create/mark/finish/
check_slow's warned flip) takes `_lock`, so a dump can never observe
a half-updated event list or double-count slow ops.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

log = logging.getLogger("osd")

#: how many tail-exemplar traces the by-trace-id ring keeps
EXEMPLAR_CAP = 32

#: rolling-p99 warmup: below this many completed ops the percentile
#: estimate is noise, so only the complaint threshold gates retention
P99_MIN_SAMPLES = 100


class TrackedOp:
    __slots__ = ("description", "start", "events", "warned",
                 "duration", "trace")

    def __init__(self, description: str):
        self.description = description
        self.start = time.monotonic()
        self.events: List[tuple] = [(self.start, "initiated")]
        self.warned = False
        self.duration: Optional[float] = None  # set at finish
        # tail exemplar: {"trace_id", "critical_path", "spans"} for
        # ops retained by the tail policy, else None
        self.trace: Optional[Dict[str, Any]] = None

    def mark(self, event: str) -> None:
        self.events.append((time.monotonic(), event))

    def age(self) -> float:
        return time.monotonic() - self.start

    def dump(self) -> Dict[str, Any]:
        out = {
            "description": self.description,
            "age": round(self.age(), 6),
            "duration": round(self.events[-1][0] - self.start, 6),
            "events": [{"time": round(t - self.start, 6), "event": e}
                       for t, e in self.events],
        }
        if self.trace is not None:
            out["trace_id"] = self.trace.get("trace_id", "")
            cp = self.trace.get("critical_path") or {}
            out["stages_us"] = dict(cp.get("stages", {}))
        return out


class OpTracker:
    """Bounded registry: live ops by id + a historic ring of completed
    ops (osd_op_history_size role) + the tail-exemplar trace ring."""

    def __init__(self, history_size: int = 20,
                 complaint_time: float = 30.0,
                 who: str = "osd"):
        self._live: Dict[int, TrackedOp] = {}
        self._seq = 0
        self._history: deque = deque(maxlen=history_size)
        self.complaint_time = complaint_time
        self.who = who
        self.slow_ops = 0  # lifetime count of ops that breached
        self.ops_total = 0  # lifetime ops created
        self._lock = threading.Lock()
        # rolling op-duration histogram: the p99 the tail-exemplar
        # policy compares against (constant memory, loadgen/stats.py)
        from ceph_tpu.loadgen.stats import LatencyHistogram

        self._durations = LatencyHistogram()
        # trace_id (hex) -> exemplar doc; LRU-bounded
        self._exemplars: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()
        self.tail_exemplars = 0  # lifetime retained

    def create(self, description: str) -> int:
        with self._lock:
            self._seq += 1
            self.ops_total += 1
            self._live[self._seq] = TrackedOp(description)
            return self._seq

    def mark(self, op_id: int, event: str) -> None:
        with self._lock:
            op = self._live.get(op_id)
            if op is not None:
                op.mark(event)

    def finish(self, op_id: int,
               event: str = "done") -> Optional[TrackedOp]:
        """Retire a live op into the historic ring; returns the op (its
        `duration` now set, fed to the rolling histogram) so the
        caller can decide tail retention."""
        with self._lock:
            op = self._live.pop(op_id, None)
            if op is not None:
                op.mark(event)
                op.duration = op.events[-1][0] - op.start
                self._durations.record(op.duration)
                self._history.append(op)
            return op

    # -- tail-exemplar policy ---------------------------------------------

    def is_tail(self, duration: Optional[float]) -> bool:
        """Does this completed op belong to the tail worth explaining?
        True past `osd_op_complaint_time`, or past the rolling p99
        once enough samples exist for the estimate to mean anything."""
        if duration is None:
            return False
        if duration >= self.complaint_time:
            return True
        with self._lock:
            if self._durations.count < P99_MIN_SAMPLES:
                return False
            p99 = self._durations.percentile(0.99)
        return p99 is not None and duration >= p99

    def retain_trace(self, op: TrackedOp,
                     doc: Dict[str, Any]) -> None:
        """Attach a tail exemplar ({"trace_id", "critical_path",
        "spans"}) to a finished op and index it by trace id for
        dump_op_trace.  The historic ring holds the same doc, so
        dump_historic_ops shows the per-stage breakdown."""
        with self._lock:
            op.trace = doc
            tid = doc.get("trace_id", "")
            if tid:
                self._exemplars[tid] = doc
                self._exemplars.move_to_end(tid)
                while len(self._exemplars) > EXEMPLAR_CAP:
                    self._exemplars.popitem(last=False)
            self.tail_exemplars += 1

    def get_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            doc = self._exemplars.get(trace_id)
            if doc is not None:
                self._exemplars.move_to_end(trace_id)
            return doc

    def exemplar_ids(self) -> List[str]:
        with self._lock:
            return list(self._exemplars)

    # -- slow-op warnings --------------------------------------------------

    def check_slow(self) -> List[TrackedOp]:
        """Warn once per op that breaches the complaint threshold
        (the OpTracker check_ops_in_flight role).  The warned flip and
        the slow_ops count happen UNDER the lock — an admin-thread
        dump racing this loop sees each op counted exactly once."""
        slow = []
        with self._lock:
            for op in self._live.values():
                if not op.warned and op.age() > self.complaint_time:
                    op.warned = True
                    self.slow_ops += 1
                    slow.append(op)
        for op in slow:  # logging outside the lock
            log.warning("%s: slow op (%.1fs >= %.1fs): %s",
                        self.who, op.age(), self.complaint_time,
                        op.description)
        return slow

    # -- dump surfaces -----------------------------------------------------

    def dump_in_flight(self) -> Dict[str, Any]:
        with self._lock:
            ops = [op.dump() for op in list(self._live.values())]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic(self) -> Dict[str, Any]:
        with self._lock:
            ops = [op.dump() for op in list(self._history)]
        return {"num_ops": len(ops), "ops": ops,
                "slow_ops_total": self.slow_ops}

    def perf(self) -> Dict[str, Any]:
        """Numeric perf-dump section: lifetime op count, the in-flight
        gauge, slow-op/exemplar totals, and the rolling latency marks
        the tail policy uses."""
        with self._lock:
            p99 = self._durations.percentile(0.99)
            return {
                "ops_total": self.ops_total,
                "ops_in_flight": len(self._live),
                "slow_ops": self.slow_ops,
                "tail_exemplars": self.tail_exemplars,
                "exemplars_held": len(self._exemplars),
                "complaint_time_s": self.complaint_time,
                "rolling_p99_ms": round(p99 * 1e3, 3)
                if p99 is not None else 0.0,
            }

"""Per-tenant admission control in front of the op queue.

The mClock tags (osd/scheduler.py) arbitrate among ops that are
ALREADY queued — but by the time an over-limit tenant's op sits in
the queue it has a parsed message, an op-tracker slot, and is about
to pull encode-service / hedge / tier resources through the execute
stage.  The admission gate is the cheaper refusal: a per-tenant token
bucket charged at the tenant's mClock LIMIT rate, consulted before
the op enters the QoS queue.  Under-limit tenants pass at a dict
lookup's cost; an over-limit tenant is first DELAYED (up to
`osd_mclock_admission_max_delay_ms`, which smooths bursts without
refusing them) and then SHED with an explicit EBUSY — so one abusive
tenant's flood is bounced at the front door instead of starving the
rest in the queue.

dmclock's delayed-tag throttling plays this role in the reference
(the client-side delta/rho loop); single-OSD scope here, so a plain
bucket is the honest equivalent.

Hot-accept-path discipline (ROADMAP item 2 tail): at extreme tenant
counts the gate itself must cost nothing when it passes untouched.
``try_admit()`` is the SYNCHRONOUS fast path — one O(1) bucket
lookup, no coroutine allocation, no per-op profile resolution (the
tenant's mClock limit is cached IN the bucket entry with a short
TTL, so the `client.<tenant>` class-string build and the profile
dict walk happen once per tenant per TTL window, not once per op).
Only an op the bucket cannot cover falls to the awaitable ``admit``
slow path, where the delay sleep / shed verdict lives.

Bounded state: tenant buckets live in an LRU capped at
`_BUCKET_CAP`; per-tenant decision counters are capped the same way
(the perf-dump `tenants` map must not itself become the unbounded
buffer the lint rule bans).
"""

from __future__ import annotations

import asyncio
import os

from ceph_tpu.common import flags
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ceph_tpu.common import tracing

ADMIT = "admit"
DELAY = "delay"
SHED = "shed"

_BUCKET_CAP = 4096
# how long a bucket's cached mClock limit serves before the profile
# resolver is consulted again (config pushes land within this window)
_LIMIT_TTL_S = 1.0

# bucket entry slots: [tokens, last_refill, cached_limit,
#                      limit_expiry]
_TOKENS, _LAST, _LIMIT, _EXPIRY = 0, 1, 2, 3


class AdmissionGate:
    """Token-bucket admission per tenant, rate = the tenant's mClock
    limit (0 = unlimited: always admit)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 profile_of: Optional[
                     Callable[[str], Tuple[float, float, float]]]
                 = None):
        config = config or {}
        self.enabled = bool(config.get(
            "osd_mclock_admission_enable", True)) and \
            flags.enabled("CEPH_TPU_QOS")
        # burst: seconds' worth of the limit rate a sleeping tenant
        # may spend instantly on wake (bucket capacity)
        self.burst_s = float(config.get(
            "osd_mclock_admission_burst", 2.0))
        self.max_delay_s = float(config.get(
            "osd_mclock_admission_max_delay_ms", 50.0)) / 1e3
        # (r, w, limit) resolver — shared with the scheduler so one
        # option surface drives both stages
        self._profile_of = profile_of or (lambda t: (0.0, 1.0, 0.0))
        # tenant -> [tokens, last_refill]; LRU-bounded
        self._buckets: "OrderedDict[str, list]" = OrderedDict()
        self.counters = {ADMIT: 0, DELAY: 0, SHED: 0}
        self._tenant_counters: "OrderedDict[str, Dict[str, int]]" = \
            OrderedDict()

    def _limit(self, tenant: str) -> float:
        return float(self._profile_of(tenant)[2])

    def _bucket(self, tenant: str, now: float) -> list:
        """O(1) on the hot path: one dict lookup + LRU touch.  The
        tenant's limit rides in the entry and refreshes on a short
        TTL — the per-op profile resolution (a `client.<t>` string
        build plus profile-map walks) was a measurable cost at
        extreme tenant counts."""
        b = self._buckets.get(tenant)
        if b is None:
            limit = self._limit(tenant)
            b = [limit * self.burst_s, now, limit,
                 now + _LIMIT_TTL_S]
            self._buckets[tenant] = b
            while len(self._buckets) > _BUCKET_CAP:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(tenant)
            if now >= b[_EXPIRY]:
                b[_LIMIT] = self._limit(tenant)
                b[_EXPIRY] = now + _LIMIT_TTL_S
        return b

    def _count(self, tenant: str, decision: str) -> None:
        self.counters[decision] += 1
        c = self._tenant_counters.get(tenant)
        if c is None:
            c = {ADMIT: 0, DELAY: 0, SHED: 0}
            self._tenant_counters[tenant] = c
            while len(self._tenant_counters) > _BUCKET_CAP:
                self._tenant_counters.popitem(last=False)
        else:
            self._tenant_counters.move_to_end(tenant)
        c[decision] += 1

    def try_admit(self, tenant: str,
                  cost: float = 1.0) -> Optional[str]:
        """The allocation-free SYNCHRONOUS fast path: ADMIT when the
        gate passes the op untouched (disabled gate, unlimited
        tenant, or the bucket covers the cost) — no coroutine object,
        no profile resolution, one bucket lookup.  None means the
        slow path must decide (delay or shed): callers then
        ``await admit(tenant, cost)``."""
        if not self.enabled:
            return ADMIT
        now = time.monotonic()
        b = self._bucket(tenant, now)
        limit = b[_LIMIT]
        if limit <= 0:
            self._count(tenant, ADMIT)
            return ADMIT
        cap = max(limit * self.burst_s, cost)
        b[_TOKENS] = min(cap, b[_TOKENS] + (now - b[_LAST]) * limit)
        b[_LAST] = now
        if b[_TOKENS] >= cost:
            b[_TOKENS] -= cost
            self._count(tenant, ADMIT)
            return ADMIT
        return None

    async def admit(self, tenant: str, cost: float = 1.0) -> str:
        """Returns ADMIT (possibly after an in-gate delay, counted
        DELAY) or SHED.  Unlimited tenants and a disabled gate admit
        unconditionally.  Hot-path callers should consult
        ``try_admit`` first and only await here on its None — this
        coroutine re-runs the fast path, so calling both never
        double-charges."""
        fast = self.try_admit(tenant, cost)
        if fast is not None:
            return fast
        b = self._buckets[tenant]
        limit = b[_LIMIT]
        wait = (cost - b[_TOKENS]) / limit
        if wait <= self.max_delay_s:
            # the delay IS the charge: the refill during the sleep
            # covers the op.  The smoothing sleep is a pipeline stage
            # an op can visibly spend its time in — span it (no-op
            # when the op is untraced; an instant ADMIT above costs
            # no wall time and gets no span)
            b[_TOKENS] -= cost
            self._count(tenant, DELAY)
            async with tracing.child_span("admission",
                                          tenant=tenant) as sp:
                sp.set_attr("decision", DELAY)
                await asyncio.sleep(wait)
            return ADMIT
        self._count(tenant, SHED)
        tracing.event(f"admission shed tenant={tenant}")
        return SHED

    # -- observability -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """qos_status shape: global + per-tenant decisions, live
        bucket levels, the gate's knobs."""
        return {
            "enabled": self.enabled,
            "burst_s": self.burst_s,
            "max_delay_ms": self.max_delay_s * 1e3,
            "decisions": dict(self.counters),
            "tenants": {
                t: {**c,
                    "limit_ops": self._limit(t),
                    "tokens": round(self._buckets.get(
                        t, [0.0])[0], 3)}
                for t, c in self._tenant_counters.items()},
        }

    def perf(self) -> Dict[str, Any]:
        """perf-dump `qos.admission` shape (numeric leaves only; the
        prometheus flattener turns `tenants` into tenant-labeled
        rows)."""
        return {
            "enabled": int(self.enabled),
            "admitted": self.counters[ADMIT],
            "delayed": self.counters[DELAY],
            "shed": self.counters[SHED],
            "tenants": {
                t: {"admitted": c[ADMIT], "delayed": c[DELAY],
                    "shed": c[SHED]}
                for t, c in self._tenant_counters.items()},
        }

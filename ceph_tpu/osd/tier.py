"""Read-tier agent: hot EC objects decode once, then serve from memory.

Reference parity: the cache-tier agent of PrimaryLogPG (agent_work /
maybe_promote, hit_set_* bookkeeping) and the pool tiering knobs
(osd_tier_promote_min_recency family).  Flat-substrate redesign: the
reference promotes objects between POOLS (a second data path worth it
when the base tier is spinning rust); here every byte already lives in
MemStore/TPUStore, so what a skewed read workload repeatedly pays is
the EC *decode dispatch*.  The tier therefore caches DECODED OBJECT
BYTES on the primary — a hot object decodes once, every subsequent
read is a memory slice with zero EC plan dispatches, bit-identical to
the cold path.

Coherency contract (what makes the bypass safe):
- entries live on the PRIMARY only, keyed (pg, oid);
- every mutation funnels through _submit_shard_writes / recovery /
  scrub-repair on that primary, each of which invalidates first;
- interval changes drop the PG's entries wholesale (same discipline as
  the RMW extent cache) — a new primary may have applied writes we
  never saw.

Observability rides ceph_tpu.common.perf_counters: hit / miss /
promote / evict / invalidate u64 counters, an inflight gauge, and a
read-frequency histogram fed at every hitset rotation.  The whole
subsystem sits behind CEPH_TPU_TIER=0 (env kill switch) and the
osd_tier_enable option.
"""

from __future__ import annotations

import os

from ceph_tpu.common import flags
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set, Tuple

from ceph_tpu.common import tracing
from ceph_tpu.common.perf_counters import PerfCounters
from ceph_tpu.osd import hitset as hitset_mod

# read-frequency histogram bounds: reads-per-object-per-period buckets
READ_FREQ_BOUNDS = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0]


def env_enabled() -> bool:
    return flags.enabled("CEPH_TPU_TIER")


class TierAgent:
    """Per-daemon hot-set tracker + decoded-object read cache."""

    def __init__(self, who: str = "osd",
                 config: Optional[Dict[str, Any]] = None):
        cfg = config or {}
        self.who = who
        self.enabled = env_enabled() and bool(
            cfg.get("osd_tier_enable", True))
        self.hit_set_count = int(cfg.get("osd_hit_set_count", 4))
        self.hit_set_period = float(cfg.get("osd_hit_set_period", 10.0))
        self.hit_set_target_size = int(
            cfg.get("osd_hit_set_target_size", 1024))
        self.hit_set_fpp = float(cfg.get("osd_hit_set_bloom_fpp", 0.05))
        self.hit_set_kind = str(cfg.get("osd_hit_set_type", "bloom"))
        self.promote_min_recency = int(
            cfg.get("osd_tier_promote_min_recency", 2))
        self.cache_bytes_max = int(
            cfg.get("osd_tier_cache_bytes", 64 << 20))
        self.promote_max_inflight = int(
            cfg.get("osd_tier_promote_max_inflight", 4))
        self.promote_backoff_s = float(
            cfg.get("osd_tier_promote_backoff", 5.0))
        # decoded-object cache: (pg, oid) -> {"data", "version",
        # "promoted_at"}; OrderedDict gives the LRU order
        self.cache: "OrderedDict[Tuple[Any, str], Dict[str, Any]]" = \
            OrderedDict()
        self.cache_bytes = 0
        self.stacks: Dict[Any, hitset_mod.HitSetStack] = {}
        self._promoting: Set[Tuple[Any, str]] = set()
        # sealed-but-unpersisted hitsets (pg, seq, hitset), drained by
        # the daemon's persistence hook
        self._sealed: List[tuple] = []
        # objects whose decoded size exceeds the whole cache budget:
        # remembered so a giant hot object cannot re-trigger a
        # whole-object promotion decode on every read (cleared when
        # the object is rewritten — its size may have changed)
        self._oversize: Set[Tuple[Any, str]] = set()
        # failed promotions back off (monotonic deadline): a hot but
        # unreadable object (ENOENT / whiteout / degraded) must not
        # re-run a full decode attempt on every read
        self._backoff: Dict[Tuple[Any, str], float] = {}
        self.perf = PerfCounters(f"{who}.tier")
        for name, desc in (
                ("hit", "reads served from the decoded-object tier"),
                ("miss", "tier-eligible reads that took the cold path"),
                ("promote", "objects promoted into the tier"),
                ("promote_fail", "promotions aborted (read error/race)"),
                ("promote_skipped",
                 "promotions not started (inflight cap/dup)"),
                ("evict", "entries evicted under the byte budget"),
                ("invalidate", "entries dropped by mutations"),
                ("hitset_rotations", "sealed hit-set periods"),
                ("records", "reads recorded into the open hit set")):
            self.perf.add_u64_counter(name, desc)
        self.perf.add_u64("inflight", "promotions currently running")
        self.perf.add_u64("cached_objects", "entries in the tier")
        self.perf.add_u64("cached_bytes", "bytes held by the tier")
        self.perf.add_histogram(
            "read_freq", READ_FREQ_BOUNDS,
            "reads per object per hit-set period (fed on rotation)")

    # -- hit-set recording -------------------------------------------------

    def _stack(self, pg) -> hitset_mod.HitSetStack:
        st = self.stacks.get(pg)
        if st is None:
            st = self.stacks[pg] = hitset_mod.HitSetStack(
                count=self.hit_set_count,
                period=self.hit_set_period,
                target_size=self.hit_set_target_size,
                fpp=self.hit_set_fpp,
                kind=self.hit_set_kind)
        return st

    def record_read(self, pg, oid: str) -> None:
        """Record one read into the open hit set.  Rotation is
        read-driven: the first read past the period boundary seals
        the open set (one device-batched bloom insert) — the sealed
        set is queued for the daemon to persist (pop_sealed).

        Deliberately does NOT compute the promote signal: a
        steady-state tier hit must not pay archived-bloom membership
        probes — callers ask hit_count() only after a cache miss."""
        if not self.enabled:
            return
        st = self._stack(pg)
        if st.due():
            self._rotate(pg, st)
        st.insert(hitset_mod.hash_oid(oid))
        self.perf.inc("records")

    def hit_count(self, pg, oid: str) -> int:
        """The promote signal: sets (open + archived) containing oid."""
        if not self.enabled:
            return 0
        st = self.stacks.get(pg)
        if st is None:
            return 0
        return st.hit_count(hitset_mod.hash_oid(oid))

    def note_read(self, pg, oid: str) -> int:
        """record_read + hit_count in one call (probes and tests; the
        daemon's read path splits them to keep tier hits cheap)."""
        if not self.enabled:
            return 0
        self.record_read(pg, oid)
        return self.hit_count(pg, oid)

    def _rotate(self, pg, st: hitset_mod.HitSetStack) -> None:
        for n in st.read_frequencies():
            self.perf.hinc("read_freq", float(n))
        sealed = st.rotate()
        self.perf.inc("hitset_rotations")
        self._sealed.append((pg, st.seq, sealed))
        del self._sealed[:-16]  # ring: persistence is best-effort

    def sealed_pending(self) -> bool:
        return bool(self._sealed)

    def pop_sealed(self) -> List[tuple]:
        """[(pg, seq, hitset)] sealed since the last call — the daemon
        persists each via the pg-meta omap prefix."""
        out, self._sealed = self._sealed, []
        return out

    def rotate_all(self) -> None:
        """Force-seal every open set (tests and the admin surface)."""
        for pg, st in list(self.stacks.items()):
            self._rotate(pg, st)

    # -- decoded-object cache ----------------------------------------------

    def lookup(self, pg, oid: str) -> Optional[bytes]:
        """Decoded bytes for (pg, oid), or None.  Counts hit/miss."""
        if not self.enabled:
            return None
        key = (pg, oid)
        entry = self.cache.get(key)
        if entry is None:
            self.perf.inc("miss")
            # annotate the op's span (no-op untraced): a tier miss
            # means the read pays the cold decode path below
            tracing.event("tier miss")
            return None
        self.cache.move_to_end(key)
        self.perf.inc("hit")
        tracing.event("tier hit")
        return entry["data"]

    def wants_promote(self, pg, oid: str, hit_count: int) -> bool:
        if not self.enabled or hit_count < self.promote_min_recency:
            return False
        key = (pg, oid)
        if key in self.cache or key in self._promoting or \
                key in self._oversize:
            return False
        until = self._backoff.get(key)
        if until is not None:
            if until > time.monotonic():
                return False
            del self._backoff[key]
        return True

    def begin_promote(self, pg, oid: str) -> bool:
        """Claim the promotion slot; False when capped or duplicate."""
        key = (pg, oid)
        if not self.enabled or key in self._promoting or \
                key in self.cache or \
                len(self._promoting) >= self.promote_max_inflight:
            self.perf.inc("promote_skipped")
            return False
        self._promoting.add(key)
        self.perf.set("inflight", len(self._promoting))
        return True

    def end_promote(self, pg, oid: str,
                    data: Optional[bytes]) -> None:
        key = (pg, oid)
        self._promoting.discard(key)
        self.perf.set("inflight", len(self._promoting))
        if data is None:
            self.perf.inc("promote_fail")
            if len(self._backoff) > 4096:
                self._backoff.clear()  # bounded, rebuilt on demand
            self._backoff[key] = time.monotonic() + \
                self.promote_backoff_s
            return
        self.install(pg, oid, data)
        self.perf.inc("promote")

    def install(self, pg, oid: str, data: bytes) -> None:
        if not self.enabled:
            return
        key = (pg, oid)
        if len(data) > self.cache_bytes_max:
            # a single over-budget object never fits: refuse it
            # WITHOUT evicting the rest of the hot set, and remember
            # it so the agent stops re-decoding it on every read
            self._oversize.add(key)
            if len(self._oversize) > 4096:
                self._oversize.clear()   # bounded, rebuilt on demand
            return
        old = self.cache.pop(key, None)
        if old is not None:
            self.cache_bytes -= len(old["data"])
        # coherence is invalidate-first + drop_pg, not versioning:
        # the entry carries no version on purpose
        self.cache[key] = {"data": bytes(data),
                           "promoted_at": time.monotonic()}
        self.cache_bytes += len(data)
        while self.cache_bytes > self.cache_bytes_max and \
                len(self.cache) > 1:
            _k, victim = self.cache.popitem(last=False)
            self.cache_bytes -= len(victim["data"])
            self.perf.inc("evict")
        self._gauges()

    def invalidate(self, pg, oid: str) -> None:
        self._oversize.discard((pg, oid))
        self._backoff.pop((pg, oid), None)
        entry = self.cache.pop((pg, oid), None)
        if entry is not None:
            self.cache_bytes -= len(entry["data"])
            self.perf.inc("invalidate")
            self._gauges()

    def drop_pg(self, pg) -> None:
        """Interval change: primary-scope state is no longer coherent."""
        for key in [k for k in self.cache if k[0] == pg]:
            self.cache_bytes -= len(self.cache.pop(key)["data"])
            self.perf.inc("invalidate")
        self._oversize = {k for k in self._oversize if k[0] != pg}
        self._backoff = {k: v for k, v in self._backoff.items()
                         if k[0] != pg}
        self.stacks.pop(pg, None)
        self._gauges()

    def _gauges(self) -> None:
        self.perf.set("cached_objects", len(self.cache))
        self.perf.set("cached_bytes", self.cache_bytes)

    # -- observability -----------------------------------------------------

    def counters(self) -> Dict[str, Any]:
        """Flat perf view (ints + the read_freq histogram dict) merged
        into the daemon's `perf dump` and scraped by prometheus."""
        return self.perf.dump()

    def status(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "cached_objects": len(self.cache),
            "cached_bytes": self.cache_bytes,
            "cache_bytes_max": self.cache_bytes_max,
            "promote_min_recency": self.promote_min_recency,
            "promotions_inflight": len(self._promoting),
            "counters": self.perf.dump(),
            "objects": [{"pg": str(k[0]), "oid": k[1],
                         "bytes": len(e["data"])}
                        for k, e in list(self.cache.items())[-32:]],
        }

    def hitset_dump(self) -> Dict[str, Any]:
        return {str(pg): st.dump() for pg, st in self.stacks.items()}

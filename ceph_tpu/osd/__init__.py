"""OSD-layer components: stripe math, EC data path, maps."""

"""Op scheduler: QoS arbitration between client and background work.

Reference parity: the OSD's op queue
(/root/reference/src/osd/scheduler/mClockScheduler.h — dmClock tags
with per-class reservation/weight/limit; src/common/WeightedPriorityQueue.h
— the WPQ alternative; op classes in src/osd/scheduler/OpSchedulerItem.h:
client, background_recovery, background_best_effort, scrub).

The reference queues OpSchedulerItems into sharded work queues; here
the daemon's work units are coroutines, so the scheduler is an ADMIT
gate: work of class c calls `await scheduler.run(c, cost, fn)` and the
grant loop decides WHEN it starts, with at most `max_concurrent`
in-flight grants.  Two disciplines:

- WPQScheduler: deficit-weighted round robin over class FIFOs.
- MClockScheduler: dmClock-lite — each class carries
  (reservation, weight, limit) in ops/sec; a queued item gets an
  R-tag (reservation deadline), P-tag (proportional-share virtual
  time), L-tag (limit gate).  Selection: any class behind its
  reservation goes first (lowest R-tag); otherwise the lowest P-tag
  among classes under their limit.  This is the same tag algebra as
  the reference's dmclock library (src/dmclock/), minus the
  distributed delta/rho piggybacking (single-OSD scope here).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

CLIENT = "client"
RECOVERY = "background_recovery"
SCRUB = "background_scrub"
BEST_EFFORT = "background_best_effort"

# (reservation ops/s, weight, limit ops/s or 0 = unlimited) — the
# shape of osd_mclock_profile "balanced": client weighted highest,
# recovery guaranteed a floor so a client flood cannot starve it
DEFAULT_PROFILES: Dict[str, Tuple[float, float, float]] = {
    CLIENT: (50.0, 10.0, 0.0),
    RECOVERY: (25.0, 3.0, 200.0),
    SCRUB: (5.0, 1.0, 50.0),
    BEST_EFFORT: (0.0, 1.0, 50.0),
}


class _Item:
    __slots__ = ("cost", "fn", "future", "r_tag", "p_tag")

    def __init__(self, cost: float, fn, future):
        self.cost = cost
        self.fn = fn
        self.future = future
        self.r_tag = 0.0
        self.p_tag = 0.0


class OpSchedulerBase:
    """Admit gate: run(cls, cost, fn) parks until granted."""

    def __init__(self, max_concurrent: int = 8):
        self.max_concurrent = max_concurrent
        self._in_flight = 0
        self._queues: Dict[str, List[_Item]] = {}
        self._wake = asyncio.Event()
        self._grant_task: Optional[asyncio.Task] = None
        self._stopping = False
        self.granted: Dict[str, int] = {}

    def start(self) -> None:
        if self._grant_task is None:
            self._grant_task = asyncio.get_running_loop().create_task(
                self._grant_loop())

    async def stop(self) -> None:
        self._stopping = True
        self._wake.set()
        if self._grant_task is not None:
            self._grant_task.cancel()
            try:
                await self._grant_task
            except asyncio.CancelledError:
                pass
            self._grant_task = None
        for q in self._queues.values():
            for item in q:
                if not item.future.done():
                    item.future.cancel()
            q.clear()

    async def run(self, op_class: str, cost: float,
                  fn: Callable[[], Awaitable[Any]]) -> Any:
        """Queue fn under op_class; execute once granted."""
        if self._stopping:
            # a latched-stopped scheduler must fail fast: start()
            # would spawn a grant loop that exits immediately and the
            # queued future would park the caller forever
            raise RuntimeError("scheduler stopped")
        self.start()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        item = _Item(max(cost, 1.0), fn, fut)
        self._enqueue(op_class, item)
        self._wake.set()
        try:
            await fut  # grant
        except asyncio.CancelledError:
            # cancelled AFTER the grant landed: the slot was consumed
            # and fn never ran — release it or the leak eventually
            # deadlocks every class (cancelled-before-grant is handled
            # by the grant loop when it pops the done future)
            if fut.done() and not fut.cancelled():
                self._in_flight -= 1
                self._wake.set()
            raise
        try:
            return await fn()
        finally:
            self._in_flight -= 1
            self._wake.set()

    # -- subclass surface --------------------------------------------------

    def _enqueue(self, op_class: str, item: _Item) -> None:
        raise NotImplementedError

    def _select(self) -> Optional[Tuple[str, _Item]]:
        raise NotImplementedError

    def _queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    async def _grant_loop(self) -> None:
        while not self._stopping:
            while self._in_flight < self.max_concurrent:
                picked = self._select()
                if picked is None:
                    break
                op_class, item = picked
                self._in_flight += 1
                self.granted[op_class] = \
                    self.granted.get(op_class, 0) + 1
                if not item.future.done():
                    item.future.set_result(None)
                else:  # caller vanished: release the slot
                    self._in_flight -= 1
            self._wake.clear()
            if self._queued() == 0 or \
                    self._in_flight >= self.max_concurrent:
                await self._wake.wait()
            else:
                # everything queued is rate-gated: poll shortly
                await asyncio.sleep(0.005)


class WPQScheduler(OpSchedulerBase):
    """Weighted fair queueing over per-class FIFOs
    (WeightedPriorityQueue.h role): grant the class with the smallest
    weight-normalized service so sustained load shares
    proportionally — a high-weight flood slows, never starves, the
    others."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 max_concurrent: int = 8):
        super().__init__(max_concurrent)
        self.weights = weights or {
            c: w for c, (_r, w, _l) in DEFAULT_PROFILES.items()}
        self._served: Dict[str, float] = {}  # weight-normalized

    def _enqueue(self, op_class: str, item: _Item) -> None:
        q = self._queues.setdefault(op_class, [])
        if not q:
            # a class waking from idle must not replay its idle time
            # as a burst: catch its virtual service up to the floor of
            # the currently-backlogged classes
            active = [self._served.get(c, 0.0)
                      for c, qq in self._queues.items() if qq]
            floor = min(active) if active else 0.0
            self._served[op_class] = max(
                self._served.get(op_class, 0.0), floor)
        q.append(item)

    def _select(self) -> Optional[Tuple[str, _Item]]:
        best = None
        for op_class, q in self._queues.items():
            if not q:
                continue
            key = self._served.get(op_class, 0.0)
            if best is None or key < best[1]:
                best = (op_class, key)
        if best is None:
            return None
        op_class = best[0]
        item = self._queues[op_class].pop(0)
        self._served[op_class] = self._served.get(op_class, 0.0) + \
            item.cost / max(self.weights.get(op_class, 1.0), 1e-9)
        return op_class, item


class MClockScheduler(OpSchedulerBase):
    """dmClock-lite tag scheduler (mClockScheduler.h role)."""

    def __init__(self,
                 profiles: Optional[
                     Dict[str, Tuple[float, float, float]]] = None,
                 max_concurrent: int = 8):
        super().__init__(max_concurrent)
        self.profiles = dict(profiles or DEFAULT_PROFILES)
        self._last_r: Dict[str, float] = {}
        self._last_p: Dict[str, float] = {}
        self._last_l: Dict[str, float] = {}

    def _enqueue(self, op_class: str, item: _Item) -> None:
        now = time.monotonic()
        r, w, l = self.profiles.get(op_class, (0.0, 1.0, 0.0))
        if r > 0:
            item.r_tag = max(now, self._last_r.get(op_class, 0.0)
                             + item.cost / r)
            self._last_r[op_class] = item.r_tag
        else:
            item.r_tag = float("inf")
        item.p_tag = max(now, self._last_p.get(op_class, 0.0)) \
            + item.cost / max(w, 1e-9)
        self._last_p[op_class] = item.p_tag
        self._queues.setdefault(op_class, []).append(item)

    def _limit_ok(self, op_class: str, now: float) -> bool:
        _r, _w, l = self.profiles.get(op_class, (0.0, 1.0, 0.0))
        if l <= 0:
            return True
        return self._last_l.get(op_class, 0.0) <= now

    def _charge_limit(self, op_class: str, item: _Item,
                      now: float) -> None:
        _r, _w, l = self.profiles.get(op_class, (0.0, 1.0, 0.0))
        if l > 0:
            self._last_l[op_class] = \
                max(now, self._last_l.get(op_class, 0.0)) \
                + item.cost / l


    def _select(self) -> Optional[Tuple[str, _Item]]:
        now = time.monotonic()
        # phase 1: reservations behind schedule (constraint-based)
        best = None
        for op_class, q in self._queues.items():
            if q and q[0].r_tag <= now:
                if best is None or q[0].r_tag < best[1]:
                    best = (op_class, q[0].r_tag)
        if best is not None:
            op_class = best[0]
            item = self._queues[op_class].pop(0)
            self._charge_limit(op_class, item, now)
            return op_class, item
        # phase 2: proportional share among classes under their limit
        best = None
        for op_class, q in self._queues.items():
            if q and self._limit_ok(op_class, now):
                if best is None or q[0].p_tag < best[1]:
                    best = (op_class, q[0].p_tag)
        if best is None:
            return None  # everything rate-gated: grant loop polls
        op_class = best[0]
        item = self._queues[op_class].pop(0)
        self._charge_limit(op_class, item, now)
        return op_class, item


def make_scheduler(kind: str, **kwargs):
    """osd_op_queue option: 'mclock_scheduler' (default) or 'wpq'."""
    if kind in ("wpq", "WPQ"):
        return WPQScheduler(**kwargs)
    return MClockScheduler(**kwargs)

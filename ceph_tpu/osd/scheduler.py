"""Op scheduler: QoS arbitration between client and background work.

Reference parity: the OSD's op queue
(/root/reference/src/osd/scheduler/mClockScheduler.h — dmClock tags
with per-class reservation/weight/limit; src/common/WeightedPriorityQueue.h
— the WPQ alternative; op classes in src/osd/scheduler/OpSchedulerItem.h:
client, background_recovery, background_best_effort, scrub).

The reference queues OpSchedulerItems into sharded work queues; here
the daemon's work units are coroutines, so the scheduler is an ADMIT
gate: work of class c calls `await scheduler.run(c, cost, fn)` and the
grant loop decides WHEN it starts, with at most `max_concurrent`
in-flight grants.  Two disciplines:

- WPQScheduler: deficit-weighted round robin over class FIFOs.
- MClockScheduler: dmClock-lite — each class carries
  (reservation, weight, limit) in ops/sec; a queued item gets an
  R-tag (reservation deadline), P-tag (proportional-share virtual
  time), L-tag (limit gate).  Selection: any class behind its
  reservation goes first (lowest R-tag); otherwise the lowest P-tag
  among classes under their limit.  This is the same tag algebra as
  the reference's dmclock library (src/dmclock/), minus the
  distributed delta/rho piggybacking (single-OSD scope here).

Multi-tenant extension (the million-client front door): client ops
carrying a tenant identity (MOSDOp v4) schedule as per-tenant classes
`client.<tenant>` with their own (reservation, weight, limit)
profiles — the typed `osd_mclock_tenant_*` options supply the default
triple and per-tenant overrides — so one abusive tenant contends
against its own tags, not against everyone's.  Queues are BOUNDED
(`max_queue_depth` per class, explicit overflow policy) and
introspectable via `stats()`, which is the signal the admission gate
(osd/admission.py) keys off.
"""

from __future__ import annotations

import asyncio
import contextvars
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ceph_tpu.common import tracing

CLIENT = "client"
RECOVERY = "background_recovery"
SCRUB = "background_scrub"
BEST_EFFORT = "background_best_effort"
#: coded-compute scans (MOSDCompute): their own dmClock class so a
#: 10k-object scan contends against its OWN tags — a small
#: reservation keeps scans progressing under client load, the weight
#: sits below client I/O, and the limit caps how hard a scan storm
#: can push (scans must never starve the data path)
COMPUTE = "compute"
#: coded inference queries (the `infer` kernels): latency-sensitive
#: serving, so a reservation like compute's but a tighter limit — a
#: query storm is shed back to the client (EBUSY) before it can
#: squeeze the data path or the compute scans
INFERENCE = "inference"

#: per-tenant client classes are `client.<tenant>`
TENANT_PREFIX = CLIENT + "."

# (reservation ops/s, weight, limit ops/s or 0 = unlimited) — the
# shape of osd_mclock_profile "balanced": client weighted highest,
# recovery guaranteed a floor so a client flood cannot starve it
DEFAULT_PROFILES: Dict[str, Tuple[float, float, float]] = {
    CLIENT: (50.0, 10.0, 0.0),
    RECOVERY: (25.0, 3.0, 200.0),
    SCRUB: (5.0, 1.0, 50.0),
    BEST_EFFORT: (0.0, 1.0, 50.0),
    COMPUTE: (10.0, 2.0, 400.0),
    INFERENCE: (10.0, 3.0, 300.0),
}

#: bookkeeping cap for per-tenant class state: at millions of tenants
#: the tag/queue maps must stay bounded — idle tenants' entries are
#: pruned once the map outgrows this
TENANT_STATE_CAP = 4096


#: mClock class of the op currently executing under a run() grant.
#: Downstream services key per-class state off this (the encode
#: service's hot/cold arrival-density router) instead of threading a
#: class argument through every call chain.
_current_class: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ceph_tpu_op_class", default="")


def current_class() -> str:
    """Scheduler class of the currently-running op, '' outside any
    grant (direct calls, tests, startup)."""
    return _current_class.get()


#: dmClock grant phase of the currently-running op: "reservation"
#: (granted against the class's r-tag constraint) or "priority"
#: (proportional-share phase).  Replies carry it back so the client's
#: ServiceTracker can count rho — reservation-phase completions —
#: separately from delta (all completions), per the dmClock paper.
PHASE_RESERVATION = "reservation"
PHASE_PRIORITY = "priority"

_current_phase: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ceph_tpu_grant_phase", default="")


def current_phase() -> str:
    """dmClock phase of the currently-running op's grant, '' outside
    any grant or under a non-mClock scheduler."""
    return _current_phase.get()


def tenant_class(tenant: str) -> str:
    """Scheduler class for a tenant's client ops ('' = the shared
    default class)."""
    return f"{TENANT_PREFIX}{tenant}" if tenant else CLIENT


def stage_class(op_class: str) -> str:
    """Trace-stage key for a scheduler class: per-tenant classes fold
    into the shared `client` stage (a million tenants must not mint a
    million stage histograms)."""
    return CLIENT if op_class.startswith(TENANT_PREFIX) else op_class


class QueueFull(RuntimeError):
    """Overflow policy 'shed': the class queue is at max_queue_depth.
    The daemon maps this to EBUSY — the client sees an explicit
    refusal, never an op silently parked on an unbounded list."""

    def __init__(self, op_class: str, depth: int):
        super().__init__(f"{op_class} queue full ({depth})")
        self.op_class = op_class
        self.depth = depth


class _Item:
    __slots__ = ("cost", "fn", "future", "r_tag", "p_tag",
                 "delta", "rho")

    def __init__(self, cost: float, fn, future,
                 delta: int = 1, rho: int = 1):
        self.cost = cost
        self.fn = fn
        self.future = future
        self.r_tag = 0.0
        self.p_tag = 0.0
        # dmClock piggyback multipliers: completions this tenant saw
        # cluster-wide (delta: all; rho: reservation-phase) at OTHER
        # OSDs since its last request here, plus one for this op.
        # 1/1 — a single-OSD or piggyback-off op — reduces every tag
        # formula below to classic single-server mClock.
        self.delta = max(int(delta), 1)
        self.rho = max(int(rho), 1)


class OpSchedulerBase:
    """Admit gate: run(cls, cost, fn) parks until granted."""

    def __init__(self, max_concurrent: int = 8,
                 max_queue_depth: int = 1024,
                 overflow: str = "shed"):
        self.max_concurrent = max_concurrent
        # bounded per-class queues with an EXPLICIT overflow policy:
        # "shed" raises QueueFull at enqueue, "block" parks the caller
        # until the class drains below the bound (backpressure)
        self.max_queue_depth = int(max_queue_depth)
        if overflow not in ("shed", "block"):
            raise ValueError(f"unknown overflow policy {overflow!r}")
        self.overflow = overflow
        self._in_flight = 0
        self._queues: Dict[str, List[_Item]] = {}
        # live queued-item count, maintained incrementally: _queued()
        # was a sum over EVERY class queue, which at thousands of
        # tenant classes made each grant-loop pass O(tenants)
        self._nqueued = 0
        self._wake = asyncio.Event()
        self._drained = asyncio.Event()
        self._grant_task: Optional[asyncio.Task] = None
        self._stopping = False
        self.granted: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}
        self.cancelled_before_grant = 0
        self.fast_lane: Dict[str, int] = {}

    def start(self) -> None:
        if self._grant_task is None:
            self._grant_task = asyncio.get_running_loop().create_task(
                self._grant_loop())

    async def stop(self) -> None:
        self._stopping = True
        self._wake.set()
        self._drained.set()
        if self._grant_task is not None:
            self._grant_task.cancel()
            try:
                await self._grant_task
            except asyncio.CancelledError:
                pass
            self._grant_task = None
        for q in self._queues.values():
            for item in q:
                if not item.future.done():
                    item.future.cancel()
            q.clear()
        self._nqueued = 0

    async def run(self, op_class: str, cost: float,
                  fn: Callable[[], Awaitable[Any]], *,
                  qos_delta: int = 1, qos_rho: int = 1) -> Any:
        """Queue fn under op_class; execute once granted.

        qos_delta/qos_rho are the dmClock piggyback multipliers from
        the client's ServiceTracker (completions it saw at other OSDs
        since its last op here, plus one): tags advance by
        delta x cost so per-tenant rates hold CLUSTER-wide, not
        per-OSD.  1/1 (the default) is classic local mClock."""
        if self._stopping:
            # a latched-stopped scheduler must fail fast: start()
            # would spawn a grant loop that exits immediately and the
            # queued future would park the caller forever
            raise RuntimeError("scheduler stopped")
        fast_phase = None
        if self._nqueued == 0 and \
                self._in_flight < self.max_concurrent:
            fast_phase = self._fast_charge(
                op_class, max(cost, 1.0), qos_delta, qos_rho)
        if fast_phase:
            # uncontended fast grant: nothing is queued and a slot is
            # free, so the grant loop's future/enqueue/select round
            # trip (two loop hops + an O(classes) scan per op) buys
            # nothing — charge the class's tags exactly as the queued
            # path would (fairness accounting stays intact; an
            # over-limit class is refused here and queues normally)
            # and run.  The trace span still marks the stage, with
            # zero wait.
            self._in_flight += 1
            self.granted[op_class] = self.granted.get(op_class, 0) + 1
            q_span = tracing.start_child(
                f"queue.{stage_class(op_class)}", cls=op_class)
            q_span.set_attr("fast", True)
            q_span.finish()
            tok = _current_class.set(op_class)
            ptok = _current_phase.set(fast_phase)
            try:
                return await fn()
            finally:
                _current_phase.reset(ptok)
                _current_class.reset(tok)
                self._in_flight -= 1
                self._wake.set()
        self.start()
        # queue WAIT is a pipeline stage: per-mClock-class span
        # covering the bounded-queue BLOCK wait and the enqueue-to-
        # grant wait — under saturation the block wait IS the queueing
        # delay, and it must attribute here, not to the op's self-time
        # (tenant classes fold into `queue.client` so stage names stay
        # bounded; the exact class rides as an attr)
        q_span = tracing.start_child(
            f"queue.{stage_class(op_class)}", cls=op_class)
        try:
            while len(self._queues.get(op_class, ())) >= \
                    self.max_queue_depth:
                if self.overflow == "shed":
                    self.shed[op_class] = \
                        self.shed.get(op_class, 0) + 1
                    q_span.set_attr("shed", True)
                    raise QueueFull(op_class,
                                    len(self._queues[op_class]))
                # block: wait for the class to drain below the bound
                self._drained.clear()
                await self._drained.wait()
                if self._stopping:
                    raise RuntimeError("scheduler stopped")
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            item = _Item(max(cost, 1.0), fn, fut,
                         qos_delta, qos_rho)
            self._enqueue(op_class, item)
            self._nqueued += 1
            self._wake.set()
            try:
                phase = await fut  # grant (dmClock phase it won)
            except asyncio.CancelledError:
                # cancelled AFTER the grant landed: the slot was
                # consumed and fn never ran — release it or the leak
                # eventually deadlocks every class (cancelled-before-
                # grant is handled by the grant loop when it pops the
                # done future, and its tag charge is refunded there)
                if fut.done() and not fut.cancelled():
                    self._in_flight -= 1
                    self._wake.set()
                q_span.set_attr("cancelled", True)
                raise
        finally:
            q_span.finish()
        tok = _current_class.set(op_class)
        ptok = _current_phase.set(phase or "")
        try:
            return await fn()
        finally:
            _current_phase.reset(ptok)
            _current_class.reset(tok)
            self._in_flight -= 1
            self._wake.set()

    def try_acquire(self, op_class: str, cost: float,
                    qos_delta: int = 1, qos_rho: int = 1):
        """Synchronous twin of run()'s uncontended fast grant — the
        sub-chunk write fast lane.  Succeeds ONLY under the exact
        conditions the fast grant would (nothing queued, a slot free,
        the class's dmClock tags advanced and within limit), with
        identical accounting: granted counts, tag charges, and the
        queue stage span all land as if run() had fast-granted, so
        QoS fairness and the per-stage histograms cannot drift between
        lanes.  Returns the dmClock grant phase (a truthy string) on
        success, False on refusal; the caller MUST pair a truthy
        return with release()."""
        if self._stopping or self._nqueued != 0 or \
                self._in_flight >= self.max_concurrent:
            return False
        phase = self._fast_charge(op_class, max(cost, 1.0),
                                  qos_delta, qos_rho)
        if not phase:
            return False
        self._in_flight += 1
        self.granted[op_class] = self.granted.get(op_class, 0) + 1
        self.fast_lane[op_class] = self.fast_lane.get(op_class, 0) + 1
        q_span = tracing.start_child(
            f"queue.{stage_class(op_class)}", cls=op_class)
        q_span.set_attr("fast", True)
        q_span.finish()
        return phase

    def release(self) -> None:
        """Release a try_acquire slot (mirrors run()'s finally)."""
        self._in_flight -= 1
        self._wake.set()

    # -- subclass surface --------------------------------------------------

    def _enqueue(self, op_class: str, item: _Item) -> None:
        raise NotImplementedError

    def _select(self) -> Optional[Tuple[str, _Item, str]]:
        """Pick the next granted item: (class, item, dmClock phase)."""
        raise NotImplementedError

    def _uncharge(self, op_class: str, item: _Item) -> None:
        """Return a cancelled-before-grant item's tag/service charge:
        the work never ran, so the class must not be debited for it."""

    def _fast_charge(self, op_class: str, cost: float,
                     delta: int = 1, rho: int = 1):
        """Charge the class's tags for an uncontended immediate grant
        (the enqueue+select accounting, minus the queue).  Returns
        the grant phase (truthy string); False = the class may not
        run right now (rate-gated) and must take the queued path."""
        return PHASE_PRIORITY

    def _queued(self) -> int:
        return self._nqueued

    def stats(self) -> Dict[str, Any]:
        """The introspection surface the admission gate (and
        qos_status) reads: grant concurrency, per-class depth,
        grant/shed counters, the bound and its policy."""
        return {
            "max_concurrent": self.max_concurrent,
            "in_flight": self._in_flight,
            "queued": self._queued(),
            "max_queue_depth": self.max_queue_depth,
            "overflow": self.overflow,
            "queue_depths": {c: len(q)
                             for c, q in self._queues.items() if q},
            "granted": dict(self.granted),
            "queue_shed": dict(self.shed),
            "cancelled_before_grant": self.cancelled_before_grant,
            "fast_lane": dict(self.fast_lane),
        }

    async def _grant_loop(self) -> None:
        while not self._stopping:
            while self._in_flight < self.max_concurrent:
                picked = self._select()
                if picked is None:
                    break
                op_class, item, phase = picked
                self._nqueued -= 1
                self._drained.set()
                if item.future.done():
                    # caller vanished before the grant: no slot was
                    # consumed, and the item's tag charge goes back so
                    # the class is not debited for unrun work
                    self.cancelled_before_grant += 1
                    self._uncharge(op_class, item)
                    continue
                self._in_flight += 1
                self.granted[op_class] = \
                    self.granted.get(op_class, 0) + 1
                item.future.set_result(phase)
            self._wake.clear()
            if self._queued() == 0 or \
                    self._in_flight >= self.max_concurrent:
                await self._wake.wait()
            else:
                # everything queued is rate-gated: poll shortly
                await asyncio.sleep(0.005)


class WPQScheduler(OpSchedulerBase):
    """Weighted fair queueing over per-class FIFOs
    (WeightedPriorityQueue.h role): grant the class with the smallest
    weight-normalized service so sustained load shares
    proportionally — a high-weight flood slows, never starves, the
    others."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 max_concurrent: int = 8,
                 max_queue_depth: int = 1024,
                 overflow: str = "shed"):
        super().__init__(max_concurrent, max_queue_depth, overflow)
        self.weights = weights or {
            c: w for c, (_r, w, _l) in DEFAULT_PROFILES.items()}
        self._served: Dict[str, float] = {}  # weight-normalized

    def _enqueue(self, op_class: str, item: _Item) -> None:
        q = self._queues.setdefault(op_class, [])
        if not q:
            # a class waking from idle must not replay its idle time
            # as a burst: catch its virtual service up to the floor of
            # the currently-backlogged classes
            active = [self._served.get(c, 0.0)
                      for c, qq in self._queues.items() if qq]
            floor = min(active) if active else 0.0
            self._served[op_class] = max(
                self._served.get(op_class, 0.0), floor)
        q.append(item)

    def _select(self) -> Optional[Tuple[str, _Item, str]]:
        best = None
        for op_class, q in self._queues.items():
            if not q:
                continue
            key = self._served.get(op_class, 0.0)
            if best is None or key < best[1]:
                best = (op_class, key)
        if best is None:
            return None
        op_class = best[0]
        item = self._queues[op_class].pop(0)
        self._served[op_class] = self._served.get(op_class, 0.0) + \
            item.cost / max(self.weights.get(op_class, 1.0), 1e-9)
        return op_class, item, PHASE_PRIORITY

    def _uncharge(self, op_class: str, item: _Item) -> None:
        self._served[op_class] = self._served.get(op_class, 0.0) - \
            item.cost / max(self.weights.get(op_class, 1.0), 1e-9)

    def _fast_charge(self, op_class: str, cost: float,
                     delta: int = 1, rho: int = 1):
        # same service charge the pop in _select takes (an idle-floor
        # catch-up is moot: the fast path only runs with EVERY queue
        # empty, so there is no backlogged floor to respect).  WPQ is
        # not dmClock: the piggyback multipliers are ignored.
        self._served[op_class] = self._served.get(op_class, 0.0) + \
            cost / max(self.weights.get(op_class, 1.0), 1e-9)
        return PHASE_PRIORITY


class MClockScheduler(OpSchedulerBase):
    """dmClock-lite tag scheduler (mClockScheduler.h role) with
    per-tenant client classes."""

    def __init__(self,
                 profiles: Optional[
                     Dict[str, Tuple[float, float, float]]] = None,
                 max_concurrent: int = 8,
                 max_queue_depth: int = 1024,
                 overflow: str = "shed",
                 tenant_default: Tuple[float, float, float] = (
                     0.0, 1.0, 0.0),
                 tenant_profiles: Optional[
                     Dict[str, Tuple[float, float, float]]] = None):
        super().__init__(max_concurrent, max_queue_depth, overflow)
        self.profiles = dict(profiles or DEFAULT_PROFILES)
        # tenant classes: per-tenant override else the default triple
        # (osd_mclock_tenant_{reservation,weight,limit})
        self.tenant_default = tuple(tenant_default)
        self.tenant_profiles = {
            t: tuple(p) for t, p in (tenant_profiles or {}).items()}
        self._last_r: Dict[str, float] = {}
        self._last_p: Dict[str, float] = {}
        self._last_l: Dict[str, float] = {}

    def profile_of(self, op_class: str) -> Tuple[float, float, float]:
        """(reservation, weight, limit) for a class: explicit profile,
        else the tenant override / tenant default for `client.<t>`
        classes, else best-effort."""
        p = self.profiles.get(op_class)
        if p is not None:
            return p
        if op_class.startswith(TENANT_PREFIX):
            t = op_class[len(TENANT_PREFIX):]
            return self.tenant_profiles.get(t, self.tenant_default)
        return (0.0, 1.0, 0.0)

    def _prune_idle_tenants(self) -> None:
        """Tenant-class bookkeeping stays bounded: once the tag maps
        outgrow TENANT_STATE_CAP, drop tenant classes with EMPTY
        queues (their tags re-seed from now on the next burst, which
        is exactly the idle-floor discipline anyway)."""
        if len(self._last_p) <= TENANT_STATE_CAP:
            return
        for c in [c for c in self._last_p
                  if c.startswith(TENANT_PREFIX)
                  and not self._queues.get(c)]:
            self._last_p.pop(c, None)
            self._last_r.pop(c, None)
            self._last_l.pop(c, None)
            self._queues.pop(c, None)

    def _enqueue(self, op_class: str, item: _Item) -> None:
        now = time.monotonic()
        r, w, l = self.profile_of(op_class)
        if r > 0:
            # the max(now, ...) floor IS the idle-tag-replay guard: a
            # tenant that slept cannot bank reservation credit and
            # replay it as an instantaneous burst.  rho scales the
            # advance by the reservation-phase completions this tenant
            # won at OTHER OSDs since its last op here (dmClock): the
            # reservation is then honored cluster-wide, not N-times
            # over by N primaries.
            item.r_tag = max(now, self._last_r.get(op_class, 0.0)
                             + item.cost * item.rho / r)
            self._last_r[op_class] = item.r_tag
        else:
            item.r_tag = float("inf")
        item.p_tag = max(now, self._last_p.get(op_class, 0.0)) \
            + item.cost * item.delta / max(w, 1e-9)
        self._last_p[op_class] = item.p_tag
        self._queues.setdefault(op_class, []).append(item)
        self._prune_idle_tenants()

    def _uncharge(self, op_class: str, item: _Item) -> None:
        """A cancelled-before-grant op returns its full cost: the R/P
        charge taken at enqueue AND the limit charge _select just
        took when it popped the dead item."""
        r, w, l = self.profile_of(op_class)
        if r > 0 and op_class in self._last_r:
            self._last_r[op_class] -= item.cost * item.rho / r
        if op_class in self._last_p:
            self._last_p[op_class] -= \
                item.cost * item.delta / max(w, 1e-9)
        if l > 0 and op_class in self._last_l:
            self._last_l[op_class] -= item.cost * item.delta / l

    def _fast_charge(self, op_class: str, cost: float,
                     delta: int = 1, rho: int = 1):
        # dmClock tags advance exactly as _enqueue + _charge_limit
        # would have; an over-limit class is REFUSED (it must queue
        # behind its L-tag like always — the fast path never launders
        # QoS)
        now = time.monotonic()
        if not self._limit_ok(op_class, now):
            return False
        r, w, l = self.profile_of(op_class)
        phase = PHASE_PRIORITY
        if r > 0:
            r_next = self._last_r.get(op_class, 0.0) \
                + cost * max(rho, 1) / r
            if r_next <= now:
                # the grant lands inside the reservation constraint:
                # this is the phase a queued _select pass 1 would
                # have used
                phase = PHASE_RESERVATION
            self._last_r[op_class] = max(now, r_next)
        self._last_p[op_class] = \
            max(now, self._last_p.get(op_class, 0.0)) \
            + cost * max(delta, 1) / max(w, 1e-9)
        if l > 0:
            self._last_l[op_class] = \
                max(now, self._last_l.get(op_class, 0.0)) \
                + cost * max(delta, 1) / l
        self._prune_idle_tenants()
        return phase

    def _limit_ok(self, op_class: str, now: float) -> bool:
        _r, _w, l = self.profile_of(op_class)
        if l <= 0:
            return True
        return self._last_l.get(op_class, 0.0) <= now

    def _charge_limit(self, op_class: str, item: _Item,
                      now: float) -> None:
        _r, _w, l = self.profile_of(op_class)
        if l > 0:
            self._last_l[op_class] = \
                max(now, self._last_l.get(op_class, 0.0)) \
                + item.cost * item.delta / l

    def _select(self) -> Optional[Tuple[str, _Item, str]]:
        now = time.monotonic()
        # phase 1: reservations behind schedule (constraint-based)
        best = None
        for op_class, q in self._queues.items():
            if q and q[0].r_tag <= now:
                if best is None or q[0].r_tag < best[1]:
                    best = (op_class, q[0].r_tag)
        if best is not None:
            op_class = best[0]
            item = self._queues[op_class].pop(0)
            self._charge_limit(op_class, item, now)
            return op_class, item, PHASE_RESERVATION
        # phase 2: proportional share among classes under their limit
        best = None
        for op_class, q in self._queues.items():
            if q and self._limit_ok(op_class, now):
                if best is None or q[0].p_tag < best[1]:
                    best = (op_class, q[0].p_tag)
        if best is None:
            return None  # everything rate-gated: grant loop polls
        op_class = best[0]
        item = self._queues[op_class].pop(0)
        self._charge_limit(op_class, item, now)
        return op_class, item, PHASE_PRIORITY

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out["tenant_classes"] = sum(
            1 for c in self._last_p if c.startswith(TENANT_PREFIX))
        return out


#: kwargs only the mClock discipline understands (make_scheduler
#: filters them for WPQ so one config surface serves both)
_MCLOCK_ONLY = ("profiles", "tenant_default", "tenant_profiles")


def make_scheduler(kind: str, **kwargs):
    """osd_op_queue option: 'mclock_scheduler' (default) or 'wpq'."""
    if kind in ("wpq", "WPQ"):
        for key in _MCLOCK_ONLY:
            kwargs.pop(key, None)
        return WPQScheduler(**kwargs)
    return MClockScheduler(**kwargs)

"""Put-object processor pipeline (rgw_putobj_processor roles).

Reference parity (/root/reference/src/rgw/rgw_putobj_processor.h):

- RadosWriter (:79-116) -> StripeWriter: writes stripe objects through
  an IoCtx with bounded concurrency (the Aio throttle role) and tracks
  written objects so a canceled upload can delete them (:87 RawObjSet).
- ChunkProcessor / StripeProcessor (:105, referenced via
  ManifestObjectProcessor :120-131) -> PutObjProcessor: buffers incoming
  byte runs, cuts them at stripe boundaries (rgw_obj_stripe_size, 4 MiB,
  options.cc:6413) and issues at most chunk-size writes
  (rgw_max_chunk_size, 4 MiB, options.cc:5521).  Here both default to
  4 MiB so one stripe = one rados object write = one EC encode batch on
  the OSD — the stripe size IS the TPU dispatch granule.
- RGWObjManifest -> Manifest: JSON description of which rados objects
  hold which logical ranges; CompleteMultipart concatenates part
  manifests (rgw_op.cc:5933).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

DEFAULT_STRIPE_SIZE = 4 << 20      # rgw_obj_stripe_size (options.cc:6413)
DEFAULT_CHUNK_SIZE = 4 << 20       # rgw_max_chunk_size (options.cc:5521)
DEFAULT_AIO_WINDOW = 8             # rgw_put_obj_min_window_size role


class Manifest:
    """JSON-serializable object manifest (RGWObjManifest role): ordered
    (oid, size) stripes covering the logical object."""

    def __init__(self, obj_size: int = 0,
                 stripes: Optional[List[Dict]] = None,
                 stripe_size: int = DEFAULT_STRIPE_SIZE):
        self.obj_size = obj_size
        self.stripe_size = stripe_size
        self.stripes: List[Dict] = stripes or []  # [{"oid", "size"}]

    def append(self, other: "Manifest") -> None:
        """CompleteMultipart stitch: concatenate a part's manifest."""
        self.stripes.extend(other.stripes)
        self.obj_size += other.obj_size

    def to_dict(self) -> Dict:
        return {"obj_size": self.obj_size,
                "stripe_size": self.stripe_size,
                "stripes": self.stripes}

    @classmethod
    def from_dict(cls, d: Dict) -> "Manifest":
        return cls(d["obj_size"], list(d["stripes"]), d["stripe_size"])


class StripeWriter:
    """RadosWriter role: bounded-concurrency stripe-object writes with
    cancel-time cleanup of everything written."""

    def __init__(self, ioctx, window: int = DEFAULT_AIO_WINDOW):
        self.ioctx = ioctx
        self._sem = asyncio.Semaphore(window)
        self._tasks: List[asyncio.Task] = []
        self.written: List[str] = []

    async def _write(self, oid: str, data: bytes,
                     entry: Optional[Dict] = None) -> None:
        try:
            out = await self.ioctx.write_full(oid, data)
            if entry is not None and out and "data_crc" in out:
                # OSD-computed content digest (write reply returnvec):
                # the manifest carries it so the gateway's ETag needs
                # no second pass over the object bytes
                entry["crc"] = out["data_crc"]
        finally:
            self._sem.release()

    async def submit(self, oid: str, data: bytes,
                     entry: Optional[Dict] = None) -> None:
        """Acquire a window slot BEFORE buffering the stripe in a task:
        memory stays O(window x stripe) no matter how large the object
        is (the rgw_put_obj_min_window_size backpressure role)."""
        await self._sem.acquire()
        self.written.append(oid)
        self._tasks.append(
            asyncio.get_running_loop().create_task(
                self._write(oid, data, entry)))

    async def drain(self) -> None:
        """Wait for every in-flight stripe; raise the first failure."""
        if self._tasks:
            results = await asyncio.gather(*self._tasks,
                                           return_exceptions=True)
            self._tasks = []
            for res in results:
                if isinstance(res, BaseException):
                    raise res

    async def cancel(self) -> None:
        """Delete whatever this upload wrote (RadosWriter dtor role)."""
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        for oid in self.written:
            try:
                await self.ioctx.remove(oid)
            except Exception:
                pass
        self.written = []


class PutObjProcessor:
    """Chunk+Stripe processor: stream bytes in, stripe objects out.

    oid_for_stripe(n) names stripe n (the manifest generator role —
    multipart parts and atomic objects differ only in naming)."""

    def __init__(self, writer: StripeWriter, oid_prefix: str,
                 stripe_size: int = DEFAULT_STRIPE_SIZE):
        self.writer = writer
        self.oid_prefix = oid_prefix
        self.stripe_size = stripe_size
        self._buf = bytearray()
        self._stripe_no = 0
        self.manifest = Manifest(stripe_size=stripe_size)

    def oid_for_stripe(self, n: int) -> str:
        # first stripe is the part/object head; extra stripes are shadow
        # objects (the reference's _shadow_ naming discipline)
        return self.oid_prefix if n == 0 else \
            f"{self.oid_prefix}_shadow_{n}"

    async def _flush_stripe(self, data: bytes) -> None:
        oid = self.oid_for_stripe(self._stripe_no)
        self._stripe_no += 1
        entry = {"oid": oid, "size": len(data)}
        self.manifest.stripes.append(entry)
        self.manifest.obj_size += len(data)
        await self.writer.submit(oid, data, entry)

    async def process(self, data: bytes) -> None:
        """Feed a run of bytes; full stripes are written as they fill
        (submit blocks on the writer window — the backpressure seam).

        Stripe-aligned runs never touch the staging buffer: full
        stripes are cut as zero-copy views of the caller's bytes, so a
        part-sized PUT reaches the rados write with no gateway-side
        copy at all (the reference's bufferlist claim/splice
        discipline in ChunkProcessor::process)."""
        view = memoryview(data)
        # zero-copy only for immutable input: stripes are written
        # asynchronously after this call returns, and a caller
        # refilling a reused bytearray would corrupt queued stripes
        writable = not view.readonly
        off = 0
        if self._buf:
            need = self.stripe_size - len(self._buf)
            take = min(need, len(view))
            self._buf.extend(view[:take])
            off = take
            if len(self._buf) >= self.stripe_size:
                full = self._buf
                self._buf = bytearray()
                await self._flush_stripe(bytes(full))
        while len(view) - off >= self.stripe_size:
            stripe = view[off:off + self.stripe_size]
            await self._flush_stripe(bytes(stripe) if writable
                                     else stripe)
            off += self.stripe_size
        if off < len(view):
            self._buf.extend(view[off:])

    async def complete(self) -> Manifest:
        """Flush the tail and wait for every stripe to be durable."""
        if self._buf:
            await self._flush_stripe(bytes(self._buf))
            self._buf = bytearray()
        await self.writer.drain()
        return self.manifest

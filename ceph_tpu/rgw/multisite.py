"""RGW multisite: zone-to-zone replication (the rgw sync role).

Reference parity: /root/reference/src/rgw/rgw_data_sync.cc,
rgw_sync.cc, rgw_bucket_sync.cc — zones in a zonegroup replicate
asynchronously: metadata (buckets + their configs) and data (objects,
versions, delete markers) flow from peer zones, driven by sharded
change logs (datalog/bilog) that agents tail with persisted markers;
full sync bootstraps, incremental tails; entries carry the
originating zone so active-active topologies do not echo writes back
(the RGWX sync-trace discipline).

Re-design notes: the reference syncs over REST between gateways;
here the peer zone is just another connected RadosClient's RGWLite
(the rbd-mirror/cephfs-mirror stance — same code path across
clusters).  Log entries are dirty-set hints, not op payloads: the
agent re-fetches the named key's CURRENT state from the source zone
and reconciles the destination wholesale (fetch_remote_obj
discipline) — replay is idempotent, ordering within a key collapses
to the newest entry, and a missed entry is healed by any later touch
or a full_sync pass.  Version ids, delete markers, mtimes and version
ORDER are preserved across zones."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

from ceph_tpu.common.periodic import PeriodicDaemon
from ceph_tpu.rgw.gateway import RGWError, RGWLite, VER_OFF

log = logging.getLogger("rgw.multisite")


class RGWSyncAgent(PeriodicDaemon):
    """Replicates src zone -> dst zone (RGWDataSyncProcessorThread +
    meta sync roles, collapsed).  Run one per direction for
    active-active."""

    def __init__(self, src: RGWLite, dst: RGWLite):
        if src.zone == dst.zone:
            raise ValueError("src and dst must be distinct zones")
        self.src = src
        self.dst = dst
        self._tick_what = f"rgw sync {src.zone}->{dst.zone}"
        # observability (tests pin loop-prevention on these)
        self.objects_copied = 0
        self.entries_applied = 0
        self.entries_skipped = 0

    # -- sync status markers (per shard, persisted on the DST) -------------

    def _marker_oid(self) -> str:
        return RGWLite._meta_oid("sync.marker", self.src.zone)

    async def _load_markers(self) -> Dict[int, str]:
        from ceph_tpu.rados.client import ObjectNotFound

        try:
            omap = await self.dst.meta.omap_get(self._marker_oid())
        except ObjectNotFound:
            return {}  # genuinely no markers yet
        # any OTHER failure must raise: treating a transient read
        # error as "no marker" would let full_sync fast-forward past
        # unapplied entries, silently skipping them forever
        return {int(k): v.decode() for k, v in omap.items()}

    async def _save_marker(self, shard: int, marker: str) -> None:
        await self.dst.meta.omap_set(self._marker_oid(),
                                     {str(shard): marker.encode()})
        # and advertise our position to the source for log trimming
        try:
            await self.src.sync_peer_position(self.dst.zone, shard,
                                              marker)
        except Exception:
            log.warning("peer position update failed", exc_info=True)

    # -- full sync (bootstrap) ---------------------------------------------

    async def full_sync(self) -> int:
        """Reconcile every bucket and key from the source zone.
        Marks the CURRENT end of each log shard as applied first, so
        changes landing during the walk are replayed incrementally
        afterwards (at-least-once handoff, the rbd-mirror bootstrap
        discipline).  Returns keys reconciled."""
        for shard in range(RGWLite.LOG_SHARDS):
            entries = await self.src.sync_log_entries(shard)
            # keep the marker if we already have one (re-bootstrap
            # must not skip unapplied tail entries)
            have = (await self._load_markers()).get(shard, "")
            end = entries[-1][0] if entries else ""
            if not have and end:
                await self._save_marker(shard, end)
        n = 0
        for bucket in await self.src.list_buckets():
            await self._reconcile_bucket(bucket)
            doc = await self.src._bucket(bucket)
            keys = set(doc["objects"]) | set(
                doc.get("versioned_keys", []))
            for key in sorted(keys):
                await self._reconcile_key(bucket, key)
                n += 1
        return n

    # -- incremental sync --------------------------------------------------

    async def sync_once(self, limit: int = 1024) -> int:
        """Tail every log shard past its marker and reconcile the
        touched buckets/keys.  Returns entries applied."""
        markers = await self._load_markers()
        applied = 0
        for shard in range(RGWLite.LOG_SHARDS):
            after = markers.get(shard, "")
            entries = await self.src.sync_log_entries(shard, after,
                                                      limit)
            if not entries:
                continue
            # collapse to the newest entry per (bucket, key): state
            # is re-fetched, so older touches are subsumed
            todo: Dict[Tuple[str, Optional[str]], Dict] = {}
            for _k, ent in entries:
                if ent.get("zone") == self.dst.zone:
                    # originated at the destination (replicated to us
                    # earlier, or we applied it there): echoing it
                    # back would ping-pong forever
                    self.entries_skipped += 1
                    continue
                todo[(ent["bucket"], ent.get("key"))] = ent
            buckets_done = set()
            for (bucket, key), _ent in sorted(
                    todo.items(), key=lambda kv: (kv[0][0],
                                                  kv[0][1] or "")):
                if bucket not in buckets_done:
                    await self._reconcile_bucket(bucket)
                    buckets_done.add(bucket)
                if key is not None:
                    await self._reconcile_key(bucket, key)
                self.entries_applied += 1
                applied += 1
            await self._save_marker(shard, entries[-1][0])
        return applied

    # -- reconciliation ----------------------------------------------------

    async def _reconcile_bucket(self, bucket: str) -> None:
        """Create/delete the bucket and align its config (the
        metadata-sync role: owner, ACL, versioning, lifecycle)."""
        try:
            src_doc = await self.src._bucket(bucket)
        except RGWError as e:
            if e.code != "NoSuchBucket":
                raise
            # deleted at the source: empty and drop it here
            try:
                await self.dst._bucket(bucket)
            except RGWError:
                return  # never existed / already gone
            for v in await self.dst.list_object_versions(bucket):
                await self.dst.delete_object(
                    bucket, v["key"], version_id=v["version_id"],
                    _origin=self.src.zone)
            try:
                await self.dst.delete_bucket(bucket,
                                             _origin=self.src.zone)
            except RGWError:
                pass
            return
        try:
            dst_doc = await self.dst._bucket(bucket)
        except RGWError as e:
            if e.code != "NoSuchBucket":
                raise
            await self.dst.create_bucket(
                bucket, owner=src_doc.get("owner", ""),
                acl=src_doc.get("acl", "private"),
                _origin=self.src.zone)
            dst_doc = await self.dst._bucket(bucket)
        if src_doc.get("acl", "private") != \
                dst_doc.get("acl", "private"):
            await self.dst.put_bucket_acl(bucket, src_doc["acl"],
                                          _origin=self.src.zone)
        sv = src_doc.get("versioning", VER_OFF)
        if sv != dst_doc.get("versioning", VER_OFF) and sv != VER_OFF:
            await self.dst.put_bucket_versioning(
                bucket, sv, _origin=self.src.zone)
        slc = src_doc.get("lifecycle", [])
        if slc != dst_doc.get("lifecycle", []):
            # [] propagates too: clearing lifecycle at the source must
            # stop the destination's expiration sweeps
            await self.dst.put_bucket_lifecycle(
                bucket, slc, _origin=self.src.zone)

    async def _reconcile_key(self, bucket: str, key: str) -> None:
        """Align one key's destination state with the source: full
        version list (ids/markers/order preserved) when versioned,
        head object otherwise."""
        try:
            src_versions = [
                v for v in await self.src.list_object_versions(
                    bucket, prefix=key)
                if v["key"] == key]
        except RGWError as e:
            if e.code != "NoSuchBucket":
                raise
            return  # bucket deleted at the source; the bucket-level
            # reconcile (which runs first) already dropped it here
        real_versioned = any(v["version_id"] != "null" or
                             v["delete_marker"]
                             for v in src_versions)
        if real_versioned:
            dst_versions = [
                v for v in await self.dst.list_object_versions(
                    bucket, prefix=key)
                if v["key"] == key]
            dst_etags = {v["version_id"]: v.get("etag", "")
                         for v in dst_versions}
            same = [(v["version_id"], v["delete_marker"])
                    for v in src_versions] == \
                   [(v["version_id"], v["delete_marker"])
                    for v in dst_versions]
            if same:
                return  # already aligned: applying would only churn
                # the destination's change log (active-active echo)
            blobs: Dict[str, bytes] = {}
            for v in src_versions:
                vid = v["version_id"]
                if v["delete_marker"]:
                    continue
                if vid in dst_etags and \
                        dst_etags[vid] == v.get("etag", ""):
                    continue  # same id AND content already there —
                    # "null" can diverge between zones, so id alone
                    # is not enough
                try:
                    data, _etag = await self.src.get_object_ex(
                        bucket, key, version_id=vid)
                except RGWError:
                    continue  # raced a source-side version delete
                blobs[vid] = data
                self.objects_copied += 1
            await self.dst.sync_replace_versions(
                bucket, key, src_versions, blobs,
                origin=self.src.zone)
            return
        # unversioned (or plain "null"-listed head): compare heads
        try:
            src_head = await self.src.head_object(bucket, key)
        except RGWError as e:
            if e.code not in ("NoSuchKey", "NoSuchBucket"):
                raise
            try:
                await self.dst.delete_object(bucket, key,
                                             _origin=self.src.zone)
            except RGWError:
                pass
            return
        try:
            dst_head = await self.dst.head_object(bucket, key)
        except RGWError:
            dst_head = None
        if dst_head is not None and \
                dst_head.get("etag") == src_head.get("etag") and \
                dst_head.get("size") == src_head.get("size"):
            acl = src_head.get("acl")
            if acl and dst_head.get("acl") != acl:
                await self.dst.put_object_acl(bucket, key, acl,
                                              _origin=self.src.zone)
            return
        data, _etag = await self.src.get_object_ex(bucket, key)
        await self.dst.put_object_ex(bucket, key, data,
                                     acl=src_head.get("acl"),
                                     _origin=self.src.zone)
        self.objects_copied += 1

    # -- log trimming ------------------------------------------------------

    async def trim_source_log(self) -> int:
        """Drop source log entries every registered peer has applied
        (the datalog trim role)."""
        total = 0
        for shard in range(RGWLite.LOG_SHARDS):
            total += await self.src.sync_log_trim(shard)
        return total

    # continuous mode: start(interval)/stop() from PeriodicDaemon
    async def _tick(self) -> None:
        await self.sync_once()

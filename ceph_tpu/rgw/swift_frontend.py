"""Swift API frontend: the gateway's second dialect.

Reference parity: /root/reference/src/rgw/rgw_rest_swift.cc +
rgw_swift_auth.cc — the same RGW op layer served over the OpenStack
Swift REST shape: TempAuth-style token handshake (`GET /auth/v1.0`
with X-Auth-User/X-Auth-Key -> X-Auth-Token + X-Storage-Url), then
account/container/object verbs under /v1/AUTH_<account>/.

Re-design notes: the reference multiplexes S3 and Swift through one
frontend with per-API handler tables; here each dialect is its own
small asyncio server over the SAME RGWLite gateway — buckets ARE
containers (shared namespace, matching radosgw's default single-zone
behavior), so an object PUT via Swift is readable via S3 and vice
versa.  Tokens are in-memory with TTL (TempAuth keeps no durable
state either).
"""

from __future__ import annotations

import asyncio
import json
import logging
import secrets
import time
from typing import Dict, Optional, Tuple

from ceph_tpu.rgw.gateway import RGWError, RGWLite

log = logging.getLogger("rgw.swift")

TOKEN_TTL = 3600.0
MAX_BODY = 5 << 30

_ERR_STATUS = {
    "NoSuchBucket": 404, "NoSuchKey": 404,
    "BucketAlreadyExists": 202,  # Swift: container PUT is idempotent
    "BucketNotEmpty": 409, "AccessDenied": 401,
}


class SwiftFrontend:
    """TempAuth + account/container/object REST over RGWLite."""

    def __init__(self, rgw: RGWLite, users: Dict[str, str]):
        """users: account -> key (the X-Auth-User/X-Auth-Key pairs;
        `account:user` forms are accepted and collapse to account)."""
        self.rgw = rgw
        self.users = dict(users)
        self._tokens: Dict[str, Tuple[str, float]] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self.addr = ""

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> str:
        self._server = await asyncio.start_server(
            self._serve, host, port, limit=8 << 20)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"{host}:{port}"
        return self.addr

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
            self._server = None

    # -- HTTP plumbing (same shape as the S3 frontend) --------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _ver = \
                        line.decode("latin-1").strip().split(" ", 2)
                except ValueError:
                    return
                headers: Dict[str, str] = {}
                while True:
                    hline = await reader.readline()
                    if hline in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = \
                        hline.decode("latin-1").partition(":")
                    headers[name.strip().lower()] = value.strip()
                try:
                    length = int(headers.get("content-length", "0"))
                except ValueError:
                    return
                if length > MAX_BODY or length < 0:
                    return
                if length and not self._token_ok(headers):
                    return  # pre-body screen, like the S3 frontend
                body = await reader.readexactly(length) \
                    if length else b""
                keep = headers.get("connection",
                                   "").lower() != "close"
                status, rhdrs, rbody = await self._handle(
                    method.upper(), target, headers, body)
                reason = {200: "OK", 201: "Created", 202: "Accepted",
                          204: "No Content", 401: "Unauthorized",
                          404: "Not Found", 409: "Conflict",
                          500: "Internal Error"}.get(status, "OK")
                out = [f"HTTP/1.1 {status} {reason}\r\n".encode()]
                rhdrs.setdefault("Content-Length", str(len(rbody)))
                rhdrs.setdefault("Connection",
                                 "keep-alive" if keep else "close")
                for k, v in rhdrs.items():
                    out.append(f"{k}: {v}\r\n".encode())
                out.append(b"\r\n")
                writer.write(b"".join(out))
                if method.upper() != "HEAD" and rbody:
                    writer.write(rbody)
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # -- TempAuth ----------------------------------------------------------

    def _token_ok(self, headers: Dict[str, str]) -> bool:
        tok = headers.get("x-auth-token", "")
        ent = self._tokens.get(tok)
        return ent is not None and ent[1] > time.monotonic()

    def _account_of(self, headers: Dict[str, str]) -> Optional[str]:
        ent = self._tokens.get(headers.get("x-auth-token", ""))
        if ent is None or ent[1] <= time.monotonic():
            return None
        return ent[0]

    def _auth(self, headers: Dict[str, str]
              ) -> Tuple[int, Dict[str, str], bytes]:
        user = headers.get("x-auth-user", "")
        account = user.split(":", 1)[0]
        key = headers.get("x-auth-key", "")
        if not account or self.users.get(account) != key:
            return 401, {}, b"auth failed\n"
        token = "AUTH_tk" + secrets.token_hex(16)
        self._tokens[token] = (account,
                               time.monotonic() + TOKEN_TTL)
        return 200, {
            "X-Auth-Token": token,
            "X-Storage-Token": token,
            "X-Storage-Url": f"http://{self.addr}/v1/AUTH_{account}",
        }, b""

    # -- dispatch ----------------------------------------------------------

    async def _handle(self, method: str, target: str,
                      headers: Dict[str, str], body: bytes
                      ) -> Tuple[int, Dict[str, str], bytes]:
        import urllib.parse

        path, _, query = target.partition("?")
        q = dict(urllib.parse.parse_qsl(query,
                                        keep_blank_values=True))
        if path.rstrip("/") == "/auth/v1.0" and method == "GET":
            return self._auth(headers)
        if not path.startswith("/v1/AUTH_"):
            return 404, {}, b"not found\n"
        account = self._account_of(headers)
        if account is None:
            return 401, {}, b"token required\n"
        rest = urllib.parse.unquote(
            path[len(f"/v1/AUTH_{account}"):]).strip("/")
        try:
            if not rest:
                return await self._account_op(method, q)
            if "/" not in rest:
                return await self._container_op(method, rest, q)
            container, obj = rest.split("/", 1)
            return await self._object_op(method, container, obj,
                                         headers, body)
        except RGWError as e:
            return (_ERR_STATUS.get(e.code, 400), {},
                    f"{e.code}\n".encode())
        except Exception:
            log.exception("swift: %s %s failed", method, target)
            return 500, {}, b"internal error\n"

    async def _account_op(self, method: str, q: Dict
                          ) -> Tuple[int, Dict[str, str], bytes]:
        if method not in ("GET", "HEAD"):
            return 405, {}, b""
        names = await self.rgw.list_buckets()
        if q.get("format") == "json":
            body = json.dumps([{"name": n} for n in names]).encode()
            ctype = "application/json"
        else:
            body = ("".join(n + "\n" for n in names)).encode()
            ctype = "text/plain"
        return ((204 if not body else 200),
                {"Content-Type": ctype,
                 "X-Account-Container-Count": str(len(names))}, body)

    async def _container_op(self, method: str, container: str,
                            q: Dict
                            ) -> Tuple[int, Dict[str, str], bytes]:
        if method == "PUT":
            try:
                await self.rgw.create_bucket(container)
                return 201, {}, b""
            except RGWError as e:
                if e.code == "BucketAlreadyExists":
                    return 202, {}, b""  # Swift PUT is idempotent
                raise
        if method == "DELETE":
            await self.rgw.delete_bucket(container)
            return 204, {}, b""
        if method in ("GET", "HEAD"):
            entries = await self.rgw.list_objects(
                container, prefix=q.get("prefix", ""))
            if q.get("format") == "json":
                body = json.dumps([
                    {"name": e["key"], "bytes": e.get("size", 0),
                     "hash": e.get("etag", "")}
                    for e in entries]).encode()
                ctype = "application/json"
            else:
                body = ("".join(e["key"] + "\n"
                                for e in entries)).encode()
                ctype = "text/plain"
            return ((204 if not body else 200),
                    {"Content-Type": ctype,
                     "X-Container-Object-Count": str(len(entries))},
                    body)
        return 405, {}, b""

    async def _object_op(self, method: str, container: str, obj: str,
                         headers: Dict, body: bytes
                         ) -> Tuple[int, Dict[str, str], bytes]:
        if method == "PUT":
            etag, _vid = await self.rgw.put_object_ex(container, obj,
                                                      body)
            return 201, {"ETag": etag}, b""
        if method in ("GET", "HEAD"):
            head = await self.rgw.head_object(container, obj)
            hdrs = {"ETag": head.get("etag", ""),
                    "Content-Type": "application/octet-stream",
                    "Content-Length": str(head.get("size", 0))}
            if method == "HEAD":
                return 200, hdrs, b""
            data, _etag = await self.rgw.get_object_ex(container, obj)
            return 200, hdrs, bytes(data)
        if method == "DELETE":
            await self.rgw.delete_object(container, obj)
            return 204, {}, b""
        return 405, {}, b""

"""RGW-lite: the S3-gateway role over networked RADOS.

Reference parity: the RGW data path — RGWPutObj::execute
(/root/reference/src/rgw/rgw_op.cc:3712) feeding the put-object
processor pipeline (rgw_putobj_processor.h:73-211: HeadObjectProcessor
-> ChunkProcessor -> StripeProcessor -> RadosWriter), multipart uploads
(MultipartObjectProcessor rgw_putobj_processor.h:211) and the
CompleteMultipart manifest stitch (rgw_op.cc:5933
RGWCompleteMultipart::execute).

Re-designed for this stack: asyncio end to end, JSON manifests/indexes
(the versioned-encoding discipline of the repo), bounded-concurrency
stripe writes over the Objecter-role client (the Aio throttle role), and
erasure-coded data pools whose encode path batches onto the TPU through
the shared ec_jax codec.  No HTTP frontend yet — the S3 op surface is
the API of RGWLite (gateway.py); a beast/asio-role frontend can wrap it.
"""

from ceph_tpu.rgw.gateway import RGWLite, RGWError  # noqa: F401
from ceph_tpu.rgw.put_processor import (  # noqa: F401
    Manifest,
    PutObjProcessor,
    StripeWriter,
)

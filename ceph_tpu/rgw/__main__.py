"""Run the S3 gateway as a real process: python -m ceph_tpu.rgw

The radosgw role: connects to the cluster, serves S3-over-HTTP with
sigv4 auth.  Prints `RGW_ADDR <host:port>` once bound.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ceph_tpu.rados.client import RadosClient
from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.s3_frontend import S3Frontend


async def _main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mon", type=str, required=True,
                    help="mon address(es), comma-separated")
    ap.add_argument("--port", type=int, default=7480)  # radosgw default
    ap.add_argument("--data-pool", type=str, default="rgw.data")
    ap.add_argument("--meta-pool", type=str, default="rgw.meta")
    ap.add_argument("--access-key", type=str, required=True)
    ap.add_argument("--secret-key", type=str, required=True)
    ap.add_argument("--secret", type=str, default="",
                    help="cluster cephx keyring")
    ap.add_argument("--secure", action="store_true",
                    help="on-wire encryption (requires --secret)")
    args = ap.parse_args()
    client = RadosClient(args.mon, name="client.rgw",
                         secret=args.secret or None,
                         secure=args.secure)
    await client.connect()
    rgw = RGWLite(client, args.data_pool, args.meta_pool)
    fe = S3Frontend(rgw, {args.access_key: args.secret_key})
    addr = await fe.start(port=args.port)
    print(f"RGW_ADDR {addr}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await fe.stop()
        await client.shutdown()


if __name__ == "__main__":
    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        sys.exit(0)

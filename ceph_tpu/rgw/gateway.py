"""RGWLite: the S3 op surface (bucket/object/multipart) over RADOS.

Reference parity:
- RGWPutObj::execute (/root/reference/src/rgw/rgw_op.cc:3712) — atomic
  object PUT through the processor pipeline, head object carrying the
  manifest (AtomicObjectProcessor, rgw_putobj_processor.h:173).
- Multipart: init (RGWInitMultipart rgw_op.cc:5778), per-part upload
  (MultipartObjectProcessor rgw_putobj_processor.h:211 — parts live in
  `_multipart_<key>.<upload_id>.<num>` objects), complete
  (RGWCompleteMultipart rgw_op.cc:5933 — part manifests stitched in
  part order, multipart ETag = hash-of-hashes "-<nparts>").
- Bucket index: cls_rgw omap entries in the reference; here a JSON
  index object per bucket (the omap op surface is a separate
  milestone), updated read-modify-write.

Data placement: object data goes to the bucket's DATA pool (typically
erasure-coded — BASELINE #5 uses EC 8+3); index/meta JSON docs go to a
replicated META pool, mirroring the reference's pool split
(default.rgw.buckets.data vs .index/.meta).

ETags are S3-compatible: hex MD5 of content for simple PUTs, and the
multipart form md5(concat(part md5 digests))-"<nparts>" for completed
multipart uploads — what stock S3 clients verify against.

etag_hash="crc32c" is the deployment knob for CPU-constrained
gateways: MD5 is a serial ~0.5 GiB/s/core hash with no integrity role
here (shard durability is covered end-to-end by the EC hinfo crc32c
ledger and per-frame wire crcs), and S3 itself does not promise
ETag==MD5 for every object (multipart and SSE-KMS objects already
return non-MD5 ETags).  Default stays "md5" for stock-client interop.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.rgw.put_processor import (
    DEFAULT_STRIPE_SIZE,
    Manifest,
    PutObjProcessor,
    StripeWriter,
)

MULTIPART_PREFIX = "_multipart_"


class RGWError(Exception):
    def __init__(self, code: str, what: str = ""):
        super().__init__(f"{code}: {what}")
        self.code = code


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


class RGWLite:
    """One gateway instance over a connected RadosClient."""

    def __init__(self, client, data_pool: str, meta_pool: str,
                 stripe_size: int = DEFAULT_STRIPE_SIZE,
                 aio_window: int = 8, etag_hash: str = "md5"):
        self.client = client
        self.etag_hash = etag_hash
        self.data = client.open_ioctx(data_pool)
        self.meta = client.open_ioctx(meta_pool)
        self.stripe_size = stripe_size
        self.aio_window = aio_window
        self._uploads = 0
        self._writes = 0
        # serializes read-modify-writes of upload/bucket meta docs
        # within this gateway instance (one gateway per cluster in this
        # tier; multi-gateway index updates need the omap op milestone)
        self._meta_locks: Dict[str, "asyncio.Lock"] = {}

    def _etag_of(self, data: bytes) -> str:
        """Content ETag under the configured hash (class docstring)."""
        if self.etag_hash == "crc32c":
            from ceph_tpu.ops import checksum as cks

            return "%08x" % cks.crc32c(0xFFFFFFFF, data)
        return _etag(data)

    def _etag_from_manifest(self, manifest: Manifest, data) -> str:
        """crc32c-mode ETag without re-reading the object: stitch the
        OSD-computed per-stripe content digests from the write replies
        (StripeWriter._write).  crc32c is affine in the seed, so
        crc(S1||S2, seed) = zeros(crc1, len2) ^ crc2 ^ zeros(seed, len2)
        — the crc32c_combine/zeros folding discipline
        (/root/reference/src/common/crc32c.cc:216-239).  Falls back to
        hashing the bytes when any stripe lacks a digest (replicated
        data pools don't return one)."""
        from ceph_tpu.ops import checksum as cks

        if self.etag_hash != "crc32c" or not manifest.stripes \
                or any("crc" not in st for st in manifest.stripes):
            return self._etag_of(bytes(data) if not isinstance(
                data, (bytes, bytearray, memoryview)) else data)
        crc = manifest.stripes[0]["crc"]
        for st in manifest.stripes[1:]:
            # stripe crcs are 0xFFFFFFFF-seeded; linearity folds the
            # seed compensation into one combine:
            #   crc(A||B, s) = combine(crc_A ^ s, crc_B_seeded_s, |B|)
            crc = cks.crc32c_combine(crc ^ 0xFFFFFFFF, st["crc"],
                                     st["size"])
        return "%08x" % crc

    def _meta_lock(self, key: str):
        import asyncio

        lock = self._meta_locks.get(key)
        if lock is None:
            lock = self._meta_locks[key] = asyncio.Lock()
        return lock

    def _write_id(self) -> str:
        """Unique suffix per PUT: an overwrite writes FRESH stripe
        objects, so a failed upload's cleanup can never delete the live
        object's data and readers never see torn old/new stripes
        (the reference's rgw_obj random-oid-prefix discipline)."""
        self._writes += 1
        return f"w{self._writes}-{int(time.time() * 1000):x}"

    # -- meta-doc helpers (JSON docs in the meta pool) ---------------------

    async def _load(self, oid: str) -> Optional[Dict]:
        try:
            raw = await self.meta.read(oid)
        except Exception:
            return None
        return json.loads(raw.decode())

    async def _store(self, oid: str, doc: Dict) -> None:
        await self.meta.write_full(oid, json.dumps(doc).encode())

    # meta-oid components are joined with the unit separator so bucket
    # or key names containing dots/slashes can never collide
    _SEP = "\x1f"

    @classmethod
    def _meta_oid(cls, kind: str, *parts: str) -> str:
        return cls._SEP.join((kind,) + parts)

    @classmethod
    def _bucket_oid(cls, bucket: str) -> str:
        return cls._meta_oid("bucket.index", bucket)

    @classmethod
    def _upload_oid(cls, bucket: str, key: str, upload_id: str) -> str:
        return cls._meta_oid("multipart", bucket, key, upload_id)

    def _head_oid(self, bucket: str, key: str) -> str:
        return self._SEP.join((bucket, key))

    # -- buckets -----------------------------------------------------------

    async def create_bucket(self, bucket: str) -> None:
        if await self._load(self._bucket_oid(bucket)) is not None:
            raise RGWError("BucketAlreadyExists", bucket)
        await self._store(self._bucket_oid(bucket),
                          {"name": bucket, "objects": {}})

    async def _bucket(self, bucket: str) -> Dict:
        doc = await self._load(self._bucket_oid(bucket))
        if doc is None:
            raise RGWError("NoSuchBucket", bucket)
        return doc

    async def list_objects(self, bucket: str,
                           prefix: str = "") -> List[Dict[str, Any]]:
        doc = await self._bucket(bucket)
        return [dict(v, key=k)
                for k, v in sorted(doc["objects"].items())
                if k.startswith(prefix)]

    async def list_buckets(self) -> List[str]:
        """ListAllMyBuckets role — the bucket.index objects ARE the
        truth (a separate registry doc could desync on a crash between
        two writes); enumerate them from the meta pool."""
        prefix = self._bucket_oid("")
        names = await self.meta.list_objects()
        return sorted(n[len(prefix):] for n in names
                      if n.startswith(prefix))

    async def delete_bucket(self, bucket: str) -> None:
        # emptiness check + removal under the bucket meta lock: a PUT
        # linking a new object concurrently must not be orphaned by a
        # delete that checked before the link landed
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            if doc["objects"]:
                raise RGWError("BucketNotEmpty", bucket)
            await self.meta.remove(self._bucket_oid(bucket))

    async def head_object(self, bucket: str, key: str
                          ) -> Dict[str, Any]:
        doc = await self._bucket(bucket)
        entry = doc["objects"].get(key)
        if entry is None:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        return dict(entry, key=key)

    # -- atomic PUT / GET / DELETE ----------------------------------------

    async def put_object(self, bucket: str, key: str,
                         data: bytes) -> str:
        """Single-shot PUT (RGWPutObj + AtomicObjectProcessor role)."""
        await self._bucket(bucket)
        writer = StripeWriter(self.data, self.aio_window)
        prefix = f"{self._head_oid(bucket, key)}.{self._write_id()}"
        proc = PutObjProcessor(writer, prefix, self.stripe_size)
        try:
            await proc.process(data)
            manifest = await proc.complete()
        except Exception:
            await writer.cancel()
            raise
        etag = self._etag_from_manifest(manifest, data)
        await self._link(bucket, key, manifest, etag)
        return etag

    async def _link(self, bucket: str, key: str, manifest: Manifest,
                    etag: str) -> None:
        """Flip the head manifest doc + bucket index entry (the bucket
        index transaction role of AtomicObjectProcessor::complete),
        then garbage-collect the replaced object's stripes (the GC
        list role)."""
        head_doc = self._meta_oid("head", bucket, key)
        # old-head read, head store and index entry ALL under the
        # bucket lock: a concurrent PUT to the same key must observe
        # the winner's head (or the winner observes its), or the
        # loser's stripes are never referenced and never GC'd; a
        # concurrent delete_bucket (same lock) can never strand an
        # orphaned head doc either
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            old = await self._load(head_doc)
            await self._store(head_doc, {"manifest": manifest.to_dict(),
                                         "etag": etag})
            doc["objects"][key] = {"size": manifest.obj_size,
                                   "etag": etag, "mtime": time.time()}
            await self._store(self._bucket_oid(bucket), doc)
        if old is not None:
            new_oids = {s["oid"] for s in manifest.stripes}
            for stripe in old["manifest"]["stripes"]:
                if stripe["oid"] not in new_oids:
                    try:
                        await self.data.remove(stripe["oid"])
                    except Exception:
                        pass

    async def _manifest(self, bucket: str, key: str) -> Tuple[Manifest,
                                                              str]:
        head = await self._load(self._meta_oid("head", bucket, key))
        if head is None:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        return Manifest.from_dict(head["manifest"]), head["etag"]

    async def get_object(self, bucket: str, key: str) -> bytes:
        data, _etag_ = await self.get_object_ex(bucket, key)
        return data

    async def get_object_ex(self, bucket: str,
                            key: str) -> Tuple[bytes, str]:
        """GET: walk the manifest, fetch stripes concurrently;
        returns (bytes, etag) from ONE head load."""
        import asyncio

        manifest, etag = await self._manifest(bucket, key)
        sem = asyncio.Semaphore(self.aio_window)

        async def fetch(stripe: Dict) -> bytes:
            async with sem:
                return await self.data.read(stripe["oid"])

        parts = await asyncio.gather(
            *(fetch(s) for s in manifest.stripes))
        out = b"".join(p[:s["size"]]
                       for p, s in zip(parts, manifest.stripes))
        if len(out) != manifest.obj_size:
            raise RGWError("IncompleteBody",
                           f"{len(out)} != {manifest.obj_size}")
        return out, etag

    async def delete_object(self, bucket: str, key: str) -> None:
        manifest, _ = await self._manifest(bucket, key)
        for stripe in manifest.stripes:
            try:
                await self.data.remove(stripe["oid"])
            except Exception:
                pass
        await self.meta.remove(self._meta_oid("head", bucket, key))
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            doc["objects"].pop(key, None)
            await self._store(self._bucket_oid(bucket), doc)

    # -- multipart ---------------------------------------------------------

    async def init_multipart(self, bucket: str, key: str) -> str:
        """RGWInitMultipart role: mint an upload id, persist state."""
        await self._bucket(bucket)
        self._uploads += 1
        upload_id = f"u{self._uploads}-{int(time.time() * 1000):x}"
        await self._store(self._upload_oid(bucket, key, upload_id),
                          {"bucket": bucket, "key": key,
                           "parts": {}})
        return upload_id

    async def _upload(self, bucket: str, key: str,
                      upload_id: str) -> Dict:
        doc = await self._load(self._upload_oid(bucket, key, upload_id))
        if doc is None:
            raise RGWError("NoSuchUpload", upload_id)
        return doc

    def _part_prefix(self, bucket: str, key: str, upload_id: str,
                     part_num: int, write_id: str) -> str:
        # the reference's part naming (<key>._multipart_.<uploadid>.<num>)
        # plus a unique write id so a part RE-upload writes fresh
        # objects instead of clobbering the live ones
        return self._SEP.join(
            (bucket, f"{MULTIPART_PREFIX}{key}"
                     f".{upload_id}.{part_num}.{write_id}"))

    async def upload_part(self, bucket: str, key: str, upload_id: str,
                          part_num: int, data: bytes) -> str:
        """MultipartObjectProcessor role: a part is its own striped
        object family; re-upload of the same part replaces it.
        Concurrent parts of one upload are the normal S3 pattern, so
        the upload-doc update is serialized per upload."""
        if part_num < 1 or part_num > 10000:
            raise RGWError("InvalidPart", str(part_num))
        await self._upload(bucket, key, upload_id)  # upload must exist
        writer = StripeWriter(self.data, self.aio_window)
        proc = PutObjProcessor(
            writer, self._part_prefix(bucket, key, upload_id, part_num,
                                      self._write_id()),
            self.stripe_size)
        try:
            await proc.process(data)
            manifest = await proc.complete()
        except Exception:
            await writer.cancel()
            raise
        etag = self._etag_from_manifest(manifest, data)
        upload_oid = self._upload_oid(bucket, key, upload_id)
        async with self._meta_lock(upload_oid):
            doc = await self._upload(bucket, key, upload_id)
            old = doc["parts"].get(str(part_num))
            doc["parts"][str(part_num)] = {
                "etag": etag, "size": manifest.obj_size,
                "manifest": manifest.to_dict()}
            await self._store(upload_oid, doc)
        if old is not None:  # GC the replaced part's stripes
            for stripe in old["manifest"]["stripes"]:
                try:
                    await self.data.remove(stripe["oid"])
                except Exception:
                    pass
        return etag

    async def complete_multipart(self, bucket: str, key: str,
                                 upload_id: str,
                                 parts: List[Tuple[int, str]]) -> str:
        """RGWCompleteMultipart::execute role (rgw_op.cc:5933): validate
        the client's part list, stitch part manifests in part order,
        write the head, unlink upload state."""
        doc = await self._upload(bucket, key, upload_id)
        if not parts:
            raise RGWError("InvalidRequest", "empty part list")
        nums = [p[0] for p in parts]
        if nums != sorted(nums) or len(set(nums)) != len(nums):
            raise RGWError("InvalidPartOrder", str(nums))
        stitched = Manifest(stripe_size=self.stripe_size)
        etags = []
        for num, etag in parts:
            part = doc["parts"].get(str(num))
            if part is None or part["etag"] != etag:
                raise RGWError("InvalidPart", f"part {num}")
            stitched.append(Manifest.from_dict(part["manifest"]))
            etags.append(etag)
        # multipart etag (S3 semantics): md5 over the concatenated
        # part md5 DIGESTS (raw bytes, not hex), suffixed "-<nparts>"
        combined = _etag(b"".join(
            bytes.fromhex(e) for e in etags)) + f"-{len(parts)}"
        await self._link(bucket, key, stitched, combined)
        await self.meta.remove(self._upload_oid(bucket, key, upload_id))
        return combined

    async def abort_multipart(self, bucket: str, key: str,
                              upload_id: str) -> None:
        """RGWAbortMultipart role: delete parts + upload state."""
        doc = await self._upload(bucket, key, upload_id)
        for part in doc["parts"].values():
            for stripe in part["manifest"]["stripes"]:
                try:
                    await self.data.remove(stripe["oid"])
                except Exception:
                    pass
        await self.meta.remove(self._upload_oid(bucket, key, upload_id))

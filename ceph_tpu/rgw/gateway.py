"""RGWLite: the S3 op surface (bucket/object/multipart) over RADOS.

Reference parity:
- RGWPutObj::execute (/root/reference/src/rgw/rgw_op.cc:3712) — atomic
  object PUT through the processor pipeline, head object carrying the
  manifest (AtomicObjectProcessor, rgw_putobj_processor.h:173).
- Multipart: init (RGWInitMultipart rgw_op.cc:5778), per-part upload
  (MultipartObjectProcessor rgw_putobj_processor.h:211 — parts live in
  `_multipart_<key>.<upload_id>.<num>` objects), complete
  (RGWCompleteMultipart rgw_op.cc:5933 — part manifests stitched in
  part order, multipart ETag = hash-of-hashes "-<nparts>").
- Bucket index: cls_rgw omap entries in the reference; here a JSON
  index object per bucket (the omap op surface is a separate
  milestone), updated read-modify-write.

Data placement: object data goes to the bucket's DATA pool (typically
erasure-coded — BASELINE #5 uses EC 8+3); index/meta JSON docs go to a
replicated META pool, mirroring the reference's pool split
(default.rgw.buckets.data vs .index/.meta).

Versioning (rgw_op.cc:3712 RGWPutObj under versioning): an enabled
bucket keeps every PUT as an immutable version (newest first in a
per-key versions doc); deletes insert delete markers; GET serves the
newest non-marker version or a named versionId.  Suspended buckets
write the "null" version in place.  Lifecycle (rgw_lc.cc) expires
current objects, prunes noncurrent versions, and aborts stale
multipart uploads on a sweep; replaced/deleted stripes are DEFERRED
to a GC queue (rgw_gc.cc role) drained by gc_process(), so a crash
between index update and data delete leaks an entry, not objects.

ETags are S3-compatible: hex MD5 of content for simple PUTs, and the
multipart form md5(concat(part md5 digests))-"<nparts>" for completed
multipart uploads — what stock S3 clients verify against.

etag_hash="crc32c" is the deployment knob for CPU-constrained
gateways: MD5 is a serial ~0.5 GiB/s/core hash with no integrity role
here (shard durability is covered end-to-end by the EC hinfo crc32c
ledger and per-frame wire crcs), and S3 itself does not promise
ETag==MD5 for every object (multipart and SSE-KMS objects already
return non-MD5 ETags).  Default stays "md5" for stock-client interop.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.rgw.put_processor import (
    DEFAULT_STRIPE_SIZE,
    Manifest,
    PutObjProcessor,
    StripeWriter,
)

MULTIPART_PREFIX = "_multipart_"

# bucket versioning states (RGWBucketVersioningStatus)
VER_OFF = "off"
VER_ENABLED = "enabled"
VER_SUSPENDED = "suspended"

# canned ACLs (rgw_acl_s3.cc's rgw_canned_acl set, minus the
# aws-exec/log-delivery grants that have no meaning here).  The
# reference also stores full grant lists; canned policies cover the
# practical surface and stay one word in metadata.
CANNED_ACLS = ("private", "public-read", "public-read-write",
               "authenticated-read")


class RGWError(Exception):
    def __init__(self, code: str, what: str = ""):
        super().__init__(f"{code}: {what}")
        self.code = code


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


class RGWLite:
    """One gateway instance over a connected RadosClient."""

    def __init__(self, client, data_pool: str, meta_pool: str,
                 stripe_size: int = DEFAULT_STRIPE_SIZE,
                 aio_window: int = 8, etag_hash: str = "md5",
                 zone: str = "default"):
        self.client = client
        self.etag_hash = etag_hash
        self.data = client.open_ioctx(data_pool)
        self.meta = client.open_ioctx(meta_pool)
        self.stripe_size = stripe_size
        self.aio_window = aio_window
        # multisite zone identity (rgw_zone.h role): stamped into
        # every change-log entry so a sync peer can tell local writes
        # from replicated ones (active-active loop prevention)
        self.zone = zone
        self._uploads = 0
        self._writes = 0
        self._log_ns: Optional[int] = None  # change-log key ratchet
        # serializes read-modify-writes of upload/bucket meta docs
        # within this gateway instance (one gateway per cluster in this
        # tier; multi-gateway index updates need the omap op milestone)
        self._meta_locks: Dict[str, "asyncio.Lock"] = {}
        self._gc_task = None  # background sweep (start_gc)

    def _etag_of(self, data: bytes) -> str:
        """Content ETag under the configured hash (class docstring)."""
        if self.etag_hash == "crc32c":
            from ceph_tpu.ops import checksum as cks

            return "%08x" % cks.crc32c(0xFFFFFFFF, data)
        return _etag(data)

    def _etag_from_manifest(self, manifest: Manifest, data) -> str:
        """crc32c-mode ETag without re-reading the object: stitch the
        OSD-computed per-stripe content digests from the write replies
        (StripeWriter._write).  crc32c is affine in the seed, so
        crc(S1||S2, seed) = zeros(crc1, len2) ^ crc2 ^ zeros(seed, len2)
        — the crc32c_combine/zeros folding discipline
        (/root/reference/src/common/crc32c.cc:216-239).  Falls back to
        hashing the bytes when any stripe lacks a digest (replicated
        data pools don't return one)."""
        from ceph_tpu.ops import checksum as cks

        if self.etag_hash != "crc32c" or not manifest.stripes \
                or any("crc" not in st for st in manifest.stripes):
            return self._etag_of(bytes(data) if not isinstance(
                data, (bytes, bytearray, memoryview)) else data)
        crc = manifest.stripes[0]["crc"]
        for st in manifest.stripes[1:]:
            # stripe crcs are 0xFFFFFFFF-seeded; linearity folds the
            # seed compensation into one combine:
            #   crc(A||B, s) = combine(crc_A ^ s, crc_B_seeded_s, |B|)
            crc = cks.crc32c_combine(crc ^ 0xFFFFFFFF, st["crc"],
                                     st["size"])
        return "%08x" % crc

    def _meta_lock(self, key: str):
        import asyncio

        lock = self._meta_locks.get(key)
        if lock is None:
            lock = self._meta_locks[key] = asyncio.Lock()
        return lock

    def _write_id(self) -> str:
        """Unique suffix per PUT: an overwrite writes FRESH stripe
        objects, so a failed upload's cleanup can never delete the live
        object's data and readers never see torn old/new stripes
        (the reference's rgw_obj random-oid-prefix discipline)."""
        self._writes += 1
        return f"w{self._writes}-{int(time.time() * 1000):x}"

    # -- meta-doc helpers (JSON docs in the meta pool) ---------------------

    async def _load(self, oid: str) -> Optional[Dict]:
        try:
            raw = await self.meta.read(oid)
        except Exception:
            return None
        return json.loads(raw.decode())

    async def _store(self, oid: str, doc: Dict) -> None:
        await self.meta.write_full(oid, json.dumps(doc).encode())

    # meta-oid components are joined with the unit separator so bucket
    # or key names containing dots/slashes can never collide
    _SEP = "\x1f"

    @classmethod
    def _meta_oid(cls, kind: str, *parts: str) -> str:
        return cls._SEP.join((kind,) + parts)

    @classmethod
    def _bucket_oid(cls, bucket: str) -> str:
        return cls._meta_oid("bucket.index", bucket)

    @classmethod
    def _upload_oid(cls, bucket: str, key: str, upload_id: str) -> str:
        return cls._meta_oid("multipart", bucket, key, upload_id)

    def _head_oid(self, bucket: str, key: str) -> str:
        return self._SEP.join((bucket, key))

    @classmethod
    def _versions_oid(cls, bucket: str, key: str) -> str:
        return cls._meta_oid("versions", bucket, key)

    @classmethod
    def _gc_oid(cls, shard: int = 0) -> str:
        # shard 0 keeps the pre-sharding name so legacy queue docs
        # drain without migration
        return cls._meta_oid("gc") if shard == 0 \
            else cls._meta_oid("gc", str(shard))

    # -- multisite change log (the datalog/bilog role) ---------------------
    #
    # Reference parity: rgw_datalog.h / cls_rgw bilog — every bucket
    # or object mutation appends a marker-ordered entry to a SHARDED
    # log that sync agents tail incrementally.  Entries are dirty-set
    # HINTS, not op payloads: a peer re-fetches the named key's
    # CURRENT state from this zone and reconciles (the
    # fetch_remote_obj discipline), so replay is idempotent and
    # ordering within a key is irrelevant past the newest entry.
    # Entry keys are time-ordered and unique per gateway; like the
    # index RMW above, one gateway instance per cluster is this
    # tier's deployment shape.

    LOG_SHARDS = 8

    @classmethod
    def _synclog_oid(cls, shard: int) -> str:
        return cls._meta_oid("sync.log", str(shard))

    def _log_shard(self, bucket: str) -> int:
        # process-stable hash (builtin hash() is salted per process;
        # shard assignment must survive gateway restarts)
        from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

        return ceph_str_hash_rjenkins(bucket.encode()) \
            % self.LOG_SHARDS

    async def _next_log_key(self) -> str:
        """Monotonic, time-ordered key for log/queue entries: a
        backwards clock step (NTP) must never mint keys below a
        peer's saved marker — those entries would be invisible to
        sync and then trimmed.  Seeded from the persisted log tail on
        first use so the ratchet survives restarts too."""
        self._writes += 1
        if self._log_ns is None:
            self._log_ns = await self._log_tail_ns()
        ns = max(time.time_ns(), self._log_ns + 1)
        self._log_ns = ns
        return f"{ns:020d}.{self._writes}"

    async def _log_change(self, bucket: str,
                          key: Optional[str] = None,
                          origin: Optional[str] = None) -> None:
        entry_key = await self._next_log_key()
        entry = {"bucket": bucket, "key": key,
                 "zone": origin or self.zone,
                 "ts": time.time()}
        await self.meta.omap_set(
            self._synclog_oid(self._log_shard(bucket)),
            {entry_key: json.dumps(entry).encode()})

    async def _log_tail_ns(self) -> int:
        tail = 0
        for shard in range(self.LOG_SHARDS):
            try:
                omap = await self.meta.omap_get(
                    self._synclog_oid(shard))
            except Exception:
                continue
            for k in omap:
                try:
                    tail = max(tail, int(k.split(".", 1)[0]))
                except ValueError:
                    pass
        return tail

    async def sync_log_entries(self, shard: int,
                               after: str = "",
                               limit: int = 1024
                               ) -> List[Tuple[str, Dict]]:
        """Log entries with key > after, oldest first."""
        try:
            omap = await self.meta.omap_get(self._synclog_oid(shard))
        except Exception:
            return []
        out = sorted((k, json.loads(v.decode()))
                     for k, v in omap.items() if k > after)
        return out[:limit]

    async def sync_peer_position(self, peer: str, shard: int,
                                 marker: str) -> None:
        """A peer records how far it has applied this shard — the
        trim floor (the reference's per-peer sync status markers)."""
        await self.meta.omap_set(
            self._meta_oid("sync.peers", peer, str(shard)),
            {"marker": marker.encode()})

    # -- users (rgw_user / radosgw-admin role) -----------------------------
    #
    # Durable user records in the meta pool: one doc per uid plus an
    # access-key index omap for O(1) auth lookups.  The reference
    # stores these through RGWUserCtl/cls_user; same shape, JSON docs.

    USER_KEYS_OID = "user.keys"

    @classmethod
    def _user_oid(cls, uid: str) -> str:
        return cls._meta_oid("user", uid)

    async def user_create(self, uid: str, display_name: str = "",
                          access_key: Optional[str] = None,
                          secret_key: Optional[str] = None) -> Dict:
        """Note: a gateway's STATIC bootstrap keys (S3Frontend users
        dict) take precedence over same-named durable keys — pick
        generated keys (the default) to stay clear of them."""
        if await self._load(self._user_oid(uid)) is not None:
            raise RGWError("UserAlreadyExists", uid)
        import os as _os

        access_key = access_key or \
            "AK" + _os.urandom(9).hex().upper()
        secret_key = secret_key or _os.urandom(20).hex()
        from ceph_tpu.rados.client import ObjectNotFound

        try:
            taken = await self.meta.omap_get(
                self._meta_oid(self.USER_KEYS_OID))
        except ObjectNotFound:
            taken = {}  # no users yet — any OTHER error must raise,
            # or a transient fault would disable the hijack guard
        if access_key in taken:
            # overwriting the index entry would hijack another
            # user's credential
            raise RGWError("KeyExists", access_key)
        doc = {"uid": uid, "display_name": display_name or uid,
               "keys": [{"access_key": access_key,
                         "secret_key": secret_key}],
               "suspended": False, "created": time.time()}
        await self._store(self._user_oid(uid), doc)
        await self.meta.omap_set(
            self._meta_oid(self.USER_KEYS_OID),
            {access_key: json.dumps(
                {"uid": uid, "secret": secret_key}).encode()})
        return doc

    async def user_info(self, uid: str) -> Dict:
        doc = await self._load(self._user_oid(uid))
        if doc is None:
            raise RGWError("NoSuchUser", uid)
        return doc

    async def user_list(self) -> List[str]:
        prefix = self._user_oid("")
        names = await self.meta.list_objects()
        return sorted(n[len(prefix):] for n in names
                      if n.startswith(prefix))

    async def user_set_suspended(self, uid: str,
                                 suspended: bool) -> None:
        doc = await self.user_info(uid)
        doc["suspended"] = bool(suspended)
        await self._store(self._user_oid(uid), doc)

    async def user_rm(self, uid: str) -> None:
        doc = await self.user_info(uid)
        await self.meta.omap_rm_keys(
            self._meta_oid(self.USER_KEYS_OID),
            [k["access_key"] for k in doc.get("keys", [])])
        await self.meta.remove(self._user_oid(uid))

    async def user_key_lookup(self, access_key: str
                              ) -> Optional[str]:
        """access key -> secret, or None (unknown / suspended).
        Transient cluster errors RAISE — "key unknown" and "meta
        pool unhealthy" must never look alike, or the frontend would
        evict valid cached credentials."""
        from ceph_tpu.rados.client import ObjectNotFound

        try:
            omap = await self.meta.omap_get(
                self._meta_oid(self.USER_KEYS_OID))
        except ObjectNotFound:
            return None  # no users ever created
        raw = omap.get(access_key)
        if raw is None:
            return None
        rec = json.loads(raw.decode())
        try:
            if (await self.user_info(rec["uid"])).get("suspended"):
                return None
        except RGWError:
            return None  # index entry orphaned by a partial rm
        return rec["secret"]

    # -- bucket notifications (rgw_notify / pubsub role) -------------------
    #
    # Reference parity: /root/reference/src/rgw/rgw_notify.cc +
    # cls_2pc_queue — per-bucket notification configs emit S3-shaped
    # event records on object mutations.  Zero-egress re-design: the
    # PERSISTENT QUEUE mode is the product (the reference has it too);
    # consumers pull and ack instead of receiving pushes.  Queue
    # objects are per-topic omaps with the same monotonic keys as the
    # sync log.

    @classmethod
    def _topic_oid(cls, topic: str) -> str:
        return cls._meta_oid("notify.topic", topic)

    async def put_bucket_notification(self, bucket: str,
                                      rules: List[Dict]) -> None:
        """rules: [{"id", "topic", "events": ["s3:ObjectCreated:*",
        ...], "filter_prefix": ""}] (PutBucketNotificationConfiguration
        role)."""
        for rule in rules:
            if not rule.get("topic"):
                raise RGWError("InvalidArgument", "rule needs a topic")
            if not rule.get("events"):
                # AWS rejects a configuration without Events; a
                # forgotten key must not silently subscribe to all
                raise RGWError("InvalidArgument", "rule needs events")
            for ev in rule["events"]:
                if not ev.startswith("s3:"):
                    raise RGWError("InvalidArgument",
                                   f"bad event {ev!r}")
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            doc["notifications"] = list(rules)
            await self._store(self._bucket_oid(bucket), doc)
        await self._log_change(bucket)

    async def get_bucket_notification(self,
                                      bucket: str) -> List[Dict]:
        return (await self._bucket(bucket)).get("notifications", [])

    @staticmethod
    def _event_matches(rule: Dict, event: str, key: str) -> bool:
        if key is not None and \
                not key.startswith(rule.get("filter_prefix", "")):
            return False
        for want in rule.get("events", []):  # no events: match none
            if want.endswith("*"):
                if event.startswith(want[:-1]):
                    return True
            elif want == event:
                return True
        return False

    async def _notify_event(self, doc: Optional[Dict], bucket: str,
                            key: str, event: str,
                            **fields) -> None:
        """Append one event record to every matching topic queue.
        `doc` is the (possibly already-loaded) bucket doc — None
        loads it."""
        if doc is None:
            try:
                doc = await self._bucket(bucket)
            except RGWError:
                return
        rules = [r for r in doc.get("notifications", [])
                 if self._event_matches(r, event, key)]
        if not rules:
            return
        entry_key = await self._next_log_key()
        record = {"eventName": event, "bucket": bucket, "key": key,
                  "eventTime": time.time(), "zone": self.zone}
        record.update({k: v for k, v in fields.items()
                       if v is not None})
        raw = json.dumps(record).encode()
        for rule in rules:
            await self.meta.omap_set(
                self._topic_oid(rule["topic"]), {entry_key: raw})

    async def pull_notifications(self, topic: str, max_events: int = 100
                                 ) -> List[Tuple[str, Dict]]:
        """Oldest-first events with their ack keys (the persistent-
        queue consumer surface)."""
        from ceph_tpu.rados.client import ObjectNotFound

        try:
            omap = await self.meta.omap_get(self._topic_oid(topic))
        except ObjectNotFound:
            return []  # topic never written — real I/O errors raise:
            # "empty queue" and "cluster unhealthy" must not look alike
        out = sorted((k, json.loads(v.decode()))
                     for k, v in omap.items())
        return out[:max_events]

    async def ack_notifications(self, topic: str,
                                keys: List[str]) -> None:
        if keys:
            await self.meta.omap_rm_keys(self._topic_oid(topic),
                                         list(keys))

    async def sync_log_trim(self, shard: int) -> int:
        """Drop entries every registered peer has applied (mdlog/
        datalog trim role).  Returns entries removed."""
        prefix = self._meta_oid("sync.peers", "")
        names = [n for n in await self.meta.list_objects()
                 if n.startswith(prefix)
                 and n.endswith(self._SEP + str(shard))]
        if not names:
            return 0
        floors = []
        for n in names:
            try:
                omap = await self.meta.omap_get(n)
                floors.append(omap.get("marker", b"").decode())
            except Exception:
                floors.append("")
        floor = min(floors)
        if not floor:
            return 0
        try:
            omap = await self.meta.omap_get(self._synclog_oid(shard))
        except Exception:
            return 0
        dead = [k for k in omap if k <= floor]
        if dead:
            await self.meta.omap_rm_keys(self._synclog_oid(shard),
                                         dead)
        return len(dead)

    # -- deferred stripe GC (rgw_gc.cc role) -------------------------------

    # GC queue shards (the rgw_gc_max_objs chain-shard role): mutation
    # churn across buckets spreads over GC_SHARDS independent queue
    # docs/locks instead of serializing on one hot object
    GC_SHARDS = 8

    async def _gc_load_locked(self, shard: int) -> Dict:
        """Load + normalize one GC shard doc (caller holds its lock).
        Legacy entries (pre-two-phase) get ids and count as ready."""
        doc = await self._load(self._gc_oid(shard)) or \
            {"entries": [], "next_id": 1}
        doc.setdefault("next_id", 1)
        for e in doc["entries"]:
            if "id" not in e:
                e["id"] = doc["next_id"]
                doc["next_id"] += 1
            e.setdefault("state", "ready")
        return doc

    async def _gc_defer(self, oids) -> List[Tuple[int, int]]:
        """Queue data objects for deferred deletion, state=PENDING.
        Two-phase against the index mutation (the cls_rgw chain-queue
        role, where the reference makes this atomic OSD-side): the
        entry lands BEFORE the index stops referencing the stripes, and
        only _gc_commit (called AFTER the index mutation persisted)
        makes it drainable.  A crash on either side of the index write
        therefore leaves a PENDING entry — a listable, reclaimable leak
        — never a deletion of still-referenced data and never a silent
        orphan.  Returns (shard, id) pairs for _gc_commit."""
        oids = [o for o in oids]
        if not oids:
            return []
        # one mutation's stripes land on one shard (one lock round
        # trip); successive mutations round-robin across shards
        shard = self._writes % self.GC_SHARDS
        async with self._meta_lock(self._gc_oid(shard)):
            doc = await self._gc_load_locked(shard)
            ids = []
            for o in oids:
                eid = doc["next_id"]
                doc["next_id"] += 1
                doc["entries"].append(
                    {"id": eid, "oid": o, "at": time.time(),
                     "state": "pending"})
                ids.append((shard, eid))
            await self._store(self._gc_oid(shard), doc)
        return ids

    async def _gc_commit(self, ids: List[Tuple[int, int]]) -> None:
        """Flip entries PENDING -> READY once the index mutation that
        dropped their references has persisted."""
        by_shard: Dict[int, set] = {}
        for shard, eid in ids:
            by_shard.setdefault(shard, set()).add(eid)
        for shard, want in by_shard.items():
            async with self._meta_lock(self._gc_oid(shard)):
                doc = await self._gc_load_locked(shard)
                for e in doc["entries"]:
                    if e["id"] in want:
                        e["state"] = "ready"
                await self._store(self._gc_oid(shard), doc)

    async def gc_list(self) -> List[Dict]:
        """Queue contents (rgw gc list): ready entries plus any
        pending leftovers from interrupted mutations."""
        out: List[Dict] = []
        for shard in range(self.GC_SHARDS):
            async with self._meta_lock(self._gc_oid(shard)):
                out.extend(
                    (await self._gc_load_locked(shard))["entries"])
        return out

    async def gc_process(self, max_entries: int = 0,
                         reclaim_pending_after: Optional[float] = None
                         ) -> int:
        """Drain READY queue entries (rgw gc process); returns entries
        removed.  Already-gone objects dequeue; any OTHER removal
        failure (down OSDs, timeouts) keeps its entry queued for the
        next sweep — dropping it would orphan the stripes, the exact
        leak deferred GC exists to prevent.  PENDING entries are
        skipped (their index mutation may never have committed, so the
        data may still be live) unless older than
        reclaim_pending_after — an explicit operator decision.

        Shard locks are held only around queue snapshots/updates, never
        across data-pool removals: a slow drain (down OSDs timing out)
        must not block PUT/DELETE mutations behind the queue docs."""
        from ceph_tpu.rados.client import ObjectNotFound

        now = time.time()
        done = 0
        for shard in range(self.GC_SHARDS):
            async with self._meta_lock(self._gc_oid(shard)):
                doc = await self._gc_load_locked(shard)
                eligible = [
                    e for e in doc["entries"]
                    if e["state"] == "ready"
                    or (reclaim_pending_after is not None
                        and now - e["at"] >= reclaim_pending_after)]
                if max_entries:
                    eligible = eligible[:max_entries - done]
            # lock released: removals run against a snapshot
            removed_ids = set()
            for entry in eligible:
                try:
                    await self.data.remove(entry["oid"])
                except ObjectNotFound:
                    pass
                except Exception:
                    continue  # stays queued for the next sweep
                removed_ids.add(entry["id"])
                done += 1
            if removed_ids:
                async with self._meta_lock(self._gc_oid(shard)):
                    doc = await self._gc_load_locked(shard)
                    doc["entries"] = [e for e in doc["entries"]
                                      if e["id"] not in removed_ids]
                    await self._store(self._gc_oid(shard), doc)
            if max_entries and done >= max_entries:
                break
        return done

    def start_gc(self, interval: float = 30.0) -> None:
        """Spawn the background GC sweep (the rgw_gc worker-thread
        role).  Idempotent; stop with stop_gc()."""
        import asyncio

        if self._gc_task is not None and not self._gc_task.done():
            return

        async def sweep():
            import logging

            while True:
                await asyncio.sleep(interval)
                try:
                    await self.gc_process()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # next sweep retries; entries never drop — but a
                    # persistently failing sweep must be VISIBLE or
                    # garbage accumulates behind a healthy-looking
                    # gateway
                    logging.getLogger("rgw").exception(
                        "gc sweep failed; will retry in %.0fs",
                        interval)

        self._gc_task = asyncio.get_running_loop().create_task(sweep())

    async def stop_gc(self) -> None:
        import asyncio

        if self._gc_task is not None:
            self._gc_task.cancel()
            try:
                await self._gc_task
            except asyncio.CancelledError:
                pass
            self._gc_task = None

    # -- versioning (RGWSetBucketVersioning / versioned PUT-GET-DEL) -------

    async def put_bucket_versioning(self, bucket: str, status: str,
                                    _origin: Optional[str] = None
                                    ) -> None:
        if status not in (VER_ENABLED, VER_SUSPENDED):
            raise RGWError("InvalidRequest", f"bad status {status!r}")
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            doc["versioning"] = status
            await self._store(self._bucket_oid(bucket), doc)
        await self._log_change(bucket, origin=_origin)

    async def get_bucket_versioning(self, bucket: str) -> str:
        return (await self._bucket(bucket)).get("versioning", VER_OFF)

    def _new_version_id(self) -> str:
        self._writes += 1
        return f"v{int(time.time() * 1000):x}.{self._writes}"

    async def _versions(self, bucket: str, key: str) -> Dict:
        return await self._load(self._versions_oid(bucket, key))             or {"versions": []}

    async def list_object_versions(self, bucket: str,
                                   prefix: str = "") -> List[Dict]:
        """GET ?versions: every version and delete marker, newest
        first per key (RGWListBucketVersions)."""
        doc = await self._bucket(bucket)
        out: List[Dict] = []
        keys = sorted(set(doc["objects"])
                      | set(doc.get("versioned_keys", [])))
        for key in keys:
            if not key.startswith(prefix):
                continue
            vdoc = await self._versions(bucket, key)
            if vdoc["versions"]:
                for v in vdoc["versions"]:
                    out.append(dict(v, key=key))
            else:
                # never-versioned key: listed as VersionId "null"
                # (S3 lists unversioned objects this way)
                ent = doc["objects"][key]
                out.append({"key": key, "version_id": "null",
                            "etag": ent.get("etag", ""),
                            "size": ent.get("size", 0),
                            "mtime": ent.get("mtime", 0),
                            "delete_marker": False})
        return out

    # -- buckets -----------------------------------------------------------

    async def create_bucket(self, bucket: str, owner: str = "",
                            acl: str = "private",
                            _origin: Optional[str] = None) -> None:
        if acl not in CANNED_ACLS:
            raise RGWError("InvalidArgument", f"bad acl {acl!r}")
        if await self._load(self._bucket_oid(bucket)) is not None:
            raise RGWError("BucketAlreadyExists", bucket)
        await self._store(self._bucket_oid(bucket),
                          {"name": bucket, "objects": {},
                           "owner": owner, "acl": acl})
        await self._log_change(bucket, origin=_origin)

    # -- ACLs (rgw_acl.cc / RGWAccessControlPolicy role) -------------------

    async def get_bucket_acl_info(self, bucket: str) -> Dict[str, str]:
        doc = await self._bucket(bucket)
        return {"owner": doc.get("owner", ""),
                "acl": doc.get("acl", "private")}

    async def put_bucket_acl(self, bucket: str, acl: str,
                             _origin: Optional[str] = None) -> None:
        if acl not in CANNED_ACLS:
            raise RGWError("InvalidArgument", f"bad acl {acl!r}")
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            doc["acl"] = acl
            await self._store(self._bucket_oid(bucket), doc)
        await self._log_change(bucket, origin=_origin)

    async def get_object_acl(self, bucket: str, key: str) -> str:
        doc = await self._bucket(bucket)
        entry = doc["objects"].get(key)
        if entry is None:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        return entry.get("acl", "private")

    async def put_object_acl(self, bucket: str, key: str, acl: str,
                             _origin: Optional[str] = None) -> None:
        if acl not in CANNED_ACLS:
            raise RGWError("InvalidArgument", f"bad acl {acl!r}")
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            entry = doc["objects"].get(key)
            if entry is None:
                raise RGWError("NoSuchKey", f"{bucket}/{key}")
            entry["acl"] = acl
            await self._store(self._bucket_oid(bucket), doc)
        await self._log_change(bucket, key, origin=_origin)

    async def _bucket(self, bucket: str) -> Dict:
        doc = await self._load(self._bucket_oid(bucket))
        if doc is None:
            raise RGWError("NoSuchBucket", bucket)
        return doc

    async def list_objects(self, bucket: str,
                           prefix: str = "") -> List[Dict[str, Any]]:
        doc = await self._bucket(bucket)
        return [dict(v, key=k)
                for k, v in sorted(doc["objects"].items())
                if k.startswith(prefix)]

    async def list_objects_v2(self, bucket: str, prefix: str = "",
                              delimiter: str = "",
                              continuation_token: str = "",
                              max_keys: int = 1000) -> Dict[str, Any]:
        """ListObjectsV2 (RGWListBucket::execute with v2 semantics):
        prefix filter, delimiter roll-up into CommonPrefixes,
        continuation token (start strictly after), max-keys
        truncation counting contents + prefixes."""
        doc = await self._bucket(bucket)
        if max_keys <= 0:
            # S3 semantics: max-keys=0 is a valid request returning no
            # entries and IsTruncated=false (a truncated=true answer
            # with an empty token would loop naive paginators forever)
            return {"contents": [], "common_prefixes": [],
                    "is_truncated": False, "next_token": ""}
        contents: List[Dict[str, Any]] = []
        prefixes: List[str] = []
        truncated = False
        next_token = ""
        last_seen = ""
        for key in sorted(doc["objects"]):
            if not key.startswith(prefix):
                continue
            if continuation_token and key <= continuation_token:
                continue
            if delimiter:
                rest = key[len(prefix):]
                cut = rest.find(delimiter)
                if cut >= 0:
                    cp = prefix + rest[:cut + len(delimiter)]
                    if prefixes and prefixes[-1] == cp:
                        last_seen = key
                        continue
                    if len(contents) + len(prefixes) >= max_keys:
                        truncated = True
                        break
                    prefixes.append(cp)
                    last_seen = key
                    continue
            if len(contents) + len(prefixes) >= max_keys:
                truncated = True
                break
            contents.append(dict(doc["objects"][key], key=key))
            last_seen = key
        if truncated:
            # the token is the LAST RETURNED key: continuation resumes
            # strictly after it (a first-excluded-key token would skip
            # that key on the next page)
            next_token = last_seen
        return {"contents": contents, "common_prefixes": prefixes,
                "is_truncated": truncated,
                "next_token": next_token if truncated else ""}

    # -- lifecycle (rgw_lc.cc role) ----------------------------------------

    async def put_bucket_lifecycle(self, bucket: str,
                                   rules: List[Dict],
                                   _origin: Optional[str] = None
                                   ) -> None:
        for rule in rules:
            if rule.get("status", "Enabled") not in ("Enabled",
                                                     "Disabled"):
                raise RGWError("InvalidRequest", "bad rule status")
            if not any(k in rule for k in
                       ("expiration_days", "noncurrent_days",
                        "abort_multipart_days")):
                raise RGWError("InvalidRequest",
                               "rule with no action")
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            doc["lifecycle"] = list(rules)
            await self._store(self._bucket_oid(bucket), doc)
        await self._log_change(bucket, origin=_origin)

    async def get_bucket_lifecycle(self, bucket: str) -> List[Dict]:
        return (await self._bucket(bucket)).get("lifecycle", [])

    async def lifecycle_process(self,
                                now: Optional[float] = None
                                ) -> Dict[str, int]:
        """One LC sweep over every bucket (RGWLC::process): expire
        current objects, prune noncurrent versions, drop lone delete
        markers, abort stale multipart uploads.  `now` is injectable
        for tests."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "noncurrent_pruned": 0,
                 "markers_removed": 0, "uploads_aborted": 0}
        for bucket in await self.list_buckets():
            doc = await self._bucket(bucket)
            rules = [r for r in doc.get("lifecycle", [])
                     if r.get("status", "Enabled") == "Enabled"]
            if not rules:
                continue
            for rule in rules:
                await self._lc_rule(bucket, rule, now, stats)
        return stats

    async def _lc_rule(self, bucket: str, rule: Dict, now: float,
                       stats: Dict[str, int]) -> None:
        prefix = rule.get("prefix", "")
        day = 86400.0
        exp = rule.get("expiration_days")
        if exp is not None:
            doc = await self._bucket(bucket)
            for key, ent in list(doc["objects"].items()):
                if key.startswith(prefix) and \
                        now - ent.get("mtime", now) > exp * day:
                    await self.delete_object(bucket, key)
                    stats["expired"] += 1
        nc = rule.get("noncurrent_days")
        if nc is not None:
            doc = await self._bucket(bucket)
            for key in list(doc.get("versioned_keys", [])):
                if not key.startswith(prefix):
                    continue
                vdoc = await self._versions(bucket, key)
                for v in vdoc["versions"][1:]:
                    if now - v["mtime"] > nc * day:
                        await self._delete_version(bucket, key,
                                                   v["version_id"])
                        stats["noncurrent_pruned"] += 1
                # a delete marker left as the ONLY version expires
                # with it (expired-object delete marker cleanup)
                vdoc = await self._versions(bucket, key)
                if len(vdoc["versions"]) == 1 and \
                        vdoc["versions"][0]["delete_marker"]:
                    await self._delete_version(
                        bucket, key, vdoc["versions"][0]["version_id"])
                    stats["markers_removed"] += 1
        ab = rule.get("abort_multipart_days")
        if ab is not None:
            uploads = await self.list_multipart_uploads(bucket)
            for up in uploads:
                if up["key"].startswith(prefix) and \
                        now - up.get("created", now) > ab * day:
                    await self.abort_multipart(bucket, up["key"],
                                               up["upload_id"])
                    stats["uploads_aborted"] += 1

    async def list_multipart_uploads(self, bucket: str) -> List[Dict]:
        """In-progress uploads for a bucket (ListMultipartUploads)."""
        prefix = self._meta_oid("multipart", bucket, "")
        names = await self.meta.list_objects()
        out = []
        for n in names:
            if not n.startswith(prefix):
                continue
            doc = await self._load(n)
            if doc is not None:
                _, _, key, upload_id = n.split(self._SEP, 3)
                out.append({"key": key, "upload_id": upload_id,
                            "created": doc.get("created")})
        return out

    async def list_buckets(self) -> List[str]:
        """ListAllMyBuckets role — the bucket.index objects ARE the
        truth (a separate registry doc could desync on a crash between
        two writes); enumerate them from the meta pool."""
        prefix = self._bucket_oid("")
        names = await self.meta.list_objects()
        return sorted(n[len(prefix):] for n in names
                      if n.startswith(prefix))

    async def delete_bucket(self, bucket: str,
                            _origin: Optional[str] = None) -> None:
        # emptiness check + removal under the bucket meta lock: a PUT
        # linking a new object concurrently must not be orphaned by a
        # delete that checked before the link landed
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            if doc["objects"] or doc.get("versioned_keys"):
                raise RGWError("BucketNotEmpty", bucket)
            await self.meta.remove(self._bucket_oid(bucket))
        await self._log_change(bucket, origin=_origin)

    async def head_object(self, bucket: str, key: str
                          ) -> Dict[str, Any]:
        doc = await self._bucket(bucket)
        entry = doc["objects"].get(key)
        if entry is None:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        return dict(entry, key=key)

    # -- atomic PUT / GET / DELETE ----------------------------------------

    async def put_object(self, bucket: str, key: str,
                         data: bytes) -> str:
        etag, _vid = await self.put_object_ex(bucket, key, data)
        return etag

    async def put_object_ex(self, bucket: str, key: str,
                            data: bytes, acl: Optional[str] = None,
                            _origin: Optional[str] = None
                            ) -> Tuple[str, Optional[str]]:
        """Single-shot PUT (RGWPutObj + AtomicObjectProcessor role);
        under versioning every PUT lands as a new immutable version
        (rgw_op.cc:3712's versioned path).  Returns (etag, version_id)
        — version_id None on unversioned buckets.  _origin: the
        originating zone when applied by a sync agent (rides the
        change log so the write is not echoed back)."""
        await self._bucket(bucket)  # existence check before the write
        writer = StripeWriter(self.data, self.aio_window)
        prefix = f"{self._head_oid(bucket, key)}.{self._write_id()}"
        proc = PutObjProcessor(writer, prefix, self.stripe_size)
        try:
            await proc.process(data)
            manifest = await proc.complete()
        except Exception:
            await writer.cancel()
            raise
        etag = self._etag_from_manifest(manifest, data)
        return await self._link_by_status(bucket, key, manifest, etag,
                                          acl=acl, _origin=_origin)

    async def _link_by_status(self, bucket: str, key: str,
                              manifest: Manifest, etag: str,
                              acl: Optional[str] = None,
                              _origin: Optional[str] = None,
                              event: str = "s3:ObjectCreated:Put"
                              ) -> Tuple[str, Optional[str]]:
        """Link a finished upload under ONE bucket lock, adjudicating
        the versioning status AT LINK TIME — a versioning flip during
        the (long) stripe upload must not split-brain the key into a
        head doc coexisting with a versions doc.  Shared by atomic PUT
        and multipart completion."""
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            status = doc.get("versioning", VER_OFF)
            vdoc = await self._versions(bucket, key)
            if status == VER_OFF and not vdoc["versions"]:
                await self._link_locked(doc, bucket, key, manifest,
                                        etag, acl=acl)
                await self._log_change(bucket, key, origin=_origin)
                await self._notify_event(doc, bucket, key, event,
                                         etag=etag,
                                         size=manifest.obj_size)
                return etag, None
            # versioned path — also when the key ALREADY has versions
            # with versioning since switched off: existing versions
            # must never be silently clobbered by a head doc
            vid = await self._link_version_locked(
                doc, vdoc, bucket, key, manifest, etag,
                null_version=(status != VER_ENABLED), acl=acl)
            await self._log_change(bucket, key, origin=_origin)
            await self._notify_event(doc, bucket, key, event,
                                     etag=etag,
                                     size=manifest.obj_size,
                                     version_id=vid)
            return etag, vid

    async def _link_locked(self, doc: Dict, bucket: str, key: str,
                           manifest: Manifest, etag: str,
                           acl: Optional[str] = None) -> None:
        """Unversioned head flip + index entry (the bucket index
        transaction role of AtomicObjectProcessor::complete); caller
        holds the bucket lock.  Replaced stripes go to deferred GC."""
        head_doc = self._meta_oid("head", bucket, key)
        old = await self._load(head_doc)
        gc_ids: List[int] = []
        if old is not None:
            # defer BEFORE the head flip (entry-lands-first invariant):
            # a crash mid-overwrite leaves a pending entry, not an
            # untracked orphan of the replaced stripes
            new_oids = {s["oid"] for s in manifest.stripes}
            gc_ids = await self._gc_defer(
                stripe["oid"] for stripe in old["manifest"]["stripes"]
                if stripe["oid"] not in new_oids)
        await self._store(head_doc, {"manifest": manifest.to_dict(),
                                     "etag": etag})
        entry = {"size": manifest.obj_size,
                 "etag": etag, "mtime": time.time()}
        if acl is None:
            # an overwrite without an explicit canned ACL keeps the
            # previous object ACL (S3: each PUT resets to private
            # unless x-amz-acl given; kept here because the frontend
            # always passes the effective canned value)
            prev = doc["objects"].get(key, {})
            if "acl" in prev:
                entry["acl"] = prev["acl"]
        else:
            entry["acl"] = acl
        doc["objects"][key] = entry
        await self._store(self._bucket_oid(bucket), doc)
        await self._gc_commit(gc_ids)

    async def _migrate_legacy_head(self, bucket: str,
                                   key: str) -> List[Dict]:
        """First versioned write to a pre-versioning key: fold the
        legacy head into a "null" version so it stays addressable."""
        head = await self._load(self._meta_oid("head", bucket, key))
        if head is None:
            return []
        await self.meta.remove(self._meta_oid("head", bucket, key))
        return [{"version_id": "null", "etag": head["etag"],
                 "manifest": head["manifest"],
                 "size": head["manifest"]["obj_size"],
                 "mtime": time.time(), "delete_marker": False}]

    async def _link_version_locked(self, doc: Dict, vdoc: Dict,
                                   bucket: str, key: str,
                                   manifest: Manifest, etag: str,
                                   null_version: bool,
                                   acl: Optional[str] = None) -> str:
        vid = "null" if null_version else self._new_version_id()
        entry = {"version_id": vid, "etag": etag,
                 "manifest": manifest.to_dict(),
                 "size": manifest.obj_size, "mtime": time.time(),
                 "delete_marker": False}
        if not vdoc["versions"]:
            vdoc["versions"] = await self._migrate_legacy_head(
                bucket, key)
        gc_ids: List[int] = []
        if null_version:
            # suspended: the new null version REPLACES a previous
            # null (its stripes go to GC); other versions survive
            for old in vdoc["versions"]:
                if old["version_id"] == "null" and \
                        not old["delete_marker"]:
                    gc_ids.extend(await self._gc_defer(
                        st["oid"]
                        for st in old["manifest"]["stripes"]))
            vdoc["versions"] = [v for v in vdoc["versions"]
                                if v["version_id"] != "null"]
        vdoc["versions"].insert(0, entry)
        await self._store(self._versions_oid(bucket, key), vdoc)
        head_entry = {"size": manifest.obj_size,
                      "etag": etag, "mtime": entry["mtime"]}
        if acl is not None:
            head_entry["acl"] = acl
        elif "acl" in doc["objects"].get(key, {}):
            head_entry["acl"] = doc["objects"][key]["acl"]
        doc["objects"][key] = head_entry
        vk = set(doc.setdefault("versioned_keys", []))
        vk.add(key)
        doc["versioned_keys"] = sorted(vk)
        await self._store(self._bucket_oid(bucket), doc)
        await self._gc_commit(gc_ids)
        return vid

    async def _manifest(self, bucket: str, key: str,
                        version_id: Optional[str] = None
                        ) -> Tuple[Manifest, str]:
        vdoc = await self._load(self._versions_oid(bucket, key))
        if vdoc is not None and vdoc["versions"]:
            if version_id is None:
                newest = vdoc["versions"][0]
                if newest["delete_marker"]:
                    raise RGWError("NoSuchKey",
                                   f"{bucket}/{key} (delete marker)")
                entry = newest
            else:
                entry = next((v for v in vdoc["versions"]
                              if v["version_id"] == version_id), None)
                if entry is None:
                    raise RGWError("NoSuchVersion",
                                   f"{bucket}/{key}@{version_id}")
                if entry["delete_marker"]:
                    raise RGWError("MethodNotAllowed",
                                   "version is a delete marker")
            return Manifest.from_dict(entry["manifest"]), entry["etag"]
        if version_id is not None and version_id != "null":
            raise RGWError("NoSuchVersion",
                           f"{bucket}/{key}@{version_id}")
        head = await self._load(self._meta_oid("head", bucket, key))
        if head is None:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        return Manifest.from_dict(head["manifest"]), head["etag"]

    async def get_object(self, bucket: str, key: str) -> bytes:
        data, _etag_ = await self.get_object_ex(bucket, key)
        return data

    async def get_object_ex(self, bucket: str, key: str,
                            version_id: Optional[str] = None,
                            byte_range: Optional[Tuple[int, int]] = None,
                            range_resolver=None
                            ) -> Tuple[bytes, str]:
        """GET: walk the manifest, fetch stripes concurrently;
        returns (bytes, etag) from ONE head load.

        byte_range=(first, last) — absolute inclusive offsets —
        fetches ONLY the overlapping sub-ranges of the touched
        stripes: a ranged S3 GET of a huge object moves O(range), not
        O(object), and each sub-read rides the OSD's ranged EC read
        path (and counts as a tier read).  range_resolver is the
        single-head-load form: called with the authoritative
        manifest.obj_size, it returns (first, last) or None (serve
        the full object) — or raises, which propagates (the
        frontend's 416)."""
        import asyncio

        manifest, etag = await self._manifest(bucket, key, version_id)
        sem = asyncio.Semaphore(self.aio_window)

        if range_resolver is not None:
            byte_range = range_resolver(manifest.obj_size)
        if byte_range is not None:
            first, last = byte_range
            last = min(last, manifest.obj_size - 1)
            reads: List[Tuple[str, int, int]] = []
            off = 0
            for s in manifest.stripes:
                lo, hi = max(first, off), min(last, off + s["size"] - 1)
                if lo <= hi:
                    reads.append((s["oid"], lo - off, hi - lo + 1))
                off += s["size"]
                if off > last:
                    break

            async def fetch_range(oid: str, ofs: int, ln: int) -> bytes:
                async with sem:
                    return await self.data.read(oid, offset=ofs,
                                                length=ln)

            parts = await asyncio.gather(
                *(fetch_range(*r) for r in reads))
            return b"".join(parts), etag

        async def fetch(stripe: Dict) -> bytes:
            async with sem:
                return await self.data.read(stripe["oid"])

        parts = await asyncio.gather(
            *(fetch(s) for s in manifest.stripes))
        out = b"".join(p[:s["size"]]
                       for p, s in zip(parts, manifest.stripes))
        if len(out) != manifest.obj_size:
            raise RGWError("IncompleteBody",
                           f"{len(out)} != {manifest.obj_size}")
        return out, etag

    async def delete_object(self, bucket: str, key: str,
                            version_id: Optional[str] = None,
                            _origin: Optional[str] = None
                            ) -> Optional[str]:
        out = await self._delete_object_impl(bucket, key, version_id)
        await self._log_change(bucket, key, origin=_origin)
        await self._notify_event(
            None, bucket, key,
            "s3:ObjectRemoved:DeleteMarkerCreated" if out is not None
            else "s3:ObjectRemoved:Delete",
            version_id=out or version_id)
        return out

    async def _delete_object_impl(self, bucket: str, key: str,
                                  version_id: Optional[str] = None
                                  ) -> Optional[str]:
        """DELETE, adjudicated under ONE bucket lock.  Unversioned:
        drop the object (stripes deferred to GC).  Versioning enabled
        + no versionId: insert a DELETE MARKER (versions survive).
        versionId given: permanently remove that version — "null"
        addresses a never-versioned object too; anything else on an
        unversioned key is NoSuchVersion (rgw_op.cc RGWDeleteObj
        versioned semantics).  Returns the delete marker's version id
        when one was created."""
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            status = doc.get("versioning", VER_OFF)
            vdoc = await self._versions(bucket, key)
            versioned = bool(vdoc["versions"])
            if version_id is not None:
                if versioned:
                    self._drop_version_locked(vdoc, version_id)
                    await self._finish_versions_locked(doc, bucket,
                                                       key, vdoc)
                    return None
                if version_id != "null":
                    raise RGWError("NoSuchVersion",
                                   f"{bucket}/{key}@{version_id}")
                # versionId=null on a never-versioned key: the plain
                # object IS the null version — permanent delete
                await self._delete_unversioned_locked(doc, bucket,
                                                      key)
                return None
            if status == VER_ENABLED:
                if not vdoc["versions"]:
                    vdoc["versions"] = \
                        await self._migrate_legacy_head(bucket, key)
                    if not vdoc["versions"]:
                        raise RGWError("NoSuchKey", f"{bucket}/{key}")
                marker = {"version_id": self._new_version_id(),
                          "etag": "", "manifest": None, "size": 0,
                          "mtime": time.time(), "delete_marker": True}
                vdoc["versions"].insert(0, marker)
                await self._store(self._versions_oid(bucket, key),
                                  vdoc)
                doc["objects"].pop(key, None)
                vk = set(doc.setdefault("versioned_keys", []))
                vk.add(key)
                doc["versioned_keys"] = sorted(vk)
                await self._store(self._bucket_oid(bucket), doc)
                return marker["version_id"]
            if versioned:
                # suspended: remove the null version and leave a null
                # delete marker, in ONE locked mutation (S3 suspended
                # semantics; a two-lock version let a concurrent null
                # PUT interleave and duplicate the null id)
                self._drop_version_locked(vdoc, "null",
                                          missing_ok=True)
                gc_ids = await self._gc_defer(vdoc.pop("_gc", []))
                marker = {"version_id": "null", "etag": "",
                          "manifest": None, "size": 0,
                          "mtime": time.time(), "delete_marker": True}
                vdoc["versions"].insert(0, marker)
                await self._store(self._versions_oid(bucket, key),
                                  vdoc)
                doc["objects"].pop(key, None)
                await self._store(self._bucket_oid(bucket), doc)
                await self._gc_commit(gc_ids)
                return "null"
            await self._delete_unversioned_locked(doc, bucket, key)
            return None

    async def _delete_unversioned_locked(self, doc: Dict, bucket: str,
                                         key: str) -> None:
        head = await self._load(self._meta_oid("head", bucket, key))
        if head is None:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        gc_ids = await self._gc_defer(
            st["oid"] for st in head["manifest"]["stripes"])
        await self.meta.remove(self._meta_oid("head", bucket, key))
        doc["objects"].pop(key, None)
        await self._store(self._bucket_oid(bucket), doc)
        await self._gc_commit(gc_ids)

    def _drop_version_locked(self, vdoc: Dict, version_id: str,
                             missing_ok: bool = False) -> None:
        """Remove one version from an in-memory vdoc, deferring its
        stripes; caller persists + refreshes the index."""
        entry = next((v for v in vdoc["versions"]
                      if v["version_id"] == version_id), None)
        if entry is None:
            if missing_ok:
                return
            raise RGWError("NoSuchVersion", version_id)
        vdoc["versions"] = [v for v in vdoc["versions"]
                            if v["version_id"] != version_id]
        if entry["manifest"] is not None:
            vdoc.setdefault("_gc", []).extend(
                st["oid"] for st in entry["manifest"]["stripes"])

    async def _finish_versions_locked(self, doc: Dict, bucket: str,
                                      key: str, vdoc: Dict) -> None:
        """Persist a mutated vdoc + refresh the bucket index; flush
        any stripes _drop_version_locked queued."""
        gc_ids = await self._gc_defer(vdoc.pop("_gc", []))
        if vdoc["versions"]:
            await self._store(self._versions_oid(bucket, key), vdoc)
        else:
            try:
                await self.meta.remove(self._versions_oid(bucket,
                                                          key))
            except Exception:
                pass
            vk = set(doc.get("versioned_keys", []))
            vk.discard(key)
            doc["versioned_keys"] = sorted(vk)
        # refresh the plain listing: newest surviving non-marker
        newest = next((v for v in vdoc["versions"]
                       if not v["delete_marker"]), None)
        newest_is_head = vdoc["versions"] and \
            vdoc["versions"][0] is newest
        if newest is not None and newest_is_head:
            doc["objects"][key] = {"size": newest["size"],
                                   "etag": newest["etag"],
                                   "mtime": newest["mtime"]}
        else:
            doc["objects"].pop(key, None)
        await self._store(self._bucket_oid(bucket), doc)
        await self._gc_commit(gc_ids)

    async def _delete_version(self, bucket: str, key: str,
                              version_id: str,
                              missing_ok: bool = False) -> None:
        """Public per-version delete (lock-acquiring wrapper)."""
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            vdoc = await self._versions(bucket, key)
            self._drop_version_locked(vdoc, version_id, missing_ok)
            await self._finish_versions_locked(doc, bucket, key, vdoc)

    # -- multisite apply seam (fetch_remote_obj role) ----------------------

    async def sync_replace_versions(self, bucket: str, key: str,
                                    src_versions: List[Dict],
                                    blobs: Dict[str, bytes],
                                    origin: str) -> None:
        """Make this zone's version set for (bucket, key) EXACTLY
        match a peer's, preserving version ids, mtimes and order (the
        reference replicates version ids across zones —
        rgw_data_sync.cc fetch_remote_obj with preset attrs).
        src_versions: the peer's newest-first version list; blobs:
        data for version ids this zone lacks.  Stripes are written
        before the lock; dropped versions' stripes go to GC."""
        uploaded: Dict[str, Manifest] = {}
        for v in src_versions:
            vid = v["version_id"]
            if v.get("delete_marker") or vid not in blobs:
                continue
            writer = StripeWriter(self.data, self.aio_window)
            prefix = (f"{self._head_oid(bucket, key)}"
                      f".{self._write_id()}")
            proc = PutObjProcessor(writer, prefix, self.stripe_size)
            try:
                await proc.process(blobs[vid])
                uploaded[vid] = await proc.complete()
            except Exception:
                await writer.cancel()
                raise
        async with self._meta_lock(self._bucket_oid(bucket)):
            doc = await self._bucket(bucket)
            vdoc = await self._versions(bucket, key)
            if not vdoc["versions"]:
                # a plain pre-versioning head here must fold into the
                # "null" version (same discipline as the local
                # versioned-write path) or its head doc and stripes
                # would be orphaned under the new version set
                vdoc["versions"] = await self._migrate_legacy_head(
                    bucket, key)
            have = {v["version_id"]: v for v in vdoc["versions"]}
            new_list: List[Dict] = []
            for v in src_versions:
                vid = v["version_id"]
                if vid in uploaded:
                    # freshly fetched peer data WINS over a same-id
                    # local entry (a divergent "null" version): the
                    # loser's stripes are garbage
                    old = have.pop(vid, None)
                    if old is not None and old.get("manifest"):
                        vdoc.setdefault("_gc", []).extend(
                            st["oid"]
                            for st in old["manifest"]["stripes"])
                    m = uploaded[vid]
                    new_list.append(
                        {"version_id": vid,
                         "etag": v.get("etag", ""),
                         "manifest": m.to_dict(),
                         "size": m.obj_size,
                         "mtime": v.get("mtime", time.time()),
                         "delete_marker": False})
                elif vid in have:
                    new_list.append(have.pop(vid))
                elif v.get("delete_marker"):
                    new_list.append(
                        {"version_id": vid, "etag": "",
                         "manifest": None, "size": 0,
                         "mtime": v.get("mtime", time.time()),
                         "delete_marker": True})
                # else: peer listed it but no blob arrived (raced a
                # source-side delete) — next log entry reconciles
            # versions only we had: their stripes are garbage now
            vdoc["versions"] = new_list
            for dead in have.values():
                if dead.get("manifest"):
                    vdoc.setdefault("_gc", []).extend(
                        st["oid"]
                        for st in dead["manifest"]["stripes"])
            if new_list:
                vk = set(doc.setdefault("versioned_keys", []))
                vk.add(key)
                doc["versioned_keys"] = sorted(vk)
            await self._finish_versions_locked(doc, bucket, key,
                                               vdoc)
        await self._log_change(bucket, key, origin=origin)

    # -- multipart ---------------------------------------------------------

    async def init_multipart(self, bucket: str, key: str,
                             acl: Optional[str] = None) -> str:
        """RGWInitMultipart role: mint an upload id, persist state.
        acl: canned ACL from the initiate request, applied when the
        upload completes (S3 binds the ACL at initiate time)."""
        await self._bucket(bucket)
        if acl is not None and acl not in CANNED_ACLS:
            raise RGWError("InvalidArgument", f"bad acl {acl!r}")
        self._uploads += 1
        upload_id = f"u{self._uploads}-{int(time.time() * 1000):x}"
        await self._store(self._upload_oid(bucket, key, upload_id),
                          {"bucket": bucket, "key": key,
                           "created": time.time(), "parts": {},
                           "acl": acl})
        return upload_id

    async def _upload(self, bucket: str, key: str,
                      upload_id: str) -> Dict:
        doc = await self._load(self._upload_oid(bucket, key, upload_id))
        if doc is None:
            raise RGWError("NoSuchUpload", upload_id)
        return doc

    def _part_prefix(self, bucket: str, key: str, upload_id: str,
                     part_num: int, write_id: str) -> str:
        # the reference's part naming (<key>._multipart_.<uploadid>.<num>)
        # plus a unique write id so a part RE-upload writes fresh
        # objects instead of clobbering the live ones
        return self._SEP.join(
            (bucket, f"{MULTIPART_PREFIX}{key}"
                     f".{upload_id}.{part_num}.{write_id}"))

    async def upload_part(self, bucket: str, key: str, upload_id: str,
                          part_num: int, data: bytes) -> str:
        """MultipartObjectProcessor role: a part is its own striped
        object family; re-upload of the same part replaces it.
        Concurrent parts of one upload are the normal S3 pattern, so
        the upload-doc update is serialized per upload."""
        if part_num < 1 or part_num > 10000:
            raise RGWError("InvalidPart", str(part_num))
        await self._upload(bucket, key, upload_id)  # upload must exist
        writer = StripeWriter(self.data, self.aio_window)
        proc = PutObjProcessor(
            writer, self._part_prefix(bucket, key, upload_id, part_num,
                                      self._write_id()),
            self.stripe_size)
        try:
            await proc.process(data)
            manifest = await proc.complete()
        except Exception:
            await writer.cancel()
            raise
        etag = self._etag_from_manifest(manifest, data)
        upload_oid = self._upload_oid(bucket, key, upload_id)
        async with self._meta_lock(upload_oid):
            doc = await self._upload(bucket, key, upload_id)
            old = doc["parts"].get(str(part_num))
            doc["parts"][str(part_num)] = {
                "etag": etag, "size": manifest.obj_size,
                "manifest": manifest.to_dict()}
            await self._store(upload_oid, doc)
        if old is not None:  # GC the replaced part's stripes
            for stripe in old["manifest"]["stripes"]:
                try:
                    await self.data.remove(stripe["oid"])
                except Exception:
                    pass
        return etag

    async def complete_multipart(self, bucket: str, key: str,
                                 upload_id: str,
                                 parts: List[Tuple[int, str]]) -> str:
        """RGWCompleteMultipart::execute role (rgw_op.cc:5933): validate
        the client's part list, stitch part manifests in part order,
        write the head, unlink upload state."""
        doc = await self._upload(bucket, key, upload_id)
        if not parts:
            raise RGWError("InvalidRequest", "empty part list")
        nums = [p[0] for p in parts]
        if nums != sorted(nums) or len(set(nums)) != len(nums):
            raise RGWError("InvalidPartOrder", str(nums))
        stitched = Manifest(stripe_size=self.stripe_size)
        etags = []
        for num, etag in parts:
            part = doc["parts"].get(str(num))
            if part is None or part["etag"] != etag:
                raise RGWError("InvalidPart", f"part {num}")
            stitched.append(Manifest.from_dict(part["manifest"]))
            etags.append(etag)
        # multipart etag (S3 semantics): md5 over the concatenated
        # part md5 DIGESTS (raw bytes, not hex), suffixed "-<nparts>"
        combined = _etag(b"".join(
            bytes.fromhex(e) for e in etags)) + f"-{len(parts)}"
        # versioning adjudicated at link time, same as atomic PUT —
        # a multipart completion on a versioned bucket lands as a
        # version, never as a stray head doc
        _etag_, _vid = await self._link_by_status(
            bucket, key, stitched, combined, acl=doc.get("acl"),
            event="s3:ObjectCreated:CompleteMultipartUpload")
        await self.meta.remove(self._upload_oid(bucket, key, upload_id))
        return combined

    async def abort_multipart(self, bucket: str, key: str,
                              upload_id: str) -> None:
        """RGWAbortMultipart role: delete parts + upload state."""
        doc = await self._upload(bucket, key, upload_id)
        for part in doc["parts"].values():
            for stripe in part["manifest"]["stripes"]:
                try:
                    await self.data.remove(stripe["oid"])
                except Exception:
                    pass
        await self.meta.remove(self._upload_oid(bucket, key, upload_id))

"""S3 HTTP frontend: the gateway's real front door.

Reference parity:
- asio HTTP frontend (/root/reference/src/rgw/rgw_asio_frontend.cc:
  1-1059) -> an asyncio HTTP/1.1 server with keep-alive, re-designed
  for the single-event-loop daemon shape.
- AWS Signature Version 4 verification (/root/reference/src/rgw/
  rgw_auth_s3.h, rgw_auth_s3.cc): canonical request reconstruction,
  signing-key derivation, constant-time comparison; supports signed
  and UNSIGNED-PAYLOAD content hashes.
- REST op dispatch (/root/reference/src/rgw/rgw_rest_s3.cc): bucket
  create/list/delete, object PUT/GET/HEAD/DELETE, multipart initiate/
  upload-part/complete/abort, ListObjects(V1-shaped) — enough surface
  that a stock S3 client works against it.

Users are (access_key -> secret_key) pairs handed to the frontend
(config-level user admin; the reference's user metadata subsystem is a
separate milestone).  ETags are S3-true MD5s (gateway.py).
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import logging
import os

from ceph_tpu.common import flags
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from ceph_tpu.rgw.gateway import CANNED_ACLS, RGWError, RGWLite

log = logging.getLogger("rgw.http")

UNSIGNED = "UNSIGNED-PAYLOAD"
MAX_BODY = 5 << 30
# anonymous (ACL-gated) requests may carry a body — public-read-write
# buckets accept unauthenticated PUTs — but the pre-auth buffering
# screen still applies: cap what an unauthenticated peer can make the
# gateway hold in memory before the ACL check rejects it
ANON_MAX_BODY = 16 << 20

_ERR_STATUS = {
    "NoSuchBucket": 404, "NoSuchKey": 404, "NoSuchUpload": 404,
    "BucketAlreadyExists": 409, "BucketNotEmpty": 409,
    "InvalidPart": 400, "InvalidPartOrder": 400,
    "InvalidRequest": 400, "InvalidArgument": 400,
    "MalformedXML": 400, "NoSuchVersion": 404,
    "MethodNotAllowed": 405, "AccessDenied": 403,
    "RequestTimeTooSkewed": 403,
    "SignatureDoesNotMatch": 403, "InternalError": 500,
    "InvalidRange": 416,
}

# parse_byte_range sentinel: the range was syntactically valid but
# lies entirely past the object end (HTTP 416)
RANGE_UNSATISFIABLE = object()


def parse_byte_range(spec: str, size: int):
    """`Range: bytes=a-b` for object GETs (RGWGetObj::parse_range
    role, rgw_op.cc:99).

    Returns (first, last) inclusive byte offsets clamped to the
    object, None when the header should be IGNORED (S3 serves 200 for
    malformed or multi-range specs), or RANGE_UNSATISFIABLE for a
    well-formed range with no overlap (416).  Suffix form `bytes=-n`
    means the final n bytes; `bytes=-0` and a start past EOF are
    unsatisfiable."""
    if not spec or not spec.strip().lower().startswith("bytes="):
        return None
    body = spec.strip()[len("bytes="):]
    if "," in body:          # multi-range: S3 ignores and serves 200
        return None
    first_s, dash, last_s = body.strip().partition("-")
    if not dash:
        return None
    first_s, last_s = first_s.strip(), last_s.strip()
    # digits only: int() would admit signed/spaced forms ("--5",
    # "+3") that are malformed per the grammar and must be IGNORED
    if first_s and not first_s.isdigit():
        return None
    if last_s and not last_s.isdigit():
        return None
    if not first_s:          # suffix: last n bytes
        if not last_s:
            return None      # bare "bytes=-"
        n = int(last_s)
        if n <= 0:
            return RANGE_UNSATISFIABLE
        return (max(size - n, 0), size - 1) if size else \
            RANGE_UNSATISFIABLE
    first = int(first_s)
    last = int(last_s) if last_s else size - 1
    if last_s and last < first:
        return None
    if first >= size:
        return RANGE_UNSATISFIABLE
    return first, min(last, size - 1)


def _int_or_400(text, what: str) -> int:
    """Malformed numeric client input is a 400, not a stack trace."""
    try:
        return int(text)
    except (TypeError, ValueError):
        raise _HttpError("InvalidArgument", f"bad {what}: {text!r}")


class _HttpError(Exception):
    def __init__(self, code: str, what: str = "",
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(what or code)
        self.code = code
        self.headers = dict(headers or {})


def _canonical_query(pairs) -> str:
    """The sigv4 canonical query string (RFC3986-quoted, sorted by
    encoded NAME then encoded VALUE — sorting the joined "k=v"
    strings would mis-order names that prefix each other, e.g.
    key2 before key=) — ONE implementation shared by both verifiers
    and both signers, so a canonicalization fix can never diverge
    them."""
    quoted = sorted(
        (urllib.parse.quote(k, safe="-_.~"),
         urllib.parse.quote(v, safe="-_.~"))
        for k, v in pairs)
    return "&".join(f"{k}={v}" for k, v in quoted)


def _sig_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = hmac.new(("AWS4" + secret).encode(), date.encode(),
                 hashlib.sha256).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


class S3Frontend:
    """One HTTP endpoint over an RGWLite gateway."""

    def __init__(self, rgw: RGWLite, users: Dict[str, str],
                 anonymous_ok: bool = True):
        self.rgw = rgw
        self.users = dict(users)  # access_key -> secret_key
        # durable-table keys cached in self.users, with expiry
        # (monotonic); static bootstrap keys are not tracked here
        self._durable_keys: Dict[str, float] = {}
        self._neg_keys: Dict[str, float] = {}  # confirmed-unknown
        # anonymous_ok: admit unauthenticated requests as identity
        # None so canned-ACL checks adjudicate them (public-read
        # buckets); False restores require-sigv4-always
        self.anonymous_ok = anonymous_ok
        self._server: Optional[asyncio.base_events.Server] = None
        self.addr = ""
        # ingress tracing: every request opens a root span installed
        # as the task's current span, so the gateway's rados submits
        # (and through them the OSD op + sub-op spans) parent into ONE
        # tree spanning s3 -> rados -> osd -> device dispatch.  The
        # gateway's head-sampling knob (CEPH_TPU_RGW_TRACE_SAMPLE,
        # default keep-everything) is what gates S3-origin retention:
        # a SAMPLED ingress root forces the whole downstream tree
        # sampled (wire contexts inherit the sender's decision), so an
        # operator turning bulk retention off must turn it off HERE —
        # an unsampled ingress leaves the OSDs to their own
        # osd_trace_sample_rate
        from ceph_tpu.common import tracing

        try:
            rate = flags.flag_float(
                "CEPH_TPU_RGW_TRACE_SAMPLE")
        except ValueError:
            rate = 1.0
        # the gateway has no admin socket: `frontend.tracer.dump()` is
        # the embedded dump surface, so the retention ring stays small
        # — sampled trees are kept for the last-N-requests view only
        self.tracer = tracing.Tracer("rgw", sample_rate=rate,
                                     max_spans=256)

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    gc_interval: float = 30.0) -> str:
        self._server = await asyncio.start_server(
            self._serve, host, port, limit=8 << 20)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"{host}:{port}"
        # a serving gateway owns the GC sweep (rgw_gc worker role):
        # without it, overwrite/delete churn accumulates stripes forever
        if gc_interval > 0:
            self.rgw.start_gc(gc_interval)
        return self.addr

    async def stop(self) -> None:
        await self.rgw.stop_gc()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
            self._server = None

    # -- HTTP plumbing -----------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _ver = \
                        line.decode("latin-1").strip().split(" ", 2)
                except ValueError:
                    return
                headers: Dict[str, str] = {}
                while True:
                    hline = await reader.readline()
                    if hline in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = hline.decode("latin-1").partition(":")
                    headers[name.strip().lower()] = value.strip()
                try:
                    length = int(headers.get("content-length", "0"))
                except ValueError:
                    return  # malformed framing: drop the connection
                if length > MAX_BODY or length < 0:
                    return
                if length and not self._plausible_auth(headers):
                    # a durable-table user may not be cached yet:
                    # hydrate before judging (one omap read, only on
                    # the unknown-key path)
                    _p, _, _q = target.partition("?")
                    await self._ensure_user(headers, _q)
                if length and not self._plausible_auth(headers) \
                        and not self._plausible_presigned(target):
                    # screen BEFORE buffering: an unauthenticated peer
                    # must not make the gateway hold a multi-GiB body
                    # in memory just to 403 it.  A request with NO auth
                    # at all may still be a legitimate anonymous write
                    # to a public-read-write bucket — allowed through
                    # under the smaller anonymous cap
                    if "authorization" in headers or \
                            length > ANON_MAX_BODY:
                        return
                body = await reader.readexactly(length) if length else b""
                keep = headers.get("connection", "").lower() != "close"
                async with self.tracer.span(
                        f"s3.{method.upper()}"
                        f" {target.partition('?')[0]}") as ingress:
                    status, rhdrs, rbody = await self._handle(
                        method.upper(), target, headers, body)
                    ingress.set_attr("status", status)
                reason = {200: "OK", 204: "No Content",
                          206: "Partial Content", 400: "Bad Request",
                          403: "Forbidden", 404: "Not Found",
                          409: "Conflict",
                          416: "Range Not Satisfiable",
                          500: "Internal Server Error",
                          501: "Not Implemented"}.get(status, "OK")
                out = [f"HTTP/1.1 {status} {reason}\r\n".encode()]
                rhdrs.setdefault("Content-Length", str(len(rbody)))
                rhdrs.setdefault("Connection",
                                 "keep-alive" if keep else "close")
                for k, v in rhdrs.items():
                    out.append(f"{k}: {v}\r\n".encode())
                out.append(b"\r\n")
                writer.write(b"".join(out))
                if method.upper() != "HEAD" and rbody:
                    writer.write(rbody)
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _plausible_auth(self, headers: Dict[str, str]) -> bool:
        """Cheap pre-body screen: sigv4-shaped Authorization with a
        KNOWN access key (full verification still runs on the body).
        One credential parser (_claimed_access) serves the screen and
        both verifiers."""
        if not headers.get("authorization", "").startswith(
                "AWS4-HMAC-SHA256 "):
            return False
        return self._claimed_access(headers, "") in self.users

    @staticmethod
    def _claimed_access(headers: Dict[str, str],
                        query: str) -> Optional[str]:
        """The access key a request CLAIMS (header or query auth) —
        unverified; used only to hydrate the key cache."""
        authz = headers.get("authorization", "")
        if authz.startswith("AWS4-HMAC-SHA256 "):
            for part in authz[len("AWS4-HMAC-SHA256 "):].split(","):
                k, _, v = part.strip().partition("=")
                if k == "Credential":
                    return v.split("/", 1)[0]
        for k, v in urllib.parse.parse_qsl(query):
            if k == "X-Amz-Credential":
                return v.split("/", 1)[0]
        return None

    USER_CACHE_TTL = 5.0
    USER_NEG_TTL = 2.0

    async def _ensure_user(self, headers: Dict[str, str],
                           query: str) -> None:
        """Hydrate self.users from the DURABLE user table (the
        radosgw-admin-created users) before the sync verifiers run.
        The static dict stays the bootstrap (never expires, takes
        precedence over a same-named durable key); durable keys carry
        a short TTL so suspension/removal take effect within seconds.
        Misses are negative-cached briefly — random-credential spam
        must not buy a meta-pool read per request — short enough that
        a just-created user works almost immediately.  A transient
        cluster error keeps whatever is cached (never evicts)."""
        import time as _time

        access = self._claimed_access(headers, query)
        if not access:
            return
        now = _time.monotonic()
        expiry = self._durable_keys.get(access)
        if access in self.users and expiry is None:
            return  # static bootstrap key
        if expiry is not None and now < expiry:
            return
        if now < self._neg_keys.get(access, 0):
            return  # recently confirmed unknown
        try:
            secret = await self.rgw.user_key_lookup(access)
        except Exception:
            return  # cluster hiccup: keep the cached state as-is
        if secret is not None:
            self.users[access] = secret
            self._durable_keys[access] = now + self.USER_CACHE_TTL
            self._neg_keys.pop(access, None)
        else:
            if expiry is not None:
                # durable key revoked/suspended since last refresh
                self.users.pop(access, None)
                self._durable_keys.pop(access, None)
            if len(self._neg_keys) > 4096:
                self._neg_keys.clear()  # bounded
            self._neg_keys[access] = now + self.USER_NEG_TTL

    def _plausible_presigned(self, target: str) -> bool:
        """Same screen for query-string auth: a presigned-shaped URL
        naming a KNOWN access key may carry a large body (the PUT
        case); full verification still runs afterwards."""
        _path, _, query = target.partition("?")
        if "X-Amz-Signature=" not in query:
            return False
        params = dict(urllib.parse.parse_qsl(query))
        cred = params.get("X-Amz-Credential", "")
        return cred.split("/", 1)[0] in self.users

    # -- sigv4 -------------------------------------------------------------

    def _verify_sigv4(self, method: str, path: str, query: str,
                      headers: Dict[str, str], body: bytes) -> str:
        """Returns the authenticated access key; raises on failure.
        (rgw_auth_s3's AWSv4ComplMulti/canonicalization role.)"""
        authz = headers.get("authorization", "")
        if not authz.startswith("AWS4-HMAC-SHA256 "):
            raise _HttpError("AccessDenied", "missing sigv4 auth")
        fields = {}
        for part in authz[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        cred = fields.get("Credential", "").split("/")
        if len(cred) != 5:
            raise _HttpError("AccessDenied", "bad credential scope")
        access, date, region, service, _term = cred
        secret = self.users.get(access)
        if secret is None:
            raise _HttpError("AccessDenied", "unknown access key")
        signed_headers = fields.get("SignedHeaders", "")
        payload_hash = headers.get("x-amz-content-sha256")
        if payload_hash is None:
            # clients (curl --aws-sigv4) may sign the payload hash
            # without sending the header: canonicalize with the actual
            # body hash, which is then integrity-checked by the
            # signature itself
            payload_hash = hashlib.sha256(body).hexdigest()
        elif payload_hash != UNSIGNED and \
                payload_hash != hashlib.sha256(body).hexdigest():
            raise _HttpError("SignatureDoesNotMatch",
                             "payload hash mismatch")
        # canonical request — spec form first; legacy curl (<8.3,
        # --aws-sigv4) signs the RAW query string verbatim (no sort,
        # no k= for bare keys), so a second pass accepts that form:
        # same HMAC strength, alternative canonicalization
        cq_spec = _canonical_query(urllib.parse.parse_qsl(
            query, keep_blank_values=True))
        ch = "".join(f"{h}:{' '.join(headers.get(h, '').split())}\n"
                     for h in signed_headers.split(";"))
        scope = f"{date}/{region}/{service}/aws4_request"
        amz_date = headers.get("x-amz-date", "")
        got_sig = fields.get("Signature", "")

        def matches(cq: str) -> bool:
            creq = "\n".join([method, path, cq, ch, signed_headers,
                              payload_hash])
            to_sign = "\n".join([
                "AWS4-HMAC-SHA256", amz_date, scope,
                hashlib.sha256(creq.encode()).hexdigest()])
            want = hmac.new(_sig_key(secret, date, region, service),
                            to_sign.encode(),
                            hashlib.sha256).hexdigest()
            return hmac.compare_digest(want, got_sig)

        if not matches(cq_spec) and \
                not (query != cq_spec and matches(query)):
            raise _HttpError("SignatureDoesNotMatch", "bad signature")
        # clock-skew window (S3's RequestTimeTooSkewed, ~15 min): a
        # captured signed request must not replay indefinitely
        try:
            then = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc)
        except ValueError:
            raise _HttpError("AccessDenied", "bad x-amz-date")
        now = datetime.datetime.now(datetime.timezone.utc)
        if abs((now - then).total_seconds()) > 900:
            raise _HttpError("RequestTimeTooSkewed", amz_date)
        return access

    def _verify_presigned(self, method: str, path: str, query: str,
                          headers: Dict[str, str]) -> str:
        """Query-string sigv4 (presigned URLs — the
        AWSv4ComplSingle/query-auth role): the signature covers every
        X-Amz-* query param except the signature itself, with an
        UNSIGNED-PAYLOAD body hash; validity is bounded by
        X-Amz-Date + X-Amz-Expires rather than the skew window."""
        pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
        params = dict(pairs)  # X-Amz fields occur once per spec
        if params.get("X-Amz-Algorithm") != "AWS4-HMAC-SHA256":
            raise _HttpError("AccessDenied", "bad presign algorithm")
        cred = params.get("X-Amz-Credential", "").split("/")
        if len(cred) != 5:
            raise _HttpError("AccessDenied", "bad credential scope")
        access, date, region, service, _term = cred
        secret = self.users.get(access)
        if secret is None:
            raise _HttpError("AccessDenied", "unknown access key")
        amz_date = params.get("X-Amz-Date", "")
        try:
            then = datetime.datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc)
            expires = int(params.get("X-Amz-Expires", "0"))
        except ValueError:
            raise _HttpError("AccessDenied", "bad presign date")
        if not 0 < expires <= 604800:
            # S3's AuthorizationQueryParametersError: a leaked URL
            # must not be a permanent credential (7-day cap)
            raise _HttpError("AccessDenied",
                             "X-Amz-Expires out of range")
        now = datetime.datetime.now(datetime.timezone.utc)
        age = (now - then).total_seconds()
        if age > expires:
            raise _HttpError("AccessDenied", "Request has expired")
        if age < -900:  # not valid before its own date (minus skew)
            raise _HttpError("AccessDenied", "not yet valid")
        signed_headers = params.get("X-Amz-SignedHeaders", "host")
        # canonicalize from the PAIR list: duplicate parameter names
        # are legal and signed individually
        cq = _canonical_query(
            (k, v) for k, v in pairs if k != "X-Amz-Signature")
        ch = "".join(f"{h}:{' '.join(headers.get(h, '').split())}\n"
                     for h in signed_headers.split(";"))
        creq = "\n".join([method, path, cq, ch, signed_headers,
                          "UNSIGNED-PAYLOAD"])
        scope = f"{date}/{region}/{service}/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(creq.encode()).hexdigest()])
        want = hmac.new(_sig_key(secret, date, region, service),
                        to_sign.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want,
                                   params.get("X-Amz-Signature", "")):
            raise _HttpError("SignatureDoesNotMatch",
                             "bad presigned signature")
        return access

    # -- dispatch ----------------------------------------------------------

    async def _handle(self, method: str, target: str,
                      headers: Dict[str, str], body: bytes
                      ) -> Tuple[int, Dict[str, str], bytes]:
        path, _, query = target.partition("?")
        try:
            await self._ensure_user(headers, query)
            if not headers.get("authorization") and any(
                    k == "X-Amz-Signature"
                    for k, _v in urllib.parse.parse_qsl(
                        query, keep_blank_values=True)):
                # a REAL X-Amz-Signature parameter — not a substring
                # inside some value — selects query auth; anything
                # else stays on the anonymous path
                access = self._verify_presigned(method, path, query,
                                                headers)
            elif headers.get("authorization") or \
                    not self.anonymous_ok:
                access = self._verify_sigv4(method, path, query,
                                            headers, body)
            else:
                # anonymous request: identity None, every op gated by
                # the canned-ACL checks below (RGWHandler_REST's
                # anonymous auth applier role)
                access = None
            # the authenticated identity IS the QoS tenant: every
            # rados op this request fans into carries it (MOSDOp v4),
            # so the OSDs' per-tenant mClock classes and admission
            # gate see s3 traffic per access key, not as one blob
            from ceph_tpu.rados.client import CURRENT_TENANT

            CURRENT_TENANT.set(f"s3:{access}" if access else "s3:anon")
            q = dict(urllib.parse.parse_qsl(query,
                                            keep_blank_values=True))
            parts = urllib.parse.unquote(path).lstrip("/").split("/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            if not bucket:
                if method == "GET":
                    if access is None:
                        raise _HttpError("AccessDenied",
                                         "anonymous service listing")
                    return await self._list_buckets()
                raise _HttpError("InvalidRequest", "no bucket")
            if not key:
                return await self._bucket_op(method, bucket, q, body,
                                             headers, access)
            return await self._object_op(method, bucket, key, q,
                                         headers, body, access)
        except _HttpError as e:
            status, hdrs, body = self._error(e.code, str(e))
            hdrs.update(e.headers)
            return status, hdrs, body
        except RGWError as e:
            return self._error(e.code, str(e))
        except Exception:
            log.exception("s3: %s %s failed", method, target)
            return self._error("InternalError", "")

    def _error(self, code: str,
               what: str) -> Tuple[int, Dict[str, str], bytes]:
        root = ET.Element("Error")
        ET.SubElement(root, "Code").text = code
        ET.SubElement(root, "Message").text = what
        return (_ERR_STATUS.get(code, 400),
                {"Content-Type": "application/xml"},
                ET.tostring(root, xml_declaration=True))

    def _xml(self, root) -> Tuple[int, Dict[str, str], bytes]:
        return 200, {"Content-Type": "application/xml"}, \
            ET.tostring(root, xml_declaration=True)

    async def _list_buckets(self):
        names = await self.rgw.list_buckets()
        root = ET.Element("ListAllMyBucketsResult")
        buckets = ET.SubElement(root, "Buckets")
        for name in names:
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = name
        return self._xml(root)

    # -- canned-ACL adjudication (rgw_acl.cc verify_permission role) -------

    @staticmethod
    def _is_owner(access: Optional[str], owner: str) -> bool:
        # pre-ACL buckets recorded no owner; they stay what they were
        # before ACLs existed here — open to every AUTHENTICATED user
        return access is not None and (not owner or access == owner)

    @classmethod
    def _may_read(cls, access: Optional[str], owner: str,
                  acl: str) -> bool:
        if cls._is_owner(access, owner):
            return True
        if acl in ("public-read", "public-read-write"):
            return True
        return acl == "authenticated-read" and access is not None

    @classmethod
    def _may_write(cls, access: Optional[str], owner: str,
                   acl: str) -> bool:
        if cls._is_owner(access, owner):
            return True
        return acl == "public-read-write"

    def _require(self, ok: bool, what: str) -> None:
        if not ok:
            raise _HttpError("AccessDenied", what)

    def _canned_from_headers(self, headers: Dict[str, str]
                             ) -> Optional[str]:
        acl = headers.get("x-amz-acl")
        if acl is not None and acl not in CANNED_ACLS:
            raise _HttpError("InvalidArgument", f"bad x-amz-acl {acl!r}")
        return acl

    def _acl_policy_xml(self, owner: str, acl: str):
        """AccessControlPolicy rendering of a canned ACL (the
        RGWAccessControlPolicy_S3 to_xml role)."""
        root = ET.Element("AccessControlPolicy")
        root.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
        o = ET.SubElement(root, "Owner")
        ET.SubElement(o, "ID").text = owner
        grants = ET.SubElement(root, "AccessControlList")

        def grant(grantee: str, perm: str):
            g = ET.SubElement(grants, "Grant")
            ge = ET.SubElement(g, "Grantee")
            if grantee == "owner":
                ge.set("xsi:type", "CanonicalUser")
                ET.SubElement(ge, "ID").text = owner
            else:
                ge.set("xsi:type", "Group")
                ET.SubElement(ge, "URI").text = (
                    "http://acs.amazonaws.com/groups/global/" + grantee)
            ET.SubElement(g, "Permission").text = perm

        grant("owner", "FULL_CONTROL")
        if acl in ("public-read", "public-read-write"):
            grant("AllUsers", "READ")
        if acl == "public-read-write":
            grant("AllUsers", "WRITE")
        if acl == "authenticated-read":
            grant("AuthenticatedUsers", "READ")
        return self._xml(root)

    async def _bucket_op(self, method: str, bucket: str, q: Dict,
                         body: bytes = b"",
                         headers: Optional[Dict] = None,
                         access: Optional[str] = None):
        headers = headers or {}
        if method == "PUT" and "acl" in q:
            info = await self.rgw.get_bucket_acl_info(bucket)
            self._require(self._is_owner(access, info["owner"]),
                          "bucket acl is owner-only")
            acl = self._canned_from_headers(headers)
            if acl is None:
                raise _HttpError("InvalidArgument",
                                 "x-amz-acl required (canned ACLs)")
            await self.rgw.put_bucket_acl(bucket, acl)
            return 200, {}, b""
        if method == "GET" and "acl" in q:
            info = await self.rgw.get_bucket_acl_info(bucket)
            self._require(self._is_owner(access, info["owner"]),
                          "bucket acl is owner-only")
            return self._acl_policy_xml(info["owner"], info["acl"])
        if method == "PUT" and not ("versioning" in q
                                    or "lifecycle" in q):
            # bucket creation: authenticated only, creator = owner
            self._require(access is not None, "anonymous create")
            await self.rgw.create_bucket(
                bucket, owner=access,
                acl=self._canned_from_headers(headers) or "private")
            return 200, {}, b""
        info = await self.rgw.get_bucket_acl_info(bucket)
        owner, bacl = info["owner"], info["acl"]
        if method in ("GET", "HEAD"):
            # listings (plain, V2, ?versions, ?versioning, ?lifecycle)
            # are bucket READs; config subresources stay owner-only
            if "versioning" in q or "lifecycle" in q:
                self._require(self._is_owner(access, owner),
                              "bucket config is owner-only")
            else:
                self._require(self._may_read(access, owner, bacl),
                              "bucket listing denied by acl")
        elif method in ("PUT", "DELETE"):
            self._require(self._is_owner(access, owner),
                          "bucket mutation is owner-only")
        return await self._bucket_op_authed(method, bucket, q, body)

    async def _bucket_op_authed(self, method: str, bucket: str,
                                q: Dict, body: bytes = b""):
        if method == "PUT" and "versioning" in q:
            try:
                root = ET.fromstring(body)
            except ET.ParseError:
                raise _HttpError("MalformedXML", "bad versioning xml")
            st_el = next((c for c in root
                          if c.tag.endswith("Status")), None)
            if st_el is None:
                # legal S3: a VersioningConfiguration with no Status
                # means "no change" — never silently suspend
                return 200, {}, b""
            if st_el.text == "Enabled":
                status = "enabled"
            elif st_el.text == "Suspended":
                status = "suspended"
            else:
                raise _HttpError("MalformedXML",
                                 f"bad Status {st_el.text!r}")
            await self.rgw.put_bucket_versioning(bucket, status)
            return 200, {}, b""
        if method == "GET" and "versioning" in q:
            status = await self.rgw.get_bucket_versioning(bucket)
            root = ET.Element("VersioningConfiguration")
            if status != "off":
                ET.SubElement(root, "Status").text = \
                    "Enabled" if status == "enabled" else "Suspended"
            return self._xml(root)
        if method == "PUT" and "lifecycle" in q:
            await self.rgw.put_bucket_lifecycle(
                bucket, self._parse_lifecycle(body))
            return 200, {}, b""
        if method == "GET" and "lifecycle" in q:
            rules = await self.rgw.get_bucket_lifecycle(bucket)
            root = ET.Element("LifecycleConfiguration")
            for r in rules:
                rule = ET.SubElement(root, "Rule")
                ET.SubElement(rule, "ID").text = r.get("id", "")
                ET.SubElement(rule, "Prefix").text = \
                    r.get("prefix", "")
                ET.SubElement(rule, "Status").text = \
                    r.get("status", "Enabled")
                if "expiration_days" in r:
                    e = ET.SubElement(rule, "Expiration")
                    ET.SubElement(e, "Days").text = \
                        str(r["expiration_days"])
                if "noncurrent_days" in r:
                    e = ET.SubElement(rule,
                                      "NoncurrentVersionExpiration")
                    ET.SubElement(e, "NoncurrentDays").text = \
                        str(r["noncurrent_days"])
                if "abort_multipart_days" in r:
                    e = ET.SubElement(rule,
                                      "AbortIncompleteMultipartUpload")
                    ET.SubElement(e, "DaysAfterInitiation").text = \
                        str(r["abort_multipart_days"])
            return self._xml(root)
        if method == "GET" and "versions" in q:
            entries = await self.rgw.list_object_versions(
                bucket, prefix=q.get("prefix", ""))
            root = ET.Element("ListVersionsResult")
            ET.SubElement(root, "Name").text = bucket
            for e in entries:
                tag = "DeleteMarker" if e["delete_marker"] \
                    else "Version"
                v = ET.SubElement(root, tag)
                ET.SubElement(v, "Key").text = e["key"]
                ET.SubElement(v, "VersionId").text = e["version_id"]
                if not e["delete_marker"]:
                    ET.SubElement(v, "Size").text = str(e["size"])
                    ET.SubElement(v, "ETag").text = \
                        f"\"{e['etag']}\""
            return self._xml(root)
        if method == "DELETE":
            await self.rgw.delete_bucket(bucket)
            return 204, {}, b""
        if method in ("GET", "HEAD") and q.get("list-type") == "2":
            try:
                max_keys = int(q.get("max-keys", "1000"))
            except ValueError:
                raise _HttpError("InvalidArgument", "bad max-keys")
            res = await self.rgw.list_objects_v2(
                bucket, prefix=q.get("prefix", ""),
                delimiter=q.get("delimiter", ""),
                continuation_token=q.get("continuation-token", ""),
                max_keys=max_keys)
            root = ET.Element("ListBucketResult")
            ET.SubElement(root, "Name").text = bucket
            ET.SubElement(root, "KeyCount").text = \
                str(len(res["contents"]) + len(res["common_prefixes"]))
            ET.SubElement(root, "IsTruncated").text = \
                "true" if res["is_truncated"] else "false"
            if res["next_token"]:
                ET.SubElement(root, "NextContinuationToken").text = \
                    res["next_token"]
            for e in res["contents"]:
                c = ET.SubElement(root, "Contents")
                ET.SubElement(c, "Key").text = e["key"]
                ET.SubElement(c, "Size").text = str(e.get("size", 0))
                ET.SubElement(c, "ETag").text = \
                    f"\"{e.get('etag', '')}\""
            for p in res["common_prefixes"]:
                cp = ET.SubElement(root, "CommonPrefixes")
                ET.SubElement(cp, "Prefix").text = p
            return self._xml(root)
        if method in ("GET", "HEAD"):
            entries = await self.rgw.list_objects(
                bucket, prefix=q.get("prefix", ""))
            root = ET.Element("ListBucketResult")
            ET.SubElement(root, "Name").text = bucket
            ET.SubElement(root, "IsTruncated").text = "false"
            for e in entries:
                c = ET.SubElement(root, "Contents")
                ET.SubElement(c, "Key").text = e["key"]
                ET.SubElement(c, "Size").text = str(e.get("size", 0))
                ET.SubElement(c, "ETag").text = \
                    f"\"{e.get('etag', '')}\""
            return self._xml(root)
        raise _HttpError("InvalidRequest", method)

    @staticmethod
    def _parse_lifecycle(body: bytes):
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise _HttpError("InvalidRequest", "bad lifecycle xml")
        rules = []
        for rel in root:
            if not rel.tag.endswith("Rule"):
                continue
            rule = {}
            for child in rel:
                tag = child.tag.rsplit("}", 1)[-1]
                if tag == "ID":
                    rule["id"] = child.text or ""
                elif tag == "Prefix":
                    rule["prefix"] = child.text or ""
                elif tag == "Status":
                    rule["status"] = child.text or "Enabled"
                elif tag == "Expiration":
                    for d in child:
                        if d.tag.endswith("Days"):
                            rule["expiration_days"] = \
                                _int_or_400(d.text, "Days")
                elif tag == "NoncurrentVersionExpiration":
                    for d in child:
                        if d.tag.endswith("NoncurrentDays"):
                            rule["noncurrent_days"] = \
                                _int_or_400(d.text, "NoncurrentDays")
                elif tag == "AbortIncompleteMultipartUpload":
                    for d in child:
                        if d.tag.endswith("DaysAfterInitiation"):
                            rule["abort_multipart_days"] = \
                                _int_or_400(d.text,
                                            "DaysAfterInitiation")
            rules.append(rule)
        return rules

    @staticmethod
    def _range_response(etag: str, part: bytes, first: int,
                        size: int) -> Tuple[int, Dict[str, str], bytes]:
        """206 Partial Content with Content-Range — one construction
        for the unversioned pushdown and the versioned slice."""
        last = first + len(part) - 1
        return 206, {
            "ETag": f"\"{etag}\"",
            "Content-Type": "application/octet-stream",
            "Content-Length": str(len(part)),
            "Content-Range": f"bytes {first}-{last}/{size}",
            "Accept-Ranges": "bytes"}, part

    async def _object_op(self, method: str, bucket: str, key: str,
                         q: Dict, headers: Dict, body: bytes,
                         access: Optional[str] = None):
        rgw = self.rgw
        info = await rgw.get_bucket_acl_info(bucket)
        owner, bacl = info["owner"], info["acl"]
        if "acl" in q and method in ("GET", "PUT"):
            # object ?acl subresource: owner-only (READ_ACP/WRITE_ACP
            # collapse onto ownership under canned policies)
            self._require(self._is_owner(access, owner),
                          "object acl is owner-only")
            if method == "GET":
                oacl = await rgw.get_object_acl(bucket, key)
                return self._acl_policy_xml(owner, oacl)
            acl = self._canned_from_headers(headers)
            if acl is None:
                raise _HttpError("InvalidArgument",
                                 "x-amz-acl required (canned ACLs)")
            await rgw.put_object_acl(bucket, key, acl)
            return 200, {}, b""
        if method in ("GET", "HEAD"):
            # object reads: the OBJECT acl governs, with the bucket
            # acl honored as a floor (a public-read bucket serves its
            # objects; stricter per-object ACLs need per-object grants
            # the canned model doesn't express)
            try:
                oacl = await rgw.get_object_acl(bucket, key)
            except RGWError:
                oacl = "private"  # versioned-only key: bucket governs
            self._require(
                self._may_read(access, owner, oacl)
                or self._may_read(access, owner, bacl),
                "object read denied by acl")
        else:
            # PUT/DELETE/multipart: bucket WRITE permission
            self._require(self._may_write(access, owner, bacl),
                          "object write denied by acl")
        if method == "POST" and "uploads" in q:
            upload_id = await rgw.init_multipart(
                bucket, key, acl=self._canned_from_headers(headers))
            root = ET.Element("InitiateMultipartUploadResult")
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "UploadId").text = upload_id
            return self._xml(root)
        if method == "PUT" and "partNumber" in q and "uploadId" in q:
            try:
                num = int(q["partNumber"])
            except ValueError:
                raise _HttpError("InvalidRequest", "bad partNumber")
            etag = await rgw.upload_part(
                bucket, key, q["uploadId"], num, body)
            return 200, {"ETag": f"\"{etag}\""}, b""
        if method == "POST" and "uploadId" in q:
            parts = self._parse_complete(body)
            etag = await rgw.complete_multipart(
                bucket, key, q["uploadId"], parts)
            root = ET.Element("CompleteMultipartUploadResult")
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "ETag").text = f"\"{etag}\""
            return self._xml(root)
        if method == "DELETE" and "uploadId" in q:
            await rgw.abort_multipart(bucket, key, q["uploadId"])
            return 204, {}, b""
        if method == "PUT":
            etag, vid = await rgw.put_object_ex(
                bucket, key, body,
                acl=self._canned_from_headers(headers))
            hdrs = {"ETag": f"\"{etag}\""}
            if vid is not None:
                hdrs["x-amz-version-id"] = vid
            return 200, hdrs, b""
        if method == "HEAD":
            head = await rgw.head_object(bucket, key)
            return 200, {"ETag": f"\"{head.get('etag', '')}\"",
                         "Content-Type": "application/octet-stream",
                         "Content-Length": str(head.get("size", 0))
                         }, b""
        if method == "GET":
            rng = headers.get("range")
            version = q.get("versionId")
            # syntactic screen against a sentinel size: malformed and
            # multi-range specs fall straight through to a plain 200
            # without paying any extra lookups
            if rng and version is None and \
                    parse_byte_range(rng, 1 << 62) is not None:
                # ranged GET (206/Content-Range; 416 when the range
                # misses the object entirely).  One head load: the
                # gateway resolves the spec against the authoritative
                # manifest size and fetches only the touched stripe
                # sub-ranges — each rides the OSD's ranged EC read
                # path and counts as a tier read.
                resolved: Dict[str, Any] = {}

                def resolve(size: int):
                    span = parse_byte_range(rng, size)
                    if span is RANGE_UNSATISFIABLE:
                        raise _HttpError(
                            "InvalidRange", f"{rng} of {size} bytes",
                            headers={"Content-Range":
                                     f"bytes */{size}"})
                    resolved["size"] = size
                    resolved["span"] = span
                    return span

                part, etag = await rgw.get_object_ex(
                    bucket, key, range_resolver=resolve)
                span, size = resolved["span"], resolved["size"]
                if span is not None and part:
                    return self._range_response(etag, part, span[0],
                                                size)
                # span None cannot happen post-screen; an empty part
                # (pathological manifest) degrades to the plain GET
            data, etag = await rgw.get_object_ex(
                bucket, key, version_id=version)
            if rng and version is not None:
                # versioned ranged GET: versions are immutable, the
                # simple fetch+slice is exact
                span = parse_byte_range(rng, len(data))
                if span is RANGE_UNSATISFIABLE:
                    status, hdrs, xml = self._error(
                        "InvalidRange", f"{rng} of {len(data)} bytes")
                    hdrs["Content-Range"] = f"bytes */{len(data)}"
                    return status, hdrs, xml
                if span is not None:
                    first, last = span
                    return self._range_response(
                        etag, data[first:last + 1], first, len(data))
            return 200, {"ETag": f"\"{etag}\"",
                         "Content-Type": "application/octet-stream",
                         "Content-Length": str(len(data)),
                         "Accept-Ranges": "bytes"}, data
        if method == "DELETE":
            marker = await rgw.delete_object(
                bucket, key, version_id=q.get("versionId"))
            hdrs = {}
            if marker is not None:
                hdrs["x-amz-delete-marker"] = "true"
                hdrs["x-amz-version-id"] = marker
            return 204, hdrs, b""
        raise _HttpError("InvalidRequest", method)

    @staticmethod
    def _parse_complete(body: bytes) -> List[Tuple[int, str]]:
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise _HttpError("InvalidRequest", "bad completion xml")
        out = []
        for part in root:
            if not part.tag.endswith("Part"):
                continue
            num = etag = None
            for child in part:
                if child.tag.endswith("PartNumber"):
                    try:
                        num = int(child.text)
                    except (TypeError, ValueError):
                        raise _HttpError("InvalidRequest",
                                         "bad PartNumber")
                elif child.tag.endswith("ETag"):
                    etag = (child.text or "").strip().strip('"')
            if num is not None and etag is not None:
                out.append((num, etag))
        return sorted(out)


# -- a spec-complete sigv4 signer (client side) ------------------------------
# Used by the CLI/tests to talk to the frontend the way a stock S3
# client does: the signature math below is implemented from the AWS
# SigV4 spec independently of the server's verifier.


def sign_request(method: str, url_path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes,
                 access: str, secret: str,
                 region: str = "us-east-1") -> Dict[str, str]:
    """Returns headers with Authorization/x-amz-date/content-sha256."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    out = dict(headers)
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    signed = sorted({k.lower() for k in out})
    cq = _canonical_query(query.items())
    lower = {k.lower(): v for k, v in out.items()}
    ch = "".join(f"{h}:{' '.join(lower.get(h, '').split())}\n"
                 for h in signed)
    creq = "\n".join([method, url_path, cq, ch, ";".join(signed),
                      payload_hash])
    scope = f"{date}/{region}/s3/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(_sig_key(secret, date, region, "s3"),
                   to_sign.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out


def presign_url(method: str, host: str, url_path: str,
                access: str, secret: str, expires: int = 3600,
                query: Optional[Dict[str, str]] = None,
                region: str = "us-east-1") -> str:
    """Mint a presigned URL (query-string sigv4, UNSIGNED-PAYLOAD) —
    what `aws s3 presign` / boto3 generate_presigned_url produce; any
    plain HTTP client can then use it with no credentials."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    scope = f"{date}/{region}/s3/aws4_request"
    # canonical-URI rule: path segments percent-encoded, "/" kept —
    # the URL carries the SAME encoded form the signature covers, so
    # keys with spaces/reserved chars verify
    url_path = urllib.parse.quote(url_path, safe="/-_.~")
    params = dict(query or {})
    params.update({
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": f"{access}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    })
    cq = _canonical_query(params.items())
    creq = "\n".join([method, url_path, cq, f"host:{host}\n",
                      "host", "UNSIGNED-PAYLOAD"])
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(_sig_key(secret, date, region, "s3"),
                   to_sign.encode(), hashlib.sha256).hexdigest()
    return (f"http://{host}{url_path}?{cq}"
            f"&X-Amz-Signature={sig}")

"""Leveled, ring-buffered, async logging.

Reference parity: the dout framework
(/root/reference/src/log/Log.cc + src/common/dout.h): per-subsystem
`<stderr level>/<memory level>` pairs (debug_osd = "1/5"), an async writer
thread draining a queue to the log file, and an in-memory ring of the most
recent high-verbosity entries dumped on crash (`log_max_recent`) — the
cheap-always/verbose-on-crash split.
"""

from __future__ import annotations

import collections
import os
import queue
import sys
import threading
import time
import traceback
from typing import Deque, Dict, Optional, TextIO, Tuple

_LEVEL_CACHE: Dict[str, Tuple[int, int]] = {}


def parse_levels(spec: str) -> Tuple[int, int]:
    """"1/5" -> (log_level, gather_level); "3" -> (3, 3)."""
    if spec in _LEVEL_CACHE:
        return _LEVEL_CACHE[spec]
    if "/" in spec:
        log_s, mem_s = spec.split("/", 1)
        out = (int(log_s), int(mem_s))
    else:
        out = (int(spec), int(spec))
    _LEVEL_CACHE[spec] = out
    return out


class Log:
    """Per-process logger: subsystem levels, ring buffer, writer thread."""

    def __init__(self, config=None, name: str = "", max_recent: int = 500):
        self._config = config
        self.name = name
        self._subsys: Dict[str, Tuple[int, int]] = {}
        self._recent: Deque[str] = collections.deque(maxlen=max_recent)
        self._queue: "queue.Queue[Optional[str]]" = queue.Queue()
        self._file: Optional[TextIO] = None
        self._file_path: Optional[str] = None
        self._stderr_level_default = 1
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        if config is not None:
            self.reload_config()
            config.add_observer(lambda keys: self.reload_config(),
                                None)

    # -- config -----------------------------------------------------------

    def reload_config(self) -> None:
        from ceph_tpu.common.options import OPTIONS

        for name in OPTIONS:
            if name.startswith("debug_"):
                self._subsys[name[len("debug_"):]] = parse_levels(
                    str(self._config.get(name)))
        max_recent = int(self._config.get("log_max_recent"))
        if max_recent != self._recent.maxlen:
            self._recent = collections.deque(self._recent, maxlen=max_recent)
        path = self._config.get("log_file")
        if path and path != self._file_path:
            self.set_log_file(path)

    def set_subsys_level(self, subsys: str, spec: str) -> None:
        self._subsys[subsys] = parse_levels(spec)

    def set_log_file(self, path: str) -> None:
        if self._file is not None:
            # drain queued lines into the OLD file before switching, so a
            # runtime log_file change doesn't misroute earlier entries
            self._queue.join()
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(path, "a", buffering=1)
            self._file_path = path
        self._ensure_thread()

    # -- emit -------------------------------------------------------------

    def dout(self, subsys: str, level: int, message: str) -> None:
        log_level, gather_level = self._subsys.get(subsys, (1, 5))
        if level > max(log_level, gather_level):
            return
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime())
        line = (f"{stamp} {os.getpid()} {self.name or '-'}"
                f" {level} {subsys}: {message}")
        if level <= gather_level:
            self._recent.append(line)
        if level <= log_level:
            if self._file is not None:
                self._queue.put(line)
            else:
                print(line, file=sys.stderr)

    def error(self, subsys: str, message: str) -> None:
        self.dout(subsys, -1, message)

    # -- crash dump -------------------------------------------------------

    def dump_recent(self, out: Optional[TextIO] = None) -> None:
        """Flush the in-memory ring (called on crash / assert)."""
        out = out or (self._file if self._file is not None else sys.stderr)
        out.write(f"--- begin dump of recent events ({len(self._recent)})"
                  " ---\n")
        for line in self._recent:
            out.write(line + "\n")
        out.write("--- end dump of recent events ---\n")
        out.flush()

    def install_crash_handler(self) -> None:
        import signal

        def handler(signum, frame):
            self.error("none", f"*** Caught signal {signum} ***")
            self._recent.append("".join(traceback.format_stack(frame)))
            self.dump_recent()
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        for sig in (signal.SIGSEGV, signal.SIGABRT, signal.SIGBUS):
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):  # non-main thread
                pass

    # -- writer thread ----------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer, name="log", daemon=True)
            self._thread.start()

    def _writer(self) -> None:
        while True:
            line = self._queue.get()
            try:
                if line is None:
                    return
                with self._lock:
                    if self._file is not None:
                        self._file.write(line + "\n")
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        # join() returns only after the writer has task_done'd every
        # enqueued line, including one it had already dequeued
        self._queue.join()
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def stop(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join(timeout=2)

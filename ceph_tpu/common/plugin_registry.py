"""Generic named-plugin registry.

Reference seam: ceph::PluginRegistry
(/root/reference/src/common/PluginRegistry.h:44-65) — a per-type map of
named plugins with dynamic loading (`load(type, name)` dlopens
`libceph_<type>_<name>.so` and calls `__ceph_plugin_init`).  The compressor
framework resolves its plugins through it (Compressor.cc:69-102); the
erasure-code framework has its own specialized registry
(ceph_tpu.ec.registry) just like the reference.

Here dynamic loading is `importlib` of `ceph_tpu_<type>_<name>` modules
exposing `__ceph_plugin_init__(registry)`.
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Dict, Optional


class Plugin:
    """Base class for registrable plugins; subclasses add factories."""

    def __init__(self, name: str):
        self.name = name


class PluginRegistry:
    _instance: Optional["PluginRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._plugins: Dict[str, Dict[str, Any]] = {}

    @classmethod
    def instance(cls) -> "PluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, type_: str, name: str, plugin: Any) -> int:
        with self._lock:
            by_name = self._plugins.setdefault(type_, {})
            if name in by_name:
                return -17  # EEXIST
            by_name[name] = plugin
            return 0

    def remove(self, type_: str, name: str) -> int:
        with self._lock:
            by_name = self._plugins.get(type_, {})
            return 0 if by_name.pop(name, None) is not None else -2

    def get(self, type_: str, name: str) -> Optional[Any]:
        with self._lock:
            return self._plugins.get(type_, {}).get(name)

    def get_or_load(self, type_: str, name: str) -> Optional[Any]:
        plugin = self.get(type_, name)
        if plugin is not None:
            return plugin
        module_name = f"ceph_tpu_{type_}_{name}"
        try:
            module = importlib.import_module(module_name)
        except ImportError:
            return None
        init = getattr(module, "__ceph_plugin_init__", None)
        if init is None:
            return None
        init(self)
        return self.get(type_, name)

"""Zero-copy shard buffer views (the bufferlist share-don't-copy role).

Reference parity: ceph::buffer::list (/root/reference/src/include/
buffer.h) lets every layer pass refcounted views of the same pages —
an EC data shard handed to the ObjectStore is a view of the client's
message buffer, never a copy.  Python's memoryview covers the
contiguous case; `StridedBuf` covers the one layout bufferlists get
from a ptr-list that a flat buffer cannot express: an EC DATA shard,
which is every k-th chunk of the logical object (chunk c of shard i
lives at stripe offset i*chunk — ErasureCodeInterface.h:39-78).
Holding the stripes as a strided numpy view of the adopted client
buffer removes the whole-object transpose copy from the write path;
byte materialization happens only where a consumer genuinely needs
contiguous bytes (socket framing, ranged reads).
"""

from __future__ import annotations

import numpy as np


def is_immutable(data) -> bool:
    """True when no OTHER owner can mutate the buffer's bytes.

    The store-adoption guard (os/memstore.py): adopted buffers must
    never change under the recorded crcs.  Walks the base chain:

    - bytes: immutable by construction.
    - memoryview: must be readonly AND backed by an immutable base —
      `memoryview(ba).toreadonly()` is readonly while its owner still
      mutates `ba`, so readonly alone is not proof.
    - ndarray / StridedBuf: every view on the chain must be frozen
      (non-writeable) down to a root that either owns its memory
      (frozen owner — producers in this repo freeze via setflags and
      never thaw; the claim contract covers them) or wraps an
      immutable buffer.
    """
    if isinstance(data, bytes):
        return True
    if isinstance(data, memoryview):
        return data.readonly and is_immutable(data.obj)
    if isinstance(data, StridedBuf):
        return is_immutable(data.view)
    if isinstance(data, np.ndarray):
        if data.flags.writeable:
            return False
        if data.base is None:
            return True  # frozen owner
        return is_immutable(data.base)
    return False


def adopt(data):
    """Immutable form of `data` for long-lived caches and stores:
    pass through when is_immutable() PROVES no other owner can
    mutate the bytes (the common case on the zero-copy read path —
    frozen decode views, bytes), materialize otherwise.  The honest
    centralization of the `bytes(payload)`-at-every-site pattern:
    the copy happens only when adoption genuinely needs one."""
    return data if is_immutable(data) else bytes(data)


def as_buffer(data):
    """Adapt `data` to something the zero-copy byte paths (frombuffer
    / memoryview slicing) accept, copying ONLY when the layout
    genuinely requires it:

    - bytes / bytearray / memoryview pass through untouched;
    - a C-contiguous uint8 ndarray hands out its buffer view;
    - a StridedBuf (strided rows — no flat buffer exists) and every
      other object materialize via bytes() — the one honest copy,
      and StridedBuf caches its flat form so repeats are free.

    This is the centralized materialize-guard the hot-path-copy
    worklist's `bytes(x)`-per-call-site pattern collapsed into."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        return data
    if isinstance(data, np.ndarray) and data.dtype == np.uint8 \
            and data.flags.c_contiguous:
        return data.reshape(-1).data
    return bytes(data)


class StridedBuf:
    """Read-only logical byte string backed by a strided uint8 view.

    view: np.ndarray shaped (rows, row_len) — logical content is the
    C-order concatenation of the rows.  Supports the small surface the
    stores and messengers use: len, slicing (returns bytes), bytes().
    """

    __slots__ = ("view", "_flat")

    def __init__(self, view: np.ndarray):
        assert view.ndim == 2 and view.dtype == np.uint8
        self.view = view
        self._flat = None

    def __len__(self) -> int:
        return int(self.view.size)

    def tobytes(self) -> bytes:
        if self._flat is None:
            self._flat = self.view.tobytes()
        return self._flat

    def __bytes__(self) -> bytes:
        return self.tobytes()

    def __getitem__(self, key) -> bytes:
        if not isinstance(key, slice):
            raise TypeError("StridedBuf supports slice access only")
        start, stop, step = key.indices(len(self))
        if step != 1:
            raise ValueError("StridedBuf slices must be contiguous")
        if self._flat is not None:
            return self._flat[start:stop]
        rows, row_len = self.view.shape
        r0, c0 = divmod(start, row_len)
        r1, c1 = divmod(stop, row_len)
        if r0 == r1:  # within one row: one contiguous copy
            return self.view[r0, c0:c1].tobytes()
        if r1 - r0 <= 2 and c0 == 0 and c1 == 0:
            return self.view[r0:r1].tobytes()
        # spans many rows: materialize once, serve from the flat form
        return self.tobytes()[start:stop]

    def __eq__(self, other) -> bool:
        if isinstance(other, (bytes, bytearray, memoryview)):
            return self.tobytes() == bytes(other)
        if isinstance(other, StridedBuf):
            return self.tobytes() == other.tobytes()
        return NotImplemented

    def __repr__(self) -> str:
        return f"StridedBuf(len={len(self)})"

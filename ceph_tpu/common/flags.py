"""Central kill-switch registry: every ``CEPH_TPU_*`` toggle in one
audited seam.

Every fast path in this tree ships with a kill switch (the
cross-cutting invariant in ROADMAP.md), and by PR 19 those switches
had grown into 50+ scattered ``os.environ`` reads — invisible to
introspection, unfindable for the chaos engine's live-flip hazard,
and with per-site default strings that could silently drift.  This
module is the single registry: each flag is declared ONCE with its
default, its scope (whether a live flip takes effect immediately or
only at the next daemon/module start), and a one-line description;
reads go through :func:`get` / :func:`enabled` / :func:`flag_float` /
:func:`flag_int`, and writes through :func:`set_flag` /
:func:`clear` — which fire live-flip hooks and append to a bounded
audit log the chaos engine echoes into its violation reports.

The backing store stays ``os.environ`` on purpose: flags must inherit
into spawned subprocesses (the meshbench multi-process sweeps, the
OSD fault-injection seams) and must keep working for tests/benches
that set ``os.environ`` directly.  The registry adds the declaration,
the audit, and the hooks — it does not invent a second store that
could disagree with the first.

Lint rule ``unregistered-kill-switch`` (analysis/rules.py) closes the
loop: a raw ``os.environ`` read of a ``CEPH_TPU_*`` literal anywhere
in the package outside this module is a finding, with a ZERO
baseline — new switches must land here first.

Scopes:

``process``
    Read on every use; a live flip applies to the next operation.
``startup``
    Read once at daemon/module initialization; a flip needs a
    restart (the chaos kill-switch hazard must not expect these to
    take effect mid-scenario).
``inject``
    Fault-injection seam, re-read per dispatch — the chaos hazards'
    levers.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "register", "get", "enabled", "flag_float", "flag_int",
    "set_flag", "clear", "setdefault", "on_flip", "flips",
    "clear_flips", "registry", "UnregisteredFlag",
]


class UnregisteredFlag(KeyError):
    """A flag name no `register()` call declared: either a typo (the
    loud failure is the point) or a new switch that must be added to
    the registry table below."""


class _Flag:
    __slots__ = ("name", "default", "scope", "desc")

    def __init__(self, name: str, default: Optional[str],
                 scope: str, desc: str):
        self.name = name
        self.default = default
        self.scope = scope
        self.desc = desc


_REGISTRY: Dict[str, _Flag] = {}
_HOOKS: List[Callable[[str, Optional[str], Optional[str]], None]] = []
_FLIPS: List[Dict[str, Any]] = []
_FLIPS_CAP = 4096
_lock = threading.Lock()
_UNSET = object()


def register(name: str, default: Optional[str] = None,
             scope: str = "process", desc: str = "") -> None:
    """Declare a flag.  Idempotent; re-registration with a DIFFERENT
    default is an error (the per-site default drift this registry
    exists to end)."""
    if scope not in ("process", "startup", "inject"):
        raise ValueError(f"unknown flag scope {scope!r}")
    with _lock:
        cur = _REGISTRY.get(name)
        if cur is not None:
            if cur.default != default:
                raise ValueError(
                    f"{name} re-registered with default {default!r}"
                    f" (was {cur.default!r})")
            return
        _REGISTRY[name] = _Flag(name, default, scope, desc)


def _flag(name: str) -> _Flag:
    f = _REGISTRY.get(name)
    if f is None:
        raise UnregisteredFlag(
            f"{name} is not in the kill-switch registry "
            "(ceph_tpu/common/flags.py): register it there")
    return f


def get(name: str, default: Any = _UNSET) -> Optional[str]:
    """Raw string value: the environment override if present, else
    the call-site `default` if given, else the registered default.
    The read is DYNAMIC (per call) so direct ``os.environ`` writes by
    tests and benches keep working."""
    f = _flag(name)
    d = f.default if default is _UNSET else default
    return os.environ.get(name, d)


def peek(name: str) -> Optional[str]:
    """The save/restore idiom's read: the raw environment OVERRIDE
    (None when unset — callers restoring state need unset-vs-default
    distinguished, which :func:`get`'s default substitution hides)."""
    _flag(name)
    return os.environ.get(name)


def enabled(name: str) -> bool:
    """Boolean view: on unless unset-with-falsy-default, empty, or
    ``"0"`` — the ``!= "0"`` convention every default-on kill switch
    in this tree uses."""
    return get(name) not in (None, "", "0")


def flag_float(name: str, default: Any = _UNSET) -> float:
    v = get(name, default)
    return float(v if v is not None else 0.0)


def flag_int(name: str, default: Any = _UNSET) -> int:
    v = get(name, default)
    # int("3.0") raises; route through float like the _env_float
    # helpers this replaces
    return int(float(v if v is not None else 0))


def _audit(name: str, old: Optional[str],
           new: Optional[str]) -> None:
    _FLIPS.append({"t": time.monotonic(), "flag": name,
                   "old": old, "new": new})
    del _FLIPS[:-_FLIPS_CAP]
    for hook in list(_HOOKS):
        try:
            hook(name, old, new)
        except Exception:
            # a broken observer must not turn a kill-switch flip into
            # an op-path failure
            pass


def set_flag(name: str, value: str) -> None:
    """Flip a flag: write the environment (subprocess inheritance),
    record the flip, fire live-flip hooks."""
    f = _flag(name)
    with _lock:
        old = os.environ.get(name, f.default)
        os.environ[name] = str(value)
        _audit(name, old, str(value))


def clear(name: str) -> None:
    """Reset a flag to its registered default (drop the override)."""
    f = _flag(name)
    with _lock:
        old = os.environ.get(name)
        if old is None:
            return
        os.environ.pop(name, None)
        _audit(name, old, f.default)


def setdefault(name: str, value: str) -> str:
    """Set only if unset (the meshbench smoke-floor pattern); returns
    the effective value.  Counted as a flip only when it writes."""
    _flag(name)
    with _lock:
        cur = os.environ.get(name)
        if cur is not None:
            return cur
        os.environ[name] = str(value)
        _audit(name, None, str(value))
        return str(value)


def on_flip(hook: Callable[[str, Optional[str], Optional[str]],
                           None]) -> None:
    """Observe flips: hook(name, old, new) fires inside set_flag /
    clear / first-write setdefault."""
    _HOOKS.append(hook)


def remove_hook(hook: Callable) -> None:
    try:
        _HOOKS.remove(hook)
    except ValueError:
        pass


def flips() -> List[Dict[str, Any]]:
    """The audit log (bounded): every flip since process start /
    last clear_flips(), oldest first."""
    return list(_FLIPS)


def clear_flips() -> None:
    del _FLIPS[:]


def registry() -> Dict[str, Dict[str, Any]]:
    """Introspection snapshot: every declared flag with its default,
    scope, description, and current effective value."""
    return {
        name: {"default": f.default, "scope": f.scope,
               "desc": f.desc,
               "value": os.environ.get(name, f.default)}
        for name, f in sorted(_REGISTRY.items())}


# ---------------------------------------------------------------------
# The registry table: every CEPH_TPU_* flag in the tree.  Grouped by
# subsystem; scope "startup" marks reads that happen once at
# daemon/module init (a live flip does not reach them).
# ---------------------------------------------------------------------

# -- device / kernel tier ---------------------------------------------
register("CEPH_TPU_PALLAS", "1", "process",
         "Pallas word-kernel tier (GF matmul / CRC); 0 = XLA path")
register("CEPH_TPU_BREAKER", "1", "process",
         "circuit breaker around device dispatch; 0 = raw dispatch")
register("CEPH_TPU_BREAKER_THRESHOLD", "3", "process",
         "consecutive failures before a family breaker opens")
register("CEPH_TPU_BREAKER_BACKOFF_S", "0.5", "process",
         "initial open-state backoff seconds")
register("CEPH_TPU_BREAKER_BACKOFF_MAX_S", "30.0", "process",
         "open-state backoff ceiling seconds")
register("CEPH_TPU_DEVICE_BREAKER_THRESHOLD", "1", "process",
         "per-device/host family breaker trip threshold")
register("CEPH_TPU_DEVICE_TIMEOUT_S", "120.0", "process",
         "device dispatch watchdog seconds")
register("CEPH_TPU_INJECT_DEVICE_FAIL", None, "inject",
         "fault injection spec: p | next=N | hang=MS | oom=K | "
         "sick=ID | down_host=H (chaos device/host hazard lever)")

# -- EC plan / mesh / multihost ---------------------------------------
register("CEPH_TPU_PLAN_CACHE", "1", "startup",
         "ExecPlan compile cache; 0 = direct jit (debug only)")
register("CEPH_TPU_PLAN_QUARANTINE_S", "30.0", "process",
         "failed-plan quarantine seconds")
register("CEPH_TPU_PLAN_FAIL_LIMIT", "3", "process",
         "plan failures before quarantine")
register("CEPH_TPU_MESH", "1", "process",
         "multi-chip mesh dispatch; 0 = single-device plans")
register("CEPH_TPU_MESH_MIN_BYTES", str(1 << 20), "process",
         "payload floor below which mesh dispatch is skipped")
register("CEPH_TPU_MESH_MIN_STRIPES", "2", "process",
         "stripe floor for mesh dispatch")
register("CEPH_TPU_MESH_MAX_DEVICES", "0", "process",
         "mesh device cap; 0 = all healthy devices")
register("CEPH_TPU_MESH_PROBE_TIMEOUT_S", "20.0", "process",
         "sick-device probe timeout seconds")
register("CEPH_TPU_MULTIHOST", "1", "process",
         "cross-host data plane; 0 = single-host meshes only")
register("CEPH_TPU_MULTIHOST_LOCAL_DEVICES", None, "startup",
         "per-process visible-device override for workers")
register("CEPH_TPU_MULTIHOST_COORD", "", "startup",
         "coordinator address for the jax.distributed bootstrap")
register("CEPH_TPU_MULTIHOST_NPROC", "1", "startup",
         "process count for the jax.distributed bootstrap")
register("CEPH_TPU_MULTIHOST_PID", "0", "startup",
         "this process's index in the jax.distributed group")
register("CEPH_TPU_MULTIHOST_HOSTS", "1", "process",
         "emulated host count for the host-topology map")
register("CEPH_TPU_MULTIHOST_AGREE_TIMEOUT_S", "10.0", "process",
         "membership-agreement collective timeout seconds")
register("CEPH_TPU_MULTIHOST_WORKER_DEADLINE_S", None, "startup",
         "meshbench worker hard deadline seconds")
register("CEPH_TPU_MULTIHOST_LEG_TIMEOUT_S", "120", "process",
         "meshbench per-leg driver timeout seconds")
register("CEPH_TPU_BENCH_SMOKE", None, "startup",
         "bench smoke mode: small sizes, fast legs")
register("CEPH_TPU_COLLECTIVE_TRACE", None, "startup",
         "record runtime collective traces for the SPMD cross-check")
register("CEPH_TPU_COLLECTIVE_TRACE_FILE", None, "startup",
         "path sink for recorded collective traces")

# -- codec compiler ----------------------------------------------------
register("CEPH_TPU_XSCHED", "1", "process",
         "XOR schedule compiler; 0 = naive row-walk")
register("CEPH_TPU_NATIVE_XSCHED", "1", "process",
         "native fused-tape executor; 0 = python executor")
register("CEPH_TPU_XSCHED_MAX_OPS", "256", "process",
         "schedule-size cap for the compiler")
register("CEPH_TPU_XSCHED_MIN_REDUCTION", "0.25", "process",
         "minimum XOR reduction to prefer the schedule")
register("CEPH_TPU_XSCHED_HOST_MAX_ONES", "4096", "process",
         "host-executor density ceiling (ones count)")

# -- subsystem kill switches ------------------------------------------
register("CEPH_TPU_COMPUTE", "1", "process",
         "coded-compute pushdown; 0 = read-then-compute")
register("CEPH_TPU_INFERENCE", "1", "process",
         "coded inference serving; 0 = exact full-decode only")
register("CEPH_TPU_MSR_REPAIR", "1", "process",
         "MSR regenerating repair; 0 = classic k-read rebuild")
register("CEPH_TPU_TIER", "1", "process",
         "hot-set read tier; 0 = every read from the store")
register("CEPH_TPU_HEDGE", "1", "process",
         "hedged shard reads; 0 = single-attempt gathers")
register("CEPH_TPU_TRACE", "1", "process",
         "critical-path span layer; 0 = spans off")
register("CEPH_TPU_ENCODE_SERVICE", "1", "startup",
         "micro-batching encode service; 0 = inline encodes")
register("CEPH_TPU_ENCODE_BATCH_WINDOW_MS", "1.0", "startup",
         "encode-service batch window milliseconds")
register("CEPH_TPU_ENCODE_BATCH_BYTES", str(8 << 20), "startup",
         "encode-service batch byte ceiling")
register("CEPH_TPU_GROUP_COMMIT", "1", "startup",
         "group-commit fsync barriers; 0 = one commit per txn")
register("CEPH_TPU_GROUP_COMMIT_WINDOW_MS", "0.5", "startup",
         "group-commit accumulation window (ms)")
register("CEPH_TPU_GROUP_COMMIT_TXNS", "64", "startup",
         "group-commit max transactions per batch")
register("CEPH_TPU_GROUP_COMMIT_BYTES", str(4 << 20), "startup",
         "group-commit max payload bytes per batch")
register("CEPH_TPU_FUSE_MIN_BYTES", None, "process",
         "object-size floor for the fused encode+crc dispatch")

# -- QoS / scheduling --------------------------------------------------
register("CEPH_TPU_QOS", "1", "startup",
         "per-tenant mClock classes + admission gate; 0 = one "
         "shared client class")
register("CEPH_TPU_DMCLOCK", "1", "process",
         "distributed mClock delta/rho piggybacking: MOSDOp carries "
         "per-tenant service deltas so tags are cluster-consistent; "
         "0 = per-OSD tags only")
register("CEPH_TPU_OP_FAST_LANE", "1", "startup",
         "sub-chunk write fast lane; 0 = every op queues")

# -- store / durability ------------------------------------------------
register("CEPH_TPU_CRASH_INJECT", "1", "process",
         "power-cut synthesis in FaultStore kill paths (chaos "
         "power-cut hazard lever)")

# -- tracing / debug / analysis ---------------------------------------
register("CEPH_TPU_DEBUG", None, "startup",
         "daemon debug logging")
register("CEPH_TPU_LOCKDEP", "0", "startup",
         "runtime lock-order detector")
register("CEPH_TPU_INTERLEAVE", "0", "startup",
         "deterministic-interleaving explorer hooks")
register("CEPH_TPU_INTERLEAVE_SEED", "0", "process",
         "interleaving exploration seed")
register("CEPH_TPU_RGW_TRACE_SAMPLE", "1.0", "process",
         "S3 frontend ingress-span sample rate")

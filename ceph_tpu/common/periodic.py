"""Shared start/stop plumbing for background one-tick daemons.

The mirror/sync agents (rbd-mirror, cephfs-mirror, rgw multisite) all
run the same shape: a loop that calls one idempotent tick, logs and
survives tick failures, and sleeps interruptibly until stopped.  One
implementation here so the next backoff or shutdown-ordering fix lands
everywhere at once."""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

log = logging.getLogger("periodic")


class PeriodicDaemon:
    """Mixin: subclasses implement `_tick()` (one idempotent pass) and
    may set `_tick_what` for log lines."""

    _tick_what: str = "tick"
    _task: Optional[asyncio.Task] = None
    _stop_evt: Optional[asyncio.Event] = None

    async def _tick(self) -> None:
        raise NotImplementedError

    async def start(self, interval: float = 1.0) -> None:
        self._stop_evt = asyncio.Event()
        stop = self._stop_evt

        async def loop():
            while not stop.is_set():
                try:
                    await self._tick()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("%s failed; retrying",
                                  self._tick_what)
                try:
                    await asyncio.wait_for(stop.wait(), interval)
                except asyncio.TimeoutError:
                    pass

        self._task = asyncio.get_running_loop().create_task(loop())

    async def stop(self) -> None:
        if self._stop_evt is not None:
            self._stop_evt.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

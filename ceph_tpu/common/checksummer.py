"""Per-block checksums for stored blobs — the Checksummer capability.

Reference seam: class Checksummer (/root/reference/src/common/Checksummer.h)
with types none/xxhash32/xxhash64/crc32c/crc32c_16/crc32c_8 (:16-22), used by
BlueStore to seed blob csums on write (BlueStore.cc:13642-13651) and verify
every read (_verify_csum, BlueStore.cc:9636-9663).

calculate() fills a little-endian value vector, one value per
csum_block_size block; verify() returns the byte offset of the first bad
block or -1.  The batched crc32c path can run on TPU
(ceph_tpu.ops.checksum.crc32c_batch_tpu) when many blocks are checksummed at
once — the BlueStore-blob-sweep shape from BASELINE config #3.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops import checksum as cks
from ceph_tpu.ops import gf

CSUM_NONE = 1
CSUM_XXHASH32 = 2
CSUM_XXHASH64 = 3
CSUM_CRC32C = 4
CSUM_CRC32C_16 = 5
CSUM_CRC32C_8 = 6

_NAMES = {
    CSUM_NONE: "none",
    CSUM_XXHASH32: "xxhash32",
    CSUM_XXHASH64: "xxhash64",
    CSUM_CRC32C: "crc32c",
    CSUM_CRC32C_16: "crc32c_16",
    CSUM_CRC32C_8: "crc32c_8",
}
_TYPES = {v: k for k, v in _NAMES.items()}

_VALUE_SIZE = {
    CSUM_NONE: 0,
    CSUM_XXHASH32: 4,
    CSUM_XXHASH64: 8,
    CSUM_CRC32C: 4,
    CSUM_CRC32C_16: 2,
    CSUM_CRC32C_8: 1,
}

_VALUE_DTYPE = {
    CSUM_XXHASH32: np.dtype("<u4"),
    CSUM_XXHASH64: np.dtype("<u8"),
    CSUM_CRC32C: np.dtype("<u4"),
    CSUM_CRC32C_16: np.dtype("<u2"),
    CSUM_CRC32C_8: np.dtype("<u1"),
}


def get_csum_type_string(t: int) -> str:
    return _NAMES.get(t, "???")


def get_csum_string_type(s: str) -> int:
    if s not in _TYPES:
        raise ValueError(f"unknown csum type {s!r}")
    return _TYPES[s]


def get_csum_value_size(t: int) -> int:
    return _VALUE_SIZE[t]


def _calc_values(csum_type: int, blocks: np.ndarray, block_size: int,
                 init_value: int, use_tpu: bool) -> np.ndarray:
    n = blocks.size // block_size
    if csum_type in (CSUM_CRC32C, CSUM_CRC32C_16, CSUM_CRC32C_8):
        if use_tpu and gf.backend_available() and n >= 8:
            vals = np.asarray(
                cks.crc32c_batch_tpu(blocks.reshape(n, block_size),
                                     init=init_value))
        else:
            vals = cks.crc32c_blocks(blocks, block_size, init=init_value)
        if csum_type == CSUM_CRC32C_16:
            vals = vals & 0xFFFF
        elif csum_type == CSUM_CRC32C_8:
            vals = vals & 0xFF
        return vals
    if csum_type == CSUM_XXHASH32:
        return np.array(
            [cks.xxh32(blocks[i * block_size:(i + 1) * block_size], init_value)
             for i in range(n)], dtype=np.uint64)
    if csum_type == CSUM_XXHASH64:
        return np.array(
            [cks.xxh64(blocks[i * block_size:(i + 1) * block_size], init_value)
             for i in range(n)], dtype=np.uint64)
    raise ValueError(f"bad csum type {csum_type}")


class Checksummer:
    """calculate/verify per-block checksums (Checksummer.h:150-260 shape)."""

    @staticmethod
    def calculate(csum_type: int, csum_block_size: int, offset: int,
                  length: int, data, csum_data: bytearray,
                  init_value: int = 0xFFFFFFFF, use_tpu: bool = True) -> None:
        """Checksum blocks [offset, offset+length) of data into csum_data.

        csum_data is indexed by block number (offset // csum_block_size),
        values little-endian — the on-disk layout BlueStore stores in
        bluestore_blob_t::csum_data.
        """
        if csum_type == CSUM_NONE:
            return
        assert offset % csum_block_size == 0
        assert length % csum_block_size == 0
        arr = np.frombuffer(bytes(data), dtype=np.uint8)
        assert offset + length <= arr.size or offset == 0
        blocks = arr[offset:offset + length]
        vals = _calc_values(csum_type, blocks, csum_block_size, init_value,
                            use_tpu)
        dtype = _VALUE_DTYPE[csum_type]
        vsize = dtype.itemsize
        first = offset // csum_block_size
        need = (first + vals.size) * vsize
        if len(csum_data) < need:
            csum_data.extend(b"\x00" * (need - len(csum_data)))
        csum_data[first * vsize:need] = vals.astype(dtype).tobytes()

    @staticmethod
    def verify(csum_type: int, csum_block_size: int, offset: int, length: int,
               data, csum_data, init_value: int = 0xFFFFFFFF,
               use_tpu: bool = True) -> int:
        """Return byte offset of the first bad block, or -1 if all match."""
        if csum_type == CSUM_NONE:
            return -1
        assert offset % csum_block_size == 0
        assert length % csum_block_size == 0
        arr = np.frombuffer(bytes(data), dtype=np.uint8)
        blocks = arr[offset:offset + length]
        vals = _calc_values(csum_type, blocks, csum_block_size, init_value,
                            use_tpu)
        dtype = _VALUE_DTYPE[csum_type]
        vsize = dtype.itemsize
        first = offset // csum_block_size
        stored = np.frombuffer(
            bytes(csum_data[first * vsize:(first + vals.size) * vsize]),
            dtype=dtype)
        if stored.size < vals.size:
            return offset  # missing csum data counts as a mismatch
        mism = np.nonzero(stored != vals.astype(dtype))[0]
        if mism.size == 0:
            return -1
        return offset + int(mism[0]) * csum_block_size

"""Layered typed configuration.

Reference parity: md_config_t (/root/reference/src/common/config.cc) and
its source precedence (SURVEY.md §5.6): compiled default < conf file <
mon centralized config < environment < CLI < runtime override.  Observers
(md_config_obs_t) are notified with the set of changed keys on
apply_changes, enabling live reconfiguration (e.g. BlueStore re-reading
bluestore_csum_type, BlueStore.cc:4457).

Conf files are ini-style like ceph.conf: [global]/[osd]/[osd.0] sections,
later/more-specific sections win.
"""

from __future__ import annotations

import configparser
import os
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Set

from ceph_tpu.common.options import OPTIONS, Option

# precedence, low to high (config.cc source ranking)
SOURCES = ("default", "file", "mon", "env", "cli", "runtime")

Observer = Callable[[Set[str]], None]


class Config:
    def __init__(self, entity: str = "client") -> None:
        self.entity = entity  # e.g. "osd.3" / "mon.a" / "client"
        self._lock = threading.RLock()
        self._values: Dict[str, Dict[str, Any]] = {s: {} for s in SOURCES}
        self._observers: List[tuple] = []  # (keys, callback)
        self._staged: Set[str] = set()

    # -- reads ------------------------------------------------------------

    def get(self, name: str) -> Any:
        opt = OPTIONS.get(name)
        with self._lock:
            for source in reversed(SOURCES):
                if name in self._values[source]:
                    return self._values[source][name]
        if opt is None:
            raise KeyError(name)
        return opt.default

    def get_val(self, name: str) -> Any:
        return self.get(name)

    def source_of(self, name: str) -> str:
        with self._lock:
            for source in reversed(SOURCES):
                if name in self._values[source]:
                    return source
        return "default"

    def show_config(self) -> Dict[str, Any]:
        return {name: self.get(name) for name in sorted(OPTIONS)}

    def diff(self) -> Dict[str, Dict[str, Any]]:
        """Non-default values with their source (`config diff`)."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, opt in OPTIONS.items():
            val = self.get(name)
            if val != opt.default:
                out[name] = {"current": val, "default": opt.default,
                             "source": self.source_of(name)}
        return out

    # -- writes -----------------------------------------------------------

    def set_val(self, name: str, value: Any, source: str = "runtime",
                apply: bool = True) -> None:
        if source not in SOURCES or source == "default":
            raise ValueError(f"bad config source {source}")
        opt = OPTIONS.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name}")
        cast = opt.cast(value)
        with self._lock:
            self._values[source][name] = cast
            self._staged.add(name)
        if apply:
            self.apply_changes()

    def rm_val(self, name: str, source: str = "runtime",
               apply: bool = True) -> None:
        with self._lock:
            if self._values[source].pop(name, None) is not None:
                self._staged.add(name)
        if apply:
            self.apply_changes()

    def apply_changes(self) -> Set[str]:
        with self._lock:
            changed = set(self._staged)
            self._staged.clear()
            observers = list(self._observers)
        for keys, callback in observers:
            relevant = changed if keys is None else (changed & keys)
            if relevant:
                callback(relevant)
        return changed

    # -- observers (md_config_obs_t) --------------------------------------

    def add_observer(self, callback: Observer,
                     keys: Optional[Iterable[str]] = None) -> None:
        with self._lock:
            self._observers.append(
                (set(keys) if keys is not None else None, callback))

    def remove_observer(self, callback: Observer) -> None:
        with self._lock:
            self._observers = [(k, cb) for k, cb in self._observers
                               if cb is not callback]

    # -- bulk sources -----------------------------------------------------

    def parse_env(self, env: Optional[Dict[str, str]] = None) -> None:
        """CEPH_TPU_<OPTION_NAME>=value environment overrides."""
        env = os.environ if env is None else env
        for key, val in env.items():
            if not key.startswith("CEPH_TPU_"):
                continue
            name = key[len("CEPH_TPU_"):].lower()
            if name in OPTIONS:
                self.set_val(name, val, source="env", apply=False)
        self.apply_changes()

    def parse_argv(self, argv: List[str]) -> List[str]:
        """--name=value / --name value CLI overrides; returns leftovers."""
        leftover: List[str] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            if arg.startswith("--"):
                body = arg[2:]
                if "=" in body:
                    name, val = body.split("=", 1)
                    name = name.replace("-", "_")
                    if name in OPTIONS:
                        self.set_val(name, val, source="cli", apply=False)
                        i += 1
                        continue
                else:
                    name = body.replace("-", "_")
                    if name in OPTIONS and i + 1 < len(argv):
                        self.set_val(name, argv[i + 1], source="cli",
                                     apply=False)
                        i += 2
                        continue
            leftover.append(arg)
            i += 1
        self.apply_changes()
        return leftover

    def parse_config_file(self, path: str) -> None:
        """ceph.conf-style ini: [global] < [<type>] < [<type>.<id>]."""
        parser = configparser.ConfigParser(strict=False)
        with open(path) as f:
            parser.read_string(f.read())
        entity_type = self.entity.split(".")[0]
        sections = ["global", entity_type, self.entity]
        for section in sections:
            if not parser.has_section(section):
                continue
            for name, val in parser.items(section):
                name = name.replace(" ", "_")
                if name in OPTIONS:
                    self.set_val(name, val, source="file", apply=False)
        self.apply_changes()

    def set_mon_vals(self, values: Dict[str, Any]) -> None:
        """Centralized config pushed by the monitor (ConfigMonitor)."""
        for name, val in values.items():
            if name in OPTIONS:
                self.set_val(name, val, source="mon", apply=False)
        self.apply_changes()


_global_config: Optional[Config] = None
_global_lock = threading.Lock()


def global_config() -> Config:
    global _global_config
    with _global_lock:
        if _global_config is None:
            _global_config = Config()
        return _global_config

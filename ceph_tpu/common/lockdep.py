"""lockdep: lock-order inversion detection for the asyncio runtime.

Reference parity: /root/reference/src/common/lockdep.cc — every lock
acquisition records "B taken while holding A" edges in a global order
graph; an acquisition that would close a cycle is a potential deadlock
and is reported at ACQUISITION time, long before any real interleaving
hits it.

Why this framework needs less than pthread lockdep — the in-tree
argument for §5.2 (race detection): every daemon runs ONE asyncio event
loop, so data races on plain Python state are impossible — state only
changes at explicit `await` points, and all mutual exclusion is
asyncio.Lock-shaped.  The remaining hazard class is therefore exactly
lock-order deadlock between coroutines (plus the slot/lock coupling
documented at the QoS scheduler), which this module detects.

Model: lock CLASSES, not instances (as in the reference) — the object
lock of obj-1 and obj-2 are the same class.  Same-class nesting is
allowed (the recovery wave legally holds many object locks via
independent subtasks; a single task re-entering the same class is the
caller's documented discipline).  Cross-class cycles are flagged.

Enabled via CEPH_TPU_LOCKDEP=1 (tests) — the hooks are no-ops
otherwise, so the production path pays one attribute check.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Dict, List, Set

log = logging.getLogger("lockdep")

enabled = os.environ.get("CEPH_TPU_LOCKDEP", "0") == "1"

# class -> classes acquired while holding it
_edges: Dict[str, Set[str]] = {}
# id(task) -> stack of held lock classes
_held: Dict[int, List[str]] = {}


class LockOrderInversion(Exception):
    """Acquiring this lock class here can deadlock: the reverse order
    is already on record."""


def _reachable(src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(_edges.get(node, ()))
    return False


def acquire(cls: str) -> None:
    """Record an acquisition of lock class `cls` by the current task;
    raises LockOrderInversion on a would-be cycle."""
    if not enabled:
        return
    task = asyncio.current_task()
    if task is None:
        return
    held = _held.setdefault(id(task), [])
    for h in held:
        if h == cls:
            continue  # same-class nesting: allowed (see docstring)
        if cls not in _edges.get(h, ()):  # new edge h -> cls
            if _reachable(cls, h):
                order = " -> ".join(held + [cls])
                log.error("lockdep: ORDER INVERSION acquiring %s "
                          "while holding %s (existing order has "
                          "%s -> ... -> %s)", cls, held, cls, h)
                raise LockOrderInversion(order)
            _edges.setdefault(h, set()).add(cls)
    held.append(cls)


def release(cls: str) -> None:
    if not enabled:
        return
    task = asyncio.current_task()
    if task is None:
        return
    held = _held.get(id(task))
    if held:
        try:
            held.reverse()
            held.remove(cls)
            held.reverse()
        except ValueError:
            pass
        if not held:
            _held.pop(id(task), None)


def reset() -> None:
    """Test hook: clear the global order graph."""
    _edges.clear()
    _held.clear()


class guard:
    """Async context manager pairing an asyncio.Lock with lockdep
    tracking: `async with lockdep.guard(lock, "mds.mutation"): ...`"""

    def __init__(self, lock: asyncio.Lock, cls: str):
        self._lock = lock
        self._cls = cls

    async def __aenter__(self):
        acquire(self._cls)
        try:
            await self._lock.acquire()
        except BaseException:
            release(self._cls)
            raise
        return self

    async def __aexit__(self, *exc):
        self._lock.release()
        release(self._cls)

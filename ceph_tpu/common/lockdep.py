"""lockdep: lock-order inversion detection for the asyncio runtime.

Reference parity: /root/reference/src/common/lockdep.cc — every lock
acquisition records "B taken while holding A" edges in a global order
graph; an acquisition that would close a cycle is a potential deadlock
and is reported at ACQUISITION time, long before any real interleaving
hits it.

Why this framework needs less than pthread lockdep — the in-tree
argument for §5.2 (race detection): every daemon runs ONE asyncio event
loop, so data races on plain Python state are impossible — state only
changes at explicit `await` points, and all mutual exclusion is
asyncio.Lock-shaped.  The remaining hazard class is therefore exactly
lock-order deadlock between coroutines (plus the slot/lock coupling
documented at the QoS scheduler), which this module detects.

Model: lock CLASSES, not instances (as in the reference) — the object
lock of obj-1 and obj-2 are the same class.  Same-class nesting is
allowed (the recovery wave legally holds many object locks via
independent subtasks; a single task re-entering the same class is the
caller's documented discipline).  Cross-class cycles are flagged.

Enabled via CEPH_TPU_LOCKDEP=1 (tests) — the hooks are no-ops
otherwise, so the production path pays one attribute check.
"""

from __future__ import annotations

import asyncio
import logging
import os

from ceph_tpu.common import flags
import weakref
from typing import Dict, List, Optional, Set

log = logging.getLogger("lockdep")

enabled = flags.get("CEPH_TPU_LOCKDEP") == "1"

# class -> classes acquired while holding it
_edges: Dict[str, Set[str]] = {}
# task -> stack of held lock classes.  Keyed by the Task OBJECT under
# a weak reference, never id(task): a task that dies with entries on
# its stack (legal — see _ObjLockCtx's cross-task handoff in the OSD
# recovery wave) must not bequeath phantom "held" locks to a later
# task that happens to recycle its id, which would fabricate order
# edges between locks no task ever nested.
_held: "weakref.WeakKeyDictionary[asyncio.Task, List[str]]" = \
    weakref.WeakKeyDictionary()


class LockOrderInversion(Exception):
    """Acquiring this lock class here can deadlock: the reverse order
    is already on record."""


def _reachable(src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        stack.extend(_edges.get(node, ()))
    return False


def acquire(cls: str) -> Optional[asyncio.Task]:
    """Record an acquisition of lock class `cls` by the current task;
    raises LockOrderInversion on a would-be cycle.  Returns the task
    whose stack recorded it, for callers that may release from a
    different task (pass it back to `release`)."""
    if not enabled:
        return None
    task = asyncio.current_task()
    if task is None:
        return None
    held = _held.get(task)
    if held is None:
        held = _held[task] = []
    for h in held:
        if h == cls:
            continue  # same-class nesting: allowed (see docstring)
        if cls not in _edges.get(h, ()):  # new edge h -> cls
            if _reachable(cls, h):
                order = " -> ".join(held + [cls])
                log.error("lockdep: ORDER INVERSION acquiring %s "
                          "while holding %s (existing order has "
                          "%s -> ... -> %s)", cls, held, cls, h)
                raise LockOrderInversion(order)
            _edges.setdefault(h, set()).add(cls)
    held.append(cls)
    return task


def release(cls: str, task: Optional[asyncio.Task] = None) -> None:
    """Drop `cls` from a task's held stack — by default the current
    task's; pass the task `acquire` returned when the releasing task
    differs from the acquiring one (lock handed across tasks)."""
    if not enabled:
        return
    if task is None:
        task = asyncio.current_task()
    if task is None:
        return
    held = _held.get(task)
    if held:
        try:
            held.reverse()
            held.remove(cls)
            held.reverse()
        except ValueError:
            pass
        if not held:
            _held.pop(task, None)


def reset() -> None:
    """Test hook: clear the global order graph."""
    _edges.clear()
    _held.clear()


async def _tracked_acquire(lock: asyncio.Lock,
                           cls: str) -> Optional[asyncio.Task]:
    """The one copy of the acquire pairing: record with lockdep, take
    the lock, un-record if the take itself fails (cancellation while
    queued).  Returns the recording task for cross-task release."""
    task = acquire(cls)
    try:
        await lock.acquire()
    except BaseException:
        release(cls, task)
        raise
    return task


class Lock(asyncio.Lock):
    """asyncio.Lock whose `async with` feeds the order graph under a
    fixed class name: `self._mutation_lock = lockdep.Lock("mds.mutation")`.
    The class string follows the static analyzer's labeling (module
    tail + attr name stripped of `_lock`), so runtime-observed edges
    line up 1:1 with ceph_tpu/analysis/lockgraph.py's graph."""

    def __init__(self, cls: str):
        super().__init__()
        self.lockdep_class = cls

    async def __aenter__(self):
        # enter/exit of one `async with` always run in the same task,
        # so the current-task release below is the right stack; no
        # per-entry state may live on self (a waiter queued inside
        # __aenter__ would race the holder's __aexit__)
        await _tracked_acquire(self, self.lockdep_class)
        return None

    async def __aexit__(self, *exc):
        self.release()
        release(self.lockdep_class)


class guard:
    """Async context manager pairing an asyncio.Lock with lockdep
    tracking: `async with lockdep.guard(lock, "mds.mutation"): ...`
    Single-use per instance; the instance remembers the acquiring
    task, so exiting from a different task releases correctly."""

    def __init__(self, lock: asyncio.Lock, cls: str):
        self._lock = lock
        self._cls = cls
        self._task: Optional[asyncio.Task] = None

    async def __aenter__(self):
        self._task = await _tracked_acquire(self._lock, self._cls)
        return self

    async def __aexit__(self, *exc):
        self._lock.release()
        release(self._cls, self._task)

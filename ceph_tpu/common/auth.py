"""cephx: shared-secret authentication with per-connection session keys,
tickets, and key rotation.

Reference parity:
- MESSAGE SIGNING (/root/reference/src/auth/cephx/CephxSessionHandler.cc
  sign_message): every frame carries a truncated HMAC keyed by the
  connection's SESSION key.
- Session-key negotiation: a mutual nonce handshake per connection
  derives session_key = HMAC(base_key, nonce_a || nonce_b) — the
  CephxSessionHandler session-key role.  A frame recorded on one
  connection can never verify on another (fresh nonces => fresh key),
  and within a connection the receiver enforces strictly increasing
  signed sequence numbers — together these kill replay.
- Mon-as-KDC tickets (/root/reference/src/auth/cephx/
  CephxServiceHandler.h:23, CephxProtocol.h): a client proves key
  possession against a server challenge; the mon grants a signed,
  expiring ticket whose base key any service holding the cluster key
  derives offline — services never consult the KDC to validate.
- Key rotation (KeyServer rotating-secrets role): the keyring holds
  multiple (kid, key) entries; new handshakes/tickets use the active
  kid, peers accept any listed kid, operators rotate by adding a key,
  flipping active, then dropping the old one.

Deliberate simplifications (documented, not hidden): one cluster-wide
key plays the per-entity key role (named per-entity keys are a keyring
layout away, not a protocol change), and ticket blobs are signed
assertions rather than encrypted grants — the base key is derived, not
carried, so nothing secret rides the wire.

Keyring format (`ceph-authtool` role): a hex string (kid 0), or
comma-separated `kid:hex` entries — the FIRST entry is the active key.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import time
from typing import Dict, Optional, Tuple

SIG_LEN = 8       # truncated HMAC-SHA256, like cephx's 64-bit signatures
NONCE_LEN = 16
TICKET_LIFETIME = 3600.0  # auth_service_ticket_ttl default role


def generate_secret() -> str:
    return os.urandom(32).hex()


class Keyring:
    """Rotating key set: {kid: key}; the active kid signs new work."""

    def __init__(self, keys: Dict[int, bytes], active: int):
        self.keys = keys
        self.active = active

    @property
    def active_key(self) -> bytes:
        return self.keys[self.active]

    def get(self, kid: int) -> Optional[bytes]:
        return self.keys.get(kid)


def parse_secret(raw) -> Optional[Keyring]:
    """Keyring string -> Keyring (None/empty = auth disabled).

    `<hex>` (kid 0) or `kid:hex,kid:hex,...` (first = active)."""
    if not raw:
        return None
    if isinstance(raw, Keyring):
        return raw
    keys: Dict[int, bytes] = {}
    active = None
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            kid_s, hexkey = part.split(":", 1)
            kid = int(kid_s)
        else:
            kid, hexkey = 0, part
        keys[kid] = bytes.fromhex(hexkey)
        if active is None:
            active = kid
    if active is None:
        return None
    return Keyring(keys, active)


def load_keyring(path: str) -> Optional[Keyring]:
    with open(path) as f:
        return parse_secret(f.read().strip())


def sign(key: bytes, *parts: bytes) -> bytes:
    mac = hmac.new(key, digestmod=hashlib.sha256)
    for part in parts:
        mac.update(part)
    return mac.digest()[:SIG_LEN]


def verify(key: bytes, sig: bytes, *parts: bytes) -> bool:
    return hmac.compare_digest(sign(key, *parts), sig)


def _prf(key: bytes, label: bytes, *parts: bytes) -> bytes:
    mac = hmac.new(key, label, hashlib.sha256)
    for part in parts:
        mac.update(part)
    return mac.digest()


def new_nonce() -> bytes:
    return os.urandom(NONCE_LEN)


def derive_session(base_key: bytes, nonce_a: bytes,
                   nonce_b: bytes) -> bytes:
    """Per-connection session key: fresh nonces on both sides make a
    frame recorded elsewhere unverifiable here."""
    return _prf(base_key, b"cephx-session", nonce_a, nonce_b)


# -- secure mode (crypto_onwire.cc AES-GCM role) ----------------------------
#
# seal/unseal wrap each secure frame's payload in AES-256-GCM: the
# 12-byte nonce is role(1) || seq(8, big-endian) || 0^3 — it never
# repeats under a key because session keys are per-connection, seqs are
# strictly increasing per direction, and the role byte separates the
# two directions' streams (the reference's distinct c->s / s->c nonce
# halves, crypto_onwire.cc:34-46).  Output = mode byte || ciphertext ||
# 16-byte tag; a receiver REJECTS any mode weaker than its best (a
# MITM must not be able to downgrade two AEAD-capable peers to the
# keystream fallback by flipping the mode byte).
#
# The AEAD is the in-repo native C++ implementation (native/src/
# aesgcm.cc, validated bit-exact against `cryptography`'s OpenSSL-
# backed AESGCM); `cryptography` is the second choice, and the old
# SHAKE-256 keystream XOR (integrity from the frame signature) remains
# only as the no-compiler, no-cryptography fallback.

MODE_XOR = 0x00
MODE_AESGCM = 0x01

_aead_impl = None  # resolved lazily: "native" | "cryptography" | None


def _resolve_aead() -> Optional[str]:
    global _aead_impl
    if _aead_impl is not None:
        return _aead_impl or None
    impl = ""
    try:
        from ceph_tpu import native

        lib = native.get_lib()
        if lib is not None and hasattr(lib, "ceph_tpu_aesgcm_seal"):
            impl = "native"
    except Exception:
        pass
    if not impl:
        try:
            from cryptography.hazmat.primitives.ciphers.aead import (  # noqa: F401
                AESGCM,
            )

            impl = "cryptography"
        except Exception:
            impl = ""
    _aead_impl = impl
    return impl or None


def _gcm_nonce(role: bytes, seq: int) -> bytes:
    return (role or b"?")[:1] + seq.to_bytes(8, "big") + b"\x00\x00\x00"


def _gcm_key(session_key: bytes) -> bytes:
    # session keys are HMAC-SHA256 outputs (32 bytes) — AES-256 direct
    return session_key if len(session_key) == 32 else \
        hashlib.sha256(session_key).digest()


def _native_gcm(op: str, key: bytes, nonce: bytes,
                data: bytes) -> Optional[bytes]:
    import ctypes

    from ceph_tpu import native

    lib = native.get_lib()
    u8 = ctypes.c_uint8
    n = len(data)
    if op == "seal":
        out = (u8 * (n + 16))()
        fn, outlen = lib.ceph_tpu_aesgcm_seal, n + 16
    else:
        if n < 16:
            return None
        out = (u8 * max(1, n - 16))()
        fn, outlen = lib.ceph_tpu_aesgcm_open, n - 16
    src = (u8 * max(1, n)).from_buffer_copy(data or b"\x00")
    rc = fn((u8 * 32).from_buffer_copy(key),
            (u8 * 12).from_buffer_copy(nonce),
            (u8 * 1)(), 0, src, n, out)
    if rc != 0:
        return None
    return bytes(out[:outlen])


def _xor_keystream(session_key: bytes, role: bytes, seq: int,
                   data: bytes) -> bytes:
    if not data:
        return data
    ks = hashlib.shake_256(
        session_key + role + seq.to_bytes(8, "big")).digest(len(data))
    import numpy as np

    a = np.frombuffer(data, dtype=np.uint8)
    b = np.frombuffer(ks, dtype=np.uint8)
    return (a ^ b).tobytes()


def aead_available() -> bool:
    """Capability probe for the MHello aead advertisement: peers
    negotiate the sealing mode instead of guessing from their OWN
    toolchain (a no-AEAD peer is a legitimate fallback, not an
    attack — but only when it SAYS so in its signed hello)."""
    return _resolve_aead() is not None


def seal(session_key: bytes, role: bytes, seq: int,
         data: bytes, peer_aead: Optional[bool] = None) -> bytes:
    """peer_aead: the peer's hello-advertised AEAD capability (None =
    unknown).  A peer that advertised False cannot open AES-GCM, so
    the frame legitimately falls back to the keystream mode."""
    impl = _resolve_aead()
    if impl is None or peer_aead is False:
        return bytes([MODE_XOR]) + _xor_keystream(session_key, role,
                                                  seq, data)
    key, nonce = _gcm_key(session_key), _gcm_nonce(role, seq)
    if impl == "native":
        ct = _native_gcm("seal", key, nonce, data)
        if ct is not None:
            return bytes([MODE_AESGCM]) + ct
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    return bytes([MODE_AESGCM]) + AESGCM(key).encrypt(nonce, data,
                                                      None)


class SealError(Exception):
    """Authentication failure or downgrade attempt on a secure frame."""


def unseal(session_key: bytes, role: bytes, seq: int,
           data: bytes, peer_aead: Optional[bool] = None) -> bytes:
    """peer_aead: the peer's hello-advertised AEAD capability (None =
    unknown).  Gates the downgrade check below: a keystream frame is
    legitimate from a peer that ADVERTISED no AEAD (its hello is
    signed, so the advertisement is authentic), and an attack when the
    peer is known or presumed capable."""
    if not data:
        raise SealError("empty secure payload")
    mode, body = data[0], data[1:]
    impl = _resolve_aead()
    if mode == MODE_AESGCM:
        if impl is None:
            raise SealError("peer sent AES-GCM but no AEAD available")
        key, nonce = _gcm_key(session_key), _gcm_nonce(role, seq)
        if impl == "native":
            pt = _native_gcm("open", key, nonce, body)
            if pt is None:
                raise SealError("AES-GCM tag mismatch")
            return pt
        from cryptography.exceptions import InvalidTag
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        try:
            return AESGCM(key).decrypt(nonce, body, None)
        except InvalidTag:
            raise SealError("AES-GCM tag mismatch")
    if mode == MODE_XOR:
        if impl is not None and peer_aead is not False:
            # the peer either advertised AEAD or never said (same-
            # version peers always advertise): a keystream frame here
            # is a downgrade (an attacker flipping the mode byte), not
            # a legitimate fallback
            raise SealError("keystream frame from an AEAD-capable"
                            " peer: downgrade rejected")
        return _xor_keystream(session_key, role, seq, body)
    raise SealError(f"unknown secure mode {mode:#x}")


# -- mon-as-KDC tickets ------------------------------------------------------


def auth_proof(key: bytes, entity: str, client_challenge: bytes,
               server_challenge: bytes) -> bytes:
    """Client's proof of key possession (the CephxServiceHandler
    challenge-hash role)."""
    return _prf(key, b"cephx-proof", entity.encode(),
                client_challenge, server_challenge)[:SIG_LEN]


def check_proof(key: bytes, entity: str, client_challenge: bytes,
                server_challenge: bytes, proof: bytes) -> bool:
    """Constant-time validation of a client's proof (the verify()
    sibling of auth_proof)."""
    return hmac.compare_digest(
        auth_proof(key, entity, client_challenge, server_challenge),
        bytes(proof))


def make_ticket(keyring: Keyring, entity: str,
                lifetime: float = TICKET_LIFETIME) -> bytes:
    """Signed expiring assertion; blob = json || sig."""
    blob = json.dumps({
        "entity": entity,
        "expires": time.time() + lifetime,
        "kid": keyring.active,
        "nonce": os.urandom(8).hex(),
    }, sort_keys=True).encode()
    return blob + sign(keyring.active_key, b"cephx-ticket", blob)


def ticket_base_key(key: bytes, blob: bytes) -> bytes:
    return _prf(key, b"cephx-ticket-base", blob)


def check_ticket(keyring: Keyring, ticket: bytes
                 ) -> Optional[Tuple[str, bytes]]:
    """Validate a ticket offline; returns (entity, base_key) or None."""
    if len(ticket) <= SIG_LEN:
        return None
    blob, sig = ticket[:-SIG_LEN], ticket[-SIG_LEN:]
    try:
        doc = json.loads(blob)
        kid = int(doc["kid"])
    except (ValueError, KeyError, TypeError):
        return None
    key = keyring.get(kid)
    if key is None:
        return None
    if not verify(key, sig, b"cephx-ticket", blob):
        return None
    if doc.get("expires", 0) < time.time():
        return None
    return str(doc.get("entity", "")), ticket_base_key(key, blob)

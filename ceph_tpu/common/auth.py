"""cephx-lite: shared-secret message authentication.

Reference parity: the cephx protocol's MESSAGE SIGNING tier
(/root/reference/src/auth/cephx/CephxSessionHandler.cc:sign_message —
every frame carries an HMAC over its header+payload keyed by the
session key; `cephx_sign_messages`).  Deliberate simplification: one
static cluster secret plays the session-key role (no ticket exchange /
per-session key negotiation — the mon-as-KDC machinery of
CephxServiceHandler).  The security property kept: a peer WITHOUT the
key cannot forge or tamper with frames — unsigned or mis-signed frames
drop the connection.  NOT kept (needs the session-key handshake):
replay protection — an observer who records a signed frame can replay
it on a new connection, since the key is static and frame seq is not
bound to a per-session nonce.  Appropriate threat model: accidental
cross-cluster joins and non-recording network peers, not an active
recording attacker.

Keyring format (`ceph-authtool` role): a hex string, one per file.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional

SIG_LEN = 8  # truncated HMAC-SHA256, like cephx's 64-bit signatures


def generate_secret() -> str:
    return os.urandom(32).hex()


def parse_secret(raw: Optional[str]) -> Optional[bytes]:
    """hex keyring string -> key bytes (None/empty = auth disabled)."""
    if not raw:
        return None
    return bytes.fromhex(raw)


def load_keyring(path: str) -> Optional[bytes]:
    with open(path) as f:
        return parse_secret(f.read().strip())


def sign(secret: bytes, *parts: bytes) -> bytes:
    mac = hmac.new(secret, digestmod=hashlib.sha256)
    for part in parts:
        mac.update(part)
    return mac.digest()[:SIG_LEN]


def verify(secret: bytes, sig: bytes, *parts: bytes) -> bool:
    return hmac.compare_digest(sign(secret, *parts), sig)

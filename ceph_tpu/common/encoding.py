"""Versioned wire encoding.

Reference parity: the encode/decode framework
(/root/reference/src/include/encoding.h): little-endian primitives,
length-prefixed strings/containers, and versioned struct blocks —
ENCODE_START(v, compat, bl) writes (struct_v u8, struct_compat u8,
struct_len u32) and DECODE_FINISH skips any unknown tail, which is what
makes rolling upgrades possible.  This module provides the same contract
for this framework's maps and messages.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple


class Encoder:
    def __init__(self) -> None:
        self._parts: List[bytes] = []
        self._len = 0
        self._blocks: List[Tuple[int, int]] = []  # (part index, len so far)

    # -- primitives -------------------------------------------------------

    def _raw(self, b: bytes) -> None:
        self._parts.append(b)
        self._len += len(b)

    def u8(self, v: int) -> None:
        self._raw(struct.pack("<B", v))

    def u16(self, v: int) -> None:
        self._raw(struct.pack("<H", v))

    def u32(self, v: int) -> None:
        self._raw(struct.pack("<I", v & 0xFFFFFFFF))

    def u64(self, v: int) -> None:
        self._raw(struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF))

    def s32(self, v: int) -> None:
        self._raw(struct.pack("<i", v))

    def s64(self, v: int) -> None:
        self._raw(struct.pack("<q", v))

    def f64(self, v: float) -> None:
        self._raw(struct.pack("<d", v))

    def bool(self, v: bool) -> None:
        self.u8(1 if v else 0)

    def bytes(self, v: bytes) -> None:
        self.u32(len(v))
        # bytes passes through untouched; memoryview rides as-is into
        # the join (bulk data must not pay an extra pass here)
        self._raw(v if isinstance(v, (bytes, memoryview))
                  else bytes(v))

    def string(self, v: str) -> None:
        self.bytes(v.encode("utf-8"))

    # -- containers -------------------------------------------------------

    def list(self, items, encode_item: Callable[["Encoder", Any], None]
             ) -> None:
        self.u32(len(items))
        for item in items:
            encode_item(self, item)

    def map(self, d: Dict, encode_key, encode_val) -> None:
        self.u32(len(d))
        for key in d:
            encode_key(self, key)
            encode_val(self, d[key])

    def optional(self, v, encode_val) -> None:
        self.bool(v is not None)
        if v is not None:
            encode_val(self, v)

    # -- versioned blocks (ENCODE_START / ENCODE_FINISH) ------------------

    def start(self, version: int, compat: int) -> None:
        self.u8(version)
        self.u8(compat)
        self._parts.append(b"\x00\x00\x00\x00")  # length hole
        self._blocks.append((len(self._parts) - 1, self._len))
        self._len += 4

    def finish(self) -> None:
        idx, len_before = self._blocks.pop()
        body_len = self._len - len_before - 4
        self._parts[idx] = struct.pack("<I", body_len)

    def to_bytes(self) -> bytes:
        assert not self._blocks, "unfinished encode block"
        return b"".join(self._parts)


class DecodeError(ValueError):
    pass


class Decoder:
    def __init__(self, data: bytes, offset: int = 0):
        self._data = memoryview(data)
        self._pos = offset
        self._ends: List[int] = []  # struct block end offsets

    def remaining(self) -> int:
        end = self._ends[-1] if self._ends else len(self._data)
        return end - self._pos

    def _take(self, n: int) -> memoryview:
        if self.remaining() < n:
            raise DecodeError(
                f"buffer exhausted: need {n}, have {self.remaining()}")
        out = self._data[self._pos:self._pos + n]
        self._pos += n
        return out

    # -- primitives -------------------------------------------------------

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def s32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def s64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def bool(self) -> bool:
        return self.u8() != 0

    def bytes(self) -> bytes:
        n = self.u32()
        return bytes(self._take(n))

    def bytes_view(self) -> memoryview:
        """Zero-copy bulk-data read: a view into the frame buffer.
        For multi-MiB payload fields the bytes() copy is a full extra
        pass over the data."""
        n = self.u32()
        return self._take(n)

    def string(self) -> str:
        return self.bytes().decode("utf-8")

    # -- containers -------------------------------------------------------

    def list(self, decode_item: Callable[["Decoder"], Any]) -> List[Any]:
        n = self.u32()
        return [decode_item(self) for _ in range(n)]

    def map(self, decode_key, decode_val) -> Dict:
        n = self.u32()
        out = {}
        for _ in range(n):
            key = decode_key(self)
            out[key] = decode_val(self)
        return out

    def optional(self, decode_val) -> Optional[Any]:
        return decode_val(self) if self.bool() else None

    # -- versioned blocks (DECODE_START / DECODE_FINISH) ------------------

    def start(self, compat_expected: int) -> int:
        """Returns struct_v; raises if the encoder's compat is newer than
        what this decoder understands (the cross-version contract)."""
        struct_v = self.u8()
        struct_compat = self.u8()
        if struct_compat > compat_expected:
            raise DecodeError(
                f"struct compat {struct_compat} > understood"
                f" {compat_expected}")
        length = self.u32()
        if self.remaining() < length:
            raise DecodeError("struct length beyond buffer")
        self._ends.append(self._pos + length)
        return struct_v

    def finish(self) -> None:
        """Skip any tail a newer encoder appended (DECODE_FINISH)."""
        end = self._ends.pop()
        if self._pos > end:
            raise DecodeError("struct overread")
        self._pos = end

"""Throttles: bounded counters gating queues.

Reference parity: Throttle / BackoffThrottle
(/root/reference/src/common/Throttle.{h,cc}): a named max-bounded counter;
`get(c)` blocks while the budget is exhausted (FIFO wakeup), `get_or_fail`
never blocks, `put(c)` returns budget and wakes waiters.  Used on every
ingest path (messenger dispatch bytes, osd op bytes, recovery ops).
"""

from __future__ import annotations

import collections
import threading
from typing import Optional


class Throttle:
    def __init__(self, name: str, max_: int):
        self.name = name
        self._max = max_
        self._count = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # FIFO ticket queue: a blocked get() only proceeds at the head,
        # so small requests cannot starve a large one (the reference keeps
        # an ordered list of per-waiter condition variables)
        self._tickets: collections.deque = collections.deque()

    # -- introspection ----------------------------------------------------

    def get_current(self) -> int:
        with self._lock:
            return self._count

    def get_max(self) -> int:
        return self._max

    def past_midpoint(self) -> bool:
        with self._lock:
            return self._count >= self._max / 2

    # -- acquire / release ------------------------------------------------

    def _should_wait(self, c: int) -> bool:
        if not self._max:
            return False
        # a single request larger than max is allowed through alone
        return ((c <= self._max and self._count + c > self._max) or
                (c > self._max and self._count > 0))

    def get(self, c: int = 1, timeout: Optional[float] = None) -> bool:
        """Block until c fits (FIFO order); False on timeout."""
        assert c >= 0
        ticket = object()
        with self._cond:
            self._tickets.append(ticket)
            try:
                ok = self._cond.wait_for(
                    lambda: (self._tickets[0] is ticket
                             and not self._should_wait(c)), timeout)
                if not ok:
                    return False
                self._count += c
                return True
            finally:
                self._tickets.remove(ticket)
                self._cond.notify_all()  # next ticket may now be at head

    def get_or_fail(self, c: int = 1) -> bool:
        with self._lock:
            if self._tickets or self._should_wait(c):
                return False
            self._count += c
            return True

    def put(self, c: int = 1) -> int:
        with self._cond:
            assert self._count >= c
            self._count -= c
            self._cond.notify_all()
            return self._count

    def reset_max(self, new_max: int) -> None:
        with self._cond:
            self._max = new_max
            self._cond.notify_all()

    def __enter__(self):
        self.get(1)
        return self

    def __exit__(self, *exc):
        self.put(1)
        return False

"""Admin socket: per-daemon unix-socket JSON command server.

Reference parity: AdminSocket
(/root/reference/src/common/admin_socket.cc): a listener thread on a unix
domain socket; requests are a NUL-terminated command (JSON
`{"prefix": "...", ...}` or a bare legacy string); responses are a 4-byte
network-order length followed by the payload — the same wire format, so
`ceph daemon <sock> <cmd>`-style clients carry over.  Built-in commands:
help, version, perf dump, perf schema, config get/set/show/diff.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

Handler = Callable[[Dict[str, Any]], Any]


class AdminSocket:
    def __init__(self, path: str, config=None, perf=None,
                 version: str = "ceph_tpu"):
        self.path = path
        self._config = config
        self._perf = perf
        self._version = version
        self._handlers: Dict[str, Tuple[str, Handler]] = {}
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False
        self._register_builtins()

    # -- command registry -------------------------------------------------

    def register_command(self, prefix: str, handler: Handler,
                         help_: str = "") -> int:
        if prefix in self._handlers:
            return -17  # EEXIST
        self._handlers[prefix] = (help_, handler)
        return 0

    def unregister_command(self, prefix: str) -> None:
        self._handlers.pop(prefix, None)

    def _register_builtins(self) -> None:
        self.register_command(
            "help", lambda cmd: {p: h for p, (h, _f) in
                                 sorted(self._handlers.items())},
            "list available commands")
        self.register_command(
            "version", lambda cmd: {"version": self._version},
            "get version")
        if self._perf is not None:
            self.register_command(
                "perf dump", lambda cmd: self._perf.dump(
                    cmd.get("logger") or cmd.get("var", "")),
                "dump perfcounters value")
            self.register_command(
                "perf schema", lambda cmd: self._perf.schema(),
                "dump perfcounters schema")
        if self._config is not None:
            self.register_command(
                "config show", lambda cmd: self._config.show_config(),
                "dump current config settings")
            self.register_command(
                "config diff", lambda cmd: self._config.diff(),
                "dump diff of current config and default config")
            self.register_command(
                "config get",
                lambda cmd: {cmd["var"]: self._config.get(cmd["var"])},
                "config get <field>: get the config value")

            def _config_set(cmd):
                self._config.set_val(cmd["var"], cmd["val"])
                return {"success": ""}

            self.register_command(
                "config set", _config_set,
                "config set <field> <val>: set a config variable")

    # -- server -----------------------------------------------------------

    def init(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(
            target=self._serve, name="admin_socket", daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._shutdown = True
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self._sock is not None:
            self._sock.close()
        if os.path.exists(self.path):
            os.unlink(self.path)

    def _serve(self) -> None:
        while not self._shutdown:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle_conn(conn)
            except Exception:
                pass
            finally:
                conn.close()

    def _handle_conn(self, conn: socket.socket) -> None:
        conn.settimeout(5.0)
        raw = bytearray()
        while True:
            b = conn.recv(1)
            if not b or b == b"\0":
                break
            raw += b
            if len(raw) > 1024:
                break
        response = self._dispatch(bytes(raw).decode("utf-8", "replace"))
        payload = json.dumps(response, indent=4,
                             default=str).encode() + b"\n"
        conn.sendall(struct.pack("!I", len(payload)) + payload)

    def _dispatch(self, request: str) -> Any:
        request = request.strip()
        try:
            cmd = json.loads(request) if request.startswith("{") else {
                "prefix": request}
        except json.JSONDecodeError:
            cmd = {"prefix": request}
        prefix = cmd.get("prefix", "")
        # longest-prefix match so "perf dump" beats "perf"
        best = ""
        for registered in self._handlers:
            if (prefix == registered or
                    prefix.startswith(registered + " ")) and \
                    len(registered) > len(best):
                best = registered
        if not best:
            return {"error": f"unknown command {prefix!r};"
                    " try 'help'"}
        # legacy form: "config get name" as a bare string
        tail = prefix[len(best):].strip()
        if tail and "var" not in cmd:
            parts = tail.split()
            cmd["var"] = parts[0]
            if len(parts) > 1:
                cmd["val"] = " ".join(parts[1:])
        try:
            return self._handlers[best][1](cmd)
        except KeyError as e:
            return {"error": f"missing/unknown field {e}"}
        except Exception as e:
            return {"error": str(e)}


def admin_socket_request(path: str, command: Any, timeout: float = 5.0
                         ) -> Any:
    """Client side (AdminSocketClient::do_request)."""
    payload = (json.dumps(command) if isinstance(command, dict)
               else str(command)).encode() + b"\0"
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(path)
        s.sendall(payload)
        header = b""
        while len(header) < 4:
            chunk = s.recv(4 - len(header))
            if not chunk:
                raise ConnectionError("short admin socket response header")
            header += chunk
        (length,) = struct.unpack("!I", header)
        body = b""
        while len(body) < length:
            chunk = s.recv(length - len(body))
            if not chunk:
                break
            body += chunk
    return json.loads(body)

"""Common runtime layer (the reference's src/common analog)."""

"""Performance counters.

Reference parity: PerfCounters
(/root/reference/src/common/perf_counters.h): typed counters built through
PerfCountersBuilder (u64 counters, time counters, averages with
count+sum, histograms), grouped per subsystem with an index range, held in
a PerfCountersCollection, and dumped as JSON by the admin socket's
`perf dump` / described by `perf schema`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

# perf counter types (perf_counters.h enum)
PERFCOUNTER_U64 = 1
PERFCOUNTER_TIME = 2
PERFCOUNTER_LONGRUNAVG = 4
PERFCOUNTER_COUNTER = 8
PERFCOUNTER_HISTOGRAM = 0x10


class _Counter:
    __slots__ = ("name", "type", "desc", "value", "count", "sum",
                 "histogram")

    def __init__(self, name: str, type_: int, desc: str,
                 histogram_bounds: Optional[List[float]] = None):
        self.name = name
        self.type = type_
        self.desc = desc
        self.value = 0
        self.count = 0
        self.sum = 0.0
        self.histogram = ([0] * (len(histogram_bounds) + 1)
                          if histogram_bounds is not None else None)


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, _Counter] = {}
        self._bounds: Dict[str, List[float]] = {}

    # -- build ------------------------------------------------------------

    def add_u64_counter(self, name: str, desc: str = "") -> None:
        self._counters[name] = _Counter(
            name, PERFCOUNTER_U64 | PERFCOUNTER_COUNTER, desc)

    def add_u64(self, name: str, desc: str = "") -> None:
        self._counters[name] = _Counter(name, PERFCOUNTER_U64, desc)

    def add_time_avg(self, name: str, desc: str = "") -> None:
        self._counters[name] = _Counter(
            name, PERFCOUNTER_TIME | PERFCOUNTER_LONGRUNAVG, desc)

    def add_histogram(self, name: str, bounds: List[float],
                      desc: str = "") -> None:
        self._counters[name] = _Counter(
            name, PERFCOUNTER_HISTOGRAM, desc, histogram_bounds=bounds)
        self._bounds[name] = list(bounds)

    # -- update -----------------------------------------------------------

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name].value += amount

    def dec(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name].value -= amount

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._counters[name].value = value

    def tinc(self, name: str, seconds: float) -> None:
        with self._lock:
            c = self._counters[name]
            c.count += 1
            c.sum += seconds

    def hinc(self, name: str, sample: float) -> None:
        with self._lock:
            c = self._counters[name]
            bounds = self._bounds[name]
            idx = len(bounds)
            for i, bound in enumerate(bounds):
                if sample <= bound:
                    idx = i
                    break
            c.histogram[idx] += 1
            c.count += 1
            c.sum += sample

    def time_it(self, name: str):
        """Context manager feeding a time_avg counter."""
        return _Timer(self, name)

    # -- read -------------------------------------------------------------

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters[name].value

    def avg(self, name: str) -> float:
        with self._lock:
            c = self._counters[name]
            return c.sum / c.count if c.count else 0.0

    def dump(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            for name, c in self._counters.items():
                if c.type & PERFCOUNTER_LONGRUNAVG:
                    out[name] = {"avgcount": c.count, "sum": c.sum,
                                 "avgtime": c.sum / c.count if c.count
                                 else 0.0}
                elif c.type & PERFCOUNTER_HISTOGRAM:
                    out[name] = {"count": c.count, "sum": c.sum,
                                 "buckets": list(c.histogram),
                                 "bounds": self._bounds[name]}
                else:
                    out[name] = c.value
        return out

    def schema(self) -> Dict[str, Any]:
        with self._lock:
            return {name: {"type": c.type, "description": c.desc}
                    for name, c in self._counters.items()}


class _Timer:
    def __init__(self, counters: PerfCounters, name: str):
        self._counters = counters
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._counters.tinc(self._name, time.perf_counter() - self._t0)
        return False


class PerfCountersCollection:
    """All of a process's PerfCounters; `perf dump` walks this."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loggers: Dict[str, PerfCounters] = {}

    def add(self, counters: PerfCounters) -> None:
        with self._lock:
            self._loggers[counters.name] = counters

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def get(self, name: str) -> Optional[PerfCounters]:
        with self._lock:
            return self._loggers.get(name)

    def dump(self, logger: str = "") -> Dict[str, Any]:
        with self._lock:
            loggers = dict(self._loggers)
        return {name: pc.dump() for name, pc in loggers.items()
                if not logger or name == logger}

    def schema(self) -> Dict[str, Any]:
        with self._lock:
            loggers = dict(self._loggers)
        return {name: pc.schema() for name, pc in loggers.items()}

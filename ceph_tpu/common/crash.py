"""Crash reporting (the src/ceph-crash.in + pybind/mgr/crash role).

A daemon that dies of an unhandled exception posts a structured
report to the monitors before exiting; reports are quorum-replicated,
listed/inspected/archived via `crash ls/info/archive/rm` commands,
and raise a RECENT_CRASH health warning until archived — the
reference's crash-dump-directory scanner collapsed into a direct
post (our daemons are python; the traceback IS the crash dump)."""

from __future__ import annotations

import time
import traceback
from typing import Any, Dict, Optional


def make_report(entity: str,
                exc: Optional[BaseException] = None) -> Dict[str, Any]:
    ts = time.time()
    rep: Dict[str, Any] = {
        "crash_id": f"{time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(ts))}"
                    f".{int(ts * 1e6) % 1000000:06d}_{entity}",
        "entity": entity,
        "timestamp": ts,
        "ceph_version": "ceph-tpu",
    }
    if exc is not None:
        rep["exception"] = repr(exc)
        rep["backtrace"] = traceback.format_exception(
            type(exc), exc, exc.__traceback__)
    return rep


async def post_crash(mon_addr: str, entity: str,
                     exc: Optional[BaseException] = None,
                     secret: Optional[str] = None) -> Optional[str]:
    """Best-effort post over a fresh mon connection (the dying
    daemon's own client state cannot be trusted).  Returns the crash
    id, or None if the monitors were unreachable."""
    from ceph_tpu.rados.client import RadosClient

    rep = make_report(entity, exc)
    client = RadosClient(mon_addr, name=f"crash.{entity}",
                         secret=secret)
    try:
        await client.connect()
        rc, _out = await client.mon_command(
            {"prefix": "crash post", "report": rep})
        return rep["crash_id"] if rc == 0 else None
    except Exception:
        return None  # never mask the original failure
    finally:
        try:
            await client.shutdown()
        except Exception:
            pass

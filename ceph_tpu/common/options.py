"""Typed option schema.

Reference parity: the Option registry
(/root/reference/src/common/options.cc — 1,649 typed `Option(...)`
definitions; schema in options.h): each option carries type, level,
default (optionally HDD/SSD variants), min/max, enum values, description,
see_also, and flags.  This module keeps the same schema and declares the
options this framework actually consumes; `ceph_tpu.common.config` layers
values over these defaults.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# levels (Option::level_t)
LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"

# flags (Option::flag_t)
FLAG_RUNTIME = 1 << 0       # may change at runtime
FLAG_STARTUP = 1 << 1       # only at daemon startup
FLAG_CREATE = 1 << 2        # only at cluster/daemon creation


@dataclass
class Option:
    name: str
    type: str                       # int | uint | float | bool | str | size | secs
    default: Any
    level: str = LEVEL_ADVANCED
    desc: str = ""
    long_desc: str = ""
    min: Optional[float] = None
    max: Optional[float] = None
    enum_values: Tuple[str, ...] = ()
    see_also: Tuple[str, ...] = ()
    flags: int = FLAG_RUNTIME
    daemon_default: Dict[str, Any] = field(default_factory=dict)

    _CASTS = {"int": int, "uint": int, "float": float, "size": int,
              "secs": float, "bool": None, "str": str}

    _SIZE_SUFFIXES = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30,
                      "t": 1 << 40, "p": 1 << 50}

    def cast(self, value: Any) -> Any:
        """Parse/validate a raw (usually string) value; raises ValueError."""
        if self.type == "size" and isinstance(value, str):
            s = value.strip().lower()
            if s.endswith("b"):
                s = s[:-1]
            if s.endswith("i"):
                s = s[:-1]
            if s and s[-1] in self._SIZE_SUFFIXES:
                try:
                    value = int(float(s[:-1]) * self._SIZE_SUFFIXES[s[-1]])
                except ValueError:
                    raise ValueError(
                        f"{self.name}: {value!r} is not a size")
            else:
                value = s  # bare number, possibly after stripping B
        if self.type == "bool":
            if isinstance(value, bool):
                out: Any = value
            elif str(value).lower() in ("true", "1", "yes", "on"):
                out = True
            elif str(value).lower() in ("false", "0", "no", "off"):
                out = False
            else:
                raise ValueError(f"{self.name}: {value!r} is not a bool")
        else:
            caster = self._CASTS.get(self.type)
            if caster is None:
                raise ValueError(f"{self.name}: unknown type {self.type}")
            try:
                out = caster(value)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{self.name}: {value!r} is not a {self.type}")
        if self.enum_values and out not in self.enum_values:
            raise ValueError(
                f"{self.name}: {out!r} not in {self.enum_values}")
        if self.min is not None and out < self.min:
            raise ValueError(f"{self.name}: {out} < min {self.min}")
        if self.max is not None and out > self.max:
            raise ValueError(f"{self.name}: {out} > max {self.max}")
        return out


def _opts() -> List[Option]:
    A, B, D = LEVEL_ADVANCED, LEVEL_BASIC, LEVEL_DEV
    return [
        # -- erasure code (options.cc:2662-2709) --------------------------
        Option("osd_pool_default_erasure_code_profile", "str",
               "plugin=jerasure technique=reed_sol_van k=2 m=2", A,
               desc="default erasure code profile"),
        Option("osd_pool_erasure_code_stripe_unit", "size", 4096, A,
               desc="chunk size for EC pools"),
        Option("osd_erasure_code_plugins", "str", "jerasure isa lrc", A,
               desc="EC plugins to preload", flags=FLAG_STARTUP),
        Option("erasure_code_dir", "str", "", A,
               desc="plugin directory (module path prefix here)",
               flags=FLAG_STARTUP),
        # -- compression / checksums (options.cc:4236-4311) ---------------
        Option("bluestore_compression_algorithm", "str", "snappy", A,
               enum_values=("", "snappy", "zlib", "zstd", "lz4", "brotli",
                            "none"),
               desc="default blob compressor"),
        Option("bluestore_compression_mode", "str", "none", A,
               enum_values=("none", "passive", "aggressive", "force"),
               desc="when to compress"),
        Option("bluestore_compression_required_ratio", "float", 0.875, A,
               min=0.0, max=1.0,
               desc="compressed size must be below this ratio of raw"),
        Option("bluestore_compression_min_blob_size", "size", 8192, A),
        Option("bluestore_compression_max_blob_size", "size", 65536, A),
        Option("bluestore_csum_type", "str", "crc32c", A,
               enum_values=("none", "crc32c", "crc32c_16", "crc32c_8",
                            "xxhash32", "xxhash64"),
               desc="per-block checksum algorithm"),
        Option("bluestore_csum_block_size", "size", 4096, D),
        # -- tpu dispatch --------------------------------------------------
        Option("tpu_ec_batch_stripes", "uint", 16, A,
               desc="stripes coalesced per EC device dispatch"),
        Option("tpu_min_dispatch_bytes", "size", 65536, A,
               desc="below this the host codec runs instead of the TPU"),
        # -- messenger / failure detection (options.cc:875-1108) ----------
        Option("ms_inject_socket_failures", "uint", 0, D,
               desc="inject a socket failure every Nth message"),
        Option("ms_inject_internal_delays", "float", 0.0, D),
        # -- wire compression (ms_osd_compress_mode family) ----------------
        Option("ms_compress_methods", "str", "", A,
               desc="csv of accepted wire compression methods, in"
                    " preference order (empty = off)"),
        Option("ms_compress_min_size", "size", 4096, A,
               desc="frames below this never compress"),
        Option("ms_compress_secure", "bool", False, A,
               desc="allow compression on AEAD-secured connections"
                    " (length side channel: off by default)"),
        Option("ms_dispatch_throttle_bytes", "size", 100 << 20, A),
        # -- elections (options.cc mon_election_*, ElectionLogic.h) --------
        Option("mon_election_default_strategy", "uint", 1, A,
               min=1, max=3,
               desc="1=classic (rank priority), 3=connectivity"
                    " (reachability-scored candidates)"),
        Option("mon_elector_ping_interval", "secs", 0.4, A,
               min=0.05, max=10.0,
               desc="mon-to-mon liveness probe period feeding the"
                    " connection tracker"),
        Option("mon_elector_score_halflife", "secs", 4.0, A,
               min=0.1, max=3600.0,
               desc="connectivity score decay half-life"),
        Option("mon_elector_ignore_propose_margin", "float", 0.05, A,
               min=0.0, max=1.0,
               desc="score difference below which rank breaks the tie"),
        Option("osd_heartbeat_interval", "secs", 6.0, A, min=0.1, max=60),
        Option("osd_heartbeat_grace", "secs", 20.0, A),
        Option("mon_osd_min_down_reporters", "uint", 2, A),
        Option("mon_osd_laggy_halflife", "secs", 3600.0, A),
        Option("mon_osd_laggy_weight", "float", 0.3, A, min=0.0, max=1.0),
        Option("mon_osd_adjust_heartbeat_grace", "bool", True, A),
        Option("heartbeat_inject_failure", "uint", 0, D),
        # -- hot-set tracking / read tier (HitSet.h + the tier agent;
        #    flat-substrate redesign: the tier caches DECODED objects
        #    on the primary, not a second pool) -----------------------
        Option("osd_tier_enable", "bool", True, A,
               desc="hot-set tracking + decoded-object read tier"
                    " (env kill switch: CEPH_TPU_TIER=0)",
               flags=FLAG_STARTUP),
        Option("osd_hit_set_count", "uint", 4, A, min=1, max=32,
               desc="hit sets per PG stack (open + archived)"),
        Option("osd_hit_set_period", "secs", 10.0, A,
               desc="seconds before the open hit set seals+rotates"),
        Option("osd_hit_set_target_size", "uint", 1024, A,
               desc="expected insertions per bloom hit set"),
        Option("osd_hit_set_bloom_fpp", "float", 0.05, A,
               min=0.0, max=0.5,
               desc="bloom hit-set false-positive probability"),
        Option("osd_hit_set_type", "str", "bloom", A,
               enum_values=("bloom", "explicit_hash"),
               desc="hit-set implementation"),
        Option("osd_tier_promote_min_recency", "uint", 2, A, min=1,
               desc="hit count across the stack before an EC object"
                    " is promoted into the decoded-object tier"),
        Option("osd_tier_cache_bytes", "size", 64 << 20, A,
               desc="decoded-object tier byte budget (LRU evicts"
                    " beyond it)"),
        Option("osd_tier_promote_max_inflight", "uint", 4, A, min=1,
               desc="concurrent agent promotions per daemon"),
        Option("osd_tier_promote_backoff", "secs", 5.0, A,
               desc="cool-down before re-attempting a failed"
                    " promotion of the same object"),
        # -- hedged reads (straggler-tolerant first-k sub-reads;
        #    osd/hedge.py — rateless/coded redundancy scheduling) ------
        Option("osd_hedge_enable", "bool", True, A,
               desc="hedged first-k EC sub-reads + per-peer latency"
                    " EWMAs (env kill switch: CEPH_TPU_HEDGE=0)",
               flags=FLAG_STARTUP),
        Option("osd_hedge_delta", "uint", 1, A, min=0, max=16,
               desc="speculative extra sub-reads beyond k in the"
                    " initial hedged fan-out (escalates by one while"
                    " the EWMA spread is high)",
               see_also=("osd_hedge_spread_escalate",)),
        Option("osd_hedge_ewma_alpha", "float", 0.25, A,
               min=0.01, max=1.0,
               desc="EWMA/EW-variance weight per sub-read RTT sample"),
        Option("osd_hedge_decay_halflife", "secs", 30.0, A,
               min=0.1, max=3600.0,
               desc="idle half-life decaying a peer's latency model"
                    " toward the prior — recovered OSDs re-earn trust"),
        Option("osd_hedge_rtt_prior_ms", "float", 10.0, A, min=0.0,
               desc="RTT prior (ms) for unsampled peers and the decay"
                    " target"),
        Option("osd_hedge_delay_floor_ms", "float", 2.0, A, min=0.0,
               desc="minimum straggler mark (ms) before a flight"
                    " recruits a spare sub-read"),
        Option("osd_hedge_delay_cap_ms", "float", 1000.0, A, min=1.0,
               desc="maximum straggler mark (ms) — bounds how long a"
                    " cold model waits before hedging"),
        Option("osd_hedge_spread_escalate", "float", 4.0, A, min=1.0,
               desc="max-p95/min-EWMA ratio across peers beyond which"
                    " the speculative Δ escalates by one"),
        # -- op queue / per-tenant QoS (mClockScheduler.h +
        #    osd_mclock_* family; tenant extension: client ops carry
        #    a tenant identity and schedule as `client.<tenant>`
        #    classes with their own dmClock triples, gated by a
        #    token-bucket admission stage before the queue) ----------
        Option("osd_op_queue", "str", "mclock_scheduler", A,
               enum_values=("mclock_scheduler", "wpq"),
               desc="op scheduling discipline", flags=FLAG_STARTUP),
        Option("osd_op_num_threads", "uint", 8, A, min=1,
               desc="max concurrent scheduler grants (the admit"
                    " gate's in-flight bound)"),
        Option("osd_scheduler_queue_depth", "uint", 1024, A, min=1,
               desc="per-class op queue bound; overflow follows"
                    " osd_scheduler_overflow"),
        Option("osd_scheduler_overflow", "str", "shed", A,
               enum_values=("shed", "block"),
               desc="bounded-queue overflow policy: shed=EBUSY the"
                    " caller, block=backpressure until the class"
                    " drains"),
        Option("osd_mclock_tenant_enable", "bool", True, A,
               desc="schedule tenant-tagged client ops as per-tenant"
                    " mClock classes (env kill switch:"
                    " CEPH_TPU_QOS=0)", flags=FLAG_STARTUP),
        Option("osd_mclock_tenant_reservation", "float", 0.0, A,
               min=0.0,
               desc="default per-tenant reservation (ops/s; 0 = no"
                    " floor)"),
        Option("osd_mclock_tenant_weight", "float", 1.0, A, min=0.01,
               desc="default per-tenant proportional-share weight"),
        Option("osd_mclock_tenant_limit", "float", 0.0, A, min=0.0,
               desc="default per-tenant limit (ops/s; 0 = unlimited)"
                    " — also the admission gate's bucket rate"),
        Option("osd_mclock_tenant_profiles", "str", "", A,
               desc="per-tenant overrides as JSON:"
                    ' {"<tenant>": [reservation, weight, limit]}'),
        Option("osd_mclock_admission_enable", "bool", True, A,
               desc="token-bucket admission gate ahead of the op"
                    " queue: over-limit tenants are delayed then shed"
                    " (EBUSY) before consuming execute-stage"
                    " resources"),
        Option("osd_mclock_admission_burst", "secs", 2.0, A, min=0.0,
               desc="bucket capacity in seconds' worth of the"
                    " tenant's limit rate"),
        Option("osd_mclock_admission_max_delay_ms", "float", 50.0, A,
               min=0.0,
               desc="max in-gate smoothing delay before an over-limit"
                    " op is shed instead"),
        # -- coded inference serving (ceph_tpu/inference) ------------------
        Option("osd_inference_error_budget", "float", 0.05, A,
               min=0.0, max=1e6,
               desc="default per-query relative error budget for"
                    " Fisher-fused approximate serving: an arrival"
                    " set whose structural error bound exceeds it"
                    " (or a caller demanding exactness) takes the"
                    " exact full-decode fallback"),
        # -- critical-path tracing (common/tracing.py: stage spans,
        #    head sampling for ring retention, tail-exemplar trees) ---
        Option("osd_trace_enable", "bool", True, A,
               desc="stage-span tracing + critical-path attribution"
                    " (env kill switch: CEPH_TPU_TRACE=0)",
               flags=FLAG_STARTUP),
        Option("osd_trace_sample_rate", "float", 1.0, A,
               min=0.0, max=1.0,
               desc="head-sampling probability that a locally-rooted"
                    " trace is retained in the dump_traces ring —"
                    " stage histograms and tail exemplars see every"
                    " op regardless"),
        # -- osd/pg --------------------------------------------------------
        Option("osd_pool_default_size", "uint", 3, B),
        Option("osd_pool_default_min_size", "uint", 0, A),
        Option("osd_pool_default_pg_num", "uint", 32, B),
        Option("osd_max_backfills", "uint", 1, A),
        Option("osd_recovery_max_active", "uint", 0, A),
        Option("osd_scrub_auto_repair", "bool", False, A),
        # -- logging -------------------------------------------------------
        Option("log_file", "str", "", B, flags=FLAG_STARTUP),
        Option("log_max_recent", "uint", 500, A),
        Option("debug_osd", "str", "1/5", A),
        Option("debug_ec", "str", "1/5", A),
        Option("debug_crush", "str", "1/5", A),
        Option("debug_compressor", "str", "1/5", A),
        Option("debug_ms", "str", "0/5", A),
        Option("debug_mon", "str", "1/5", A),
        Option("debug_bluestore", "str", "1/5", A),
        # -- admin socket --------------------------------------------------
        Option("admin_socket", "str", "", A, flags=FLAG_STARTUP,
               desc="path to the unix admin socket"),
    ]


OPTIONS: Dict[str, Option] = {o.name: o for o in _opts()}


def get_option(name: str) -> Option:
    return OPTIONS[name]

"""Device-health circuit breakers + the guarded dispatch choke point.

PRs 2-4 moved the data path's math (EC matmuls, fused CRC, hitset
hashing, CRUSH batch placement) onto the accelerator assuming every
XLA dispatch succeeds.  Production does not: runtimes wedge, transfers
hang, RESOURCE_EXHAUSTED fires under memory pressure.  Coded-
computation systems treat worker faults and stragglers as the normal
case and degrade by construction (arXiv:1804.10331, arXiv:2409.01420)
— this module gives the device tier the same discipline:

* **CircuitBreaker** — one per dispatch *family* (ec-encode,
  ec-decode, fused-crc, hitset-hash, crush-batch), the classic
  closed/open/half-open machine: tripped by consecutive failures OR a
  watchdog timeout, re-closed by a single half-open probe dispatch
  gated on exponential backoff with full jitter (fixed backoffs
  synchronize into thundering herds when a breaker trips
  cluster-wide).
* **device_call()** — THE choke point every device dispatch routes
  through.  It runs the call on a watchdog thread with a hard timeout
  (a wedged TPU cannot hang the event loop), classifies
  RESOURCE_EXHAUSTED separately (callers halve their batch and
  retry), records the outcome on the family's breaker, and NEVER
  raises — callers read the status and fall back to the bit-exact
  host path.
* **Per-device health** — every chip gets its own breaker family
  (``device:<id>``, threshold 1: a failed probe targeted the chip, the
  verdict is decisive).  Mesh dispatches pass ``devices=`` so the
  choke point records success on every participating chip's breaker;
  failures are attributed only by an actual probe — a dispatch whose
  family IS the chip's own breaker (plan._probe_devices).  Ordinary
  dispatch failures, single- or multi-chip, cannot be attributed here
  — the mesh layer (ec/plan.py) probes each participant individually
  and re-plans on the surviving set, so one sick chip shrinks the
  mesh instead of degrading the whole batch to host.
* **Host failure domains** — once the mesh spans hosts
  (parallel/multihost.py), the unit of loss is the HOST
  (arXiv:1804.10331's model): ``host:<id>`` breaker families hold a
  whole host's chips out together.  ``retire_host()`` is ONE breaker
  event — the host breaker trips once, ``device_degraded()`` reads
  every chip of a retired host as held out, and none of the chips'
  own threshold-1 breakers fire (no N-chip breaker storm).  The mesh
  layer re-keys plans on the survivor processes in one shrink.

* **Fault injection** — `CEPH_TPU_INJECT_DEVICE_FAIL` is read at the
  same choke point so tests and the thrasher can script device
  failure deterministically:

      1.0 / 0.25 / p=0.25   fail each dispatch with probability p
      next=N                fail the next N dispatches, then heal
      hang=MS               sleep MS milliseconds inside the dispatch
                            (drives the watchdog timeout)
      oom=K                 raise RESOURCE_EXHAUSTED when the dispatch
                            batch exceeds K (drives batch halving)
      sick=D                fail any dispatch whose `devices` include
                            device id D (drives the mesh-shrink path:
                            sick chip out, smaller mesh in)
      down_host=H           fail any dispatch whose `devices` include
                            a device of host H (parallel/multihost.py
                            topology — drives the host-loss shrink:
                            one host:<H> event, all its chips retired
                            together)

  Modes combine comma-separated (``p=0.3,hang=5``).  The env var is
  re-read on every dispatch, so flipping it mid-workload takes effect
  immediately.

Kill switch: CEPH_TPU_BREAKER=0 restores the raw pre-guard behavior
(dispatch runs inline, exceptions propagate, no injection).
"""

from __future__ import annotations

import os

from ceph_tpu.common import flags
import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ceph_tpu.common import tracing

__all__ = [
    "CLOSED", "OPEN", "HALF_OPEN", "FAMILIES",
    "CircuitBreaker", "DeviceFault", "InjectedResourceExhausted",
    "breaker", "degraded", "device_breaker", "device_call",
    "device_degraded", "device_stats", "enabled", "fault_events",
    "force_open_all", "host_breaker", "host_degraded", "host_stats",
    "injection", "is_resource_exhausted", "parse_injection",
    "perf_dump", "probe_raw", "reset_all", "retire_host",
    "stats_all",
]

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# the dispatch families the device tier runs; breakers are created on
# demand so new families cost one registry entry, not a code change
FAMILIES = ("ec-encode", "ec-decode", "fused-crc", "hitset-hash",
            "crush-batch")

# per-chip breaker families ride the same registry under this prefix;
# they are created with fail_threshold 1 — a failed dispatch PINNED to
# one chip (the mesh layer's attribution probe) is a decisive verdict,
# unlike a family failure that might be a transient of any layer
DEVICE_FAMILY_PREFIX = "device:"

# per-HOST breaker families (parallel/multihost.py failure domains):
# losing a host is ONE event on its host:<id> breaker — all its chips
# read degraded through it, none of their own breakers trip
HOST_FAMILY_PREFIX = "host:"


def enabled() -> bool:
    return flags.enabled("CEPH_TPU_BREAKER")


def _env_float(name: str, default: float) -> float:
    try:
        return flags.flag_float(name, default)
    except ValueError:
        return default


class DeviceFault(RuntimeError):
    """Injected (or classified) device dispatch failure."""


class InjectedResourceExhausted(RuntimeError):
    """Injected OOM; the message carries RESOURCE_EXHAUSTED so the
    generic classifier treats it exactly like the real XLA error."""


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for XLA/PJRT allocation failures (and their injected
    twin): the class of error batch halving can actually fix."""
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "Resource exhausted" in text
            or "out of memory" in text.lower())


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Closed/open/half-open breaker for one dispatch family.

    closed    dispatches flow; `fail_threshold` CONSECUTIVE failures
              (or one watchdog timeout — a wedged runtime must not
              need three straight hangs) trip it open.
    open      dispatches are refused (callers take the host path)
              until the backoff expires; the backoff doubles per trip
              with full jitter, capped at `max_backoff`.
    half_open exactly ONE probe dispatch is admitted; success
              re-closes the breaker (and resets the backoff), failure
              re-opens it with the next backoff step.  Concurrent
              callers while the probe is in flight are refused.
    """

    __slots__ = ("family", "fail_threshold", "base_backoff",
                 "max_backoff", "_clock", "_rng", "_lock", "_state",
                 "_retry_at", "_opens", "_probing", "counters")

    def __init__(self, family: str, fail_threshold: int = None,
                 base_backoff: float = None, max_backoff: float = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Callable[[], float] = random.random):
        self.family = family
        self.fail_threshold = int(
            fail_threshold if fail_threshold is not None
            else _env_float("CEPH_TPU_BREAKER_THRESHOLD", 3))
        self.base_backoff = float(
            base_backoff if base_backoff is not None
            else _env_float("CEPH_TPU_BREAKER_BACKOFF_S", 0.5))
        self.max_backoff = float(
            max_backoff if max_backoff is not None
            else _env_float("CEPH_TPU_BREAKER_BACKOFF_MAX_S", 30.0))
        self._clock = clock
        self._rng = rng
        self._lock = threading.Lock()
        self._state = CLOSED
        self._retry_at = 0.0
        self._opens = 0          # consecutive opens: the backoff exponent
        self._probing = False
        self.counters: Dict[str, int] = {
            "successes": 0, "failures": 0, "consecutive": 0,
            "trips": 0, "probes": 0, "recoveries": 0, "fallbacks": 0,
            "watchdog_timeouts": 0,
        }

    # -- state machine -----------------------------------------------------

    def allow(self) -> bool:
        """Admission check — MUTATING: an open breaker whose backoff
        expired transitions to half-open and hands THIS caller the
        probe slot.  Use `degraded()` for a read-only peek."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN and now >= self._retry_at:
                self._state = HALF_OPEN
                self._probing = True
                self.counters["probes"] += 1
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                self.counters["probes"] += 1
                return True
            return False

    def degraded(self) -> bool:
        """Read-only: True while dispatches would be refused (open
        with an unexpired backoff, or a probe already in flight)."""
        with self._lock:
            if self._state == CLOSED:
                return False
            if self._state == HALF_OPEN:
                return self._probing
            return self._clock() < self._retry_at

    def record_success(self) -> None:
        with self._lock:
            self.counters["successes"] += 1
            self.counters["consecutive"] = 0
            if self._state != CLOSED:
                self.counters["recoveries"] += 1
            self._state = CLOSED
            self._probing = False
            self._opens = 0

    def record_failure(self, timeout: bool = False) -> None:
        with self._lock:
            self.counters["failures"] += 1
            self.counters["consecutive"] += 1
            if timeout:
                self.counters["watchdog_timeouts"] += 1
            if self._state == HALF_OPEN:
                self._trip_locked()       # failed probe: back off more
            elif self._state == CLOSED and (
                    timeout
                    or self.counters["consecutive"]
                    >= self.fail_threshold):
                self._trip_locked()

    def note_fallback(self) -> None:
        with self._lock:
            self.counters["fallbacks"] += 1

    def _trip_locked(self) -> None:
        self.counters["trips"] += 1
        self._state = OPEN
        self._probing = False
        self._opens += 1
        # full jitter (AWS style): U(0, min(cap, base * 2^(opens-1))).
        # Uniform-from-zero is deliberate — a fleet of breakers tripped
        # by one cluster-wide event must not probe in lockstep.
        ceiling = min(self.max_backoff,
                      self.base_backoff * (2 ** (self._opens - 1)))
        self._retry_at = self._clock() + self._rng() * ceiling

    # -- admin -------------------------------------------------------------

    def force_open(self, duration: Optional[float] = None) -> None:
        """Admin/bench lever: hold the breaker open (host path) for
        `duration` seconds (default max_backoff)."""
        with self._lock:
            self._state = OPEN
            self._probing = False
            self._opens += 1
            self.counters["trips"] += 1
            self._retry_at = self._clock() + (
                duration if duration is not None else self.max_backoff)

    def force_probe(self) -> None:
        """Expire the backoff: the next allow() is the probe."""
        with self._lock:
            if self._state == OPEN:
                self._retry_at = self._clock()
            self._probing = False

    def release_probe(self) -> None:
        """Give the half-open probe slot back WITHOUT a verdict: the
        probe dispatch ended in an outcome that says nothing about
        device health (OOM to be batch-halved, a benign
        NotImplementedError).  Without this the slot would leak and
        the breaker wedge in half_open forever."""
        with self._lock:
            self._probing = False

    def absolve(self) -> None:
        """Rescind a failure verdict that was ATTRIBUTED elsewhere:
        the mesh layer probed the participants of a failed multi-chip
        dispatch and found a sick chip — the chip's own breaker now
        owns the fault, so this family must not stay tripped (an open
        family breaker would degrade every caller to host, exactly
        what the mesh shrink exists to avoid).  Re-closes, clears the
        consecutive count and the backoff escalation; lifetime
        failure/trip counters are kept (they happened)."""
        with self._lock:
            self._state = CLOSED
            self._probing = False
            self._opens = 0
            self._retry_at = 0.0
            self.counters["consecutive"] = 0

    def reset(self, counters: bool = True) -> None:
        with self._lock:
            self._state = CLOSED
            self._probing = False
            self._opens = 0
            self._retry_at = 0.0
            if counters:
                for k in self.counters:
                    self.counters[k] = 0

    # -- observability -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            now = self._clock()
            return {
                "state": self._state,
                "state_code": _STATE_CODE[self._state],
                "retry_in_s": round(max(self._retry_at - now, 0.0), 3)
                if self._state == OPEN else 0.0,
                **self.counters,
            }


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_breakers: Dict[str, CircuitBreaker] = {}


def breaker(family: str) -> CircuitBreaker:
    with _reg_lock:
        br = _breakers.get(family)
        if br is None:
            kw = {}
            if family.startswith((DEVICE_FAMILY_PREFIX,
                                  HOST_FAMILY_PREFIX)):
                kw["fail_threshold"] = int(_env_float(
                    "CEPH_TPU_DEVICE_BREAKER_THRESHOLD", 1))
            br = _breakers[family] = CircuitBreaker(family, **kw)
        return br


def device_breaker(device_id: int) -> CircuitBreaker:
    """The per-chip breaker: family ``device:<id>`` in the shared
    registry (threshold 1 — attribution probes are decisive)."""
    return breaker(f"{DEVICE_FAMILY_PREFIX}{int(device_id)}")


def device_degraded(device_id: int) -> bool:
    """Read-only per-chip health: True while the chip is held out of
    the mesh — its own breaker open with an unexpired backoff, OR its
    HOST's ``host:<id>`` breaker open (a retired host holds all its
    chips out through ONE breaker; the chips' own breakers never
    fire).  An expired backoff reads healthy — the chip rejoins the
    next mesh build, and that dispatch is its de-facto half-open
    probe."""
    if not enabled():
        return False
    if device_breaker(device_id).degraded():
        return True
    if not _host_families_used:
        # no host:<id> breaker exists anywhere: skip the topology
        # lookup entirely (the single-host hot path pays nothing,
        # and a read must never CREATE a phantom host family)
        return False
    return host_degraded(_host_of(device_id))


def _host_of(device_id: int) -> int:
    """Device -> host failure domain; 0 (the trivial domain) when the
    topology layer is absent.  Lazy import: circuit is a leaf module
    the parallel package builds on."""
    try:
        from ceph_tpu.parallel import multihost

        if multihost.host_count() <= 1:
            return 0
        return multihost.host_of_id(device_id)
    except Exception:  # pragma: no cover - topology layer unavailable
        return 0


# flipped the first time any host:<id> family is created: the
# device_call success path only pays the host-mapping cost once host
# failure domains are actually in play
_host_families_used = False


def host_breaker(host_id: int) -> CircuitBreaker:
    """The per-host breaker: family ``host:<id>`` in the shared
    registry (threshold 1 — host loss is a decisive, single event)."""
    global _host_families_used
    _host_families_used = True
    return breaker(f"{HOST_FAMILY_PREFIX}{int(host_id)}")


def host_degraded(host_id: int) -> bool:
    """Read-only host health: True while every chip of the host is
    held out (its host breaker open with an unexpired backoff).
    Reads never create a family — a host nobody retired has no
    breaker and is simply healthy."""
    if not enabled():
        return False
    with _reg_lock:
        br = _breakers.get(f"{HOST_FAMILY_PREFIX}{int(host_id)}")
    return br is not None and br.degraded()


def retire_host(host_id: int,
                duration: Optional[float] = None) -> None:
    """Losing a host is ONE event: trip its ``host:<id>`` breaker
    once.  All the host's chips read degraded through it (the healthy
    set drops them together in one mesh rebuild) and none of their
    own threshold-1 breakers fire — retiring an 8-chip host is one
    breaker trip, not an 8-chip breaker storm."""
    host_breaker(host_id).force_open(duration)
    tracing.event(f"host {host_id} retired (one event: all chips"
                  " held out together)")


def host_stats() -> Dict[str, Dict[str, Any]]:
    """Per-host breaker snapshot keyed by host id (string, for the
    prometheus label map) — the `hosts` twin of device_stats()."""
    with _reg_lock:
        brs = {f[len(HOST_FAMILY_PREFIX):]: br
               for f, br in _breakers.items()
               if f.startswith(HOST_FAMILY_PREFIX)}
    return {h: br.stats()
            for h, br in sorted(brs.items(), key=lambda kv: kv[0])}


def device_stats() -> Dict[str, Dict[str, Any]]:
    """Per-chip breaker snapshot keyed by device id (string, for the
    prometheus label map); `dispatches` aliases the success count —
    the satellite gauge ceph_osd_device_*{device=...} reads it."""
    with _reg_lock:
        brs = {f[len(DEVICE_FAMILY_PREFIX):]: br
               for f, br in _breakers.items()
               if f.startswith(DEVICE_FAMILY_PREFIX)}
    out = {}
    for dev, br in sorted(brs.items(), key=lambda kv: kv[0]):
        st = br.stats()
        st["dispatches"] = st["successes"]
        out[dev] = st
    return out


def degraded(family: str) -> bool:
    """Read-only pre-filter for dispatch routers: True while the
    family's device path would be refused (skip straight to host
    without consuming the half-open probe slot)."""
    if not enabled():
        return False
    return breaker(family).degraded()


def stats_all() -> Dict[str, Dict[str, Any]]:
    with _reg_lock:
        brs = dict(_breakers)
    out = {f: brs[f].stats() for f in sorted(brs)}
    for f in FAMILIES:              # always-present rows for dashboards
        out.setdefault(f, CircuitBreaker(f).stats())
    return out


def perf_dump() -> Dict[str, Dict[str, Any]]:
    """Numeric-only nested snapshot for `perf dump` (the prometheus
    flattener skips string leaves, so the state rides as state_code).
    Per-chip ``device:<id>`` and per-host ``host:<id>`` families are
    excluded here — the daemon exports them under `devices`/`hosts`
    label maps instead, so chips and hosts become ``device=``/
    ``host=`` labels rather than a metric name per unit."""
    return {f: {k: v for k, v in st.items() if not isinstance(v, str)}
            for f, st in stats_all().items()
            if not f.startswith((DEVICE_FAMILY_PREFIX,
                                 HOST_FAMILY_PREFIX))}


def fault_events(families: Optional[Tuple[str, ...]] = None) -> int:
    """Monotone total of failures + fallbacks + timeouts — a cheap
    'did the device tier degrade during this span' delta signal (the
    encode service's device_fallback accounting).  Pass `families` to
    scope the sum; unscoped deltas would attribute a concurrent fault
    in an unrelated family (hitset hashing, CRUSH) to the caller."""
    with _reg_lock:
        brs = [br for f, br in _breakers.items()
               if families is None or f in families]
    total = 0
    for br in brs:
        c = br.counters
        total += c["failures"] + c["fallbacks"] + c["watchdog_timeouts"]
    return total


def reset_all(counters: bool = True) -> None:
    with _reg_lock:
        brs = list(_breakers.values())
    for br in brs:
        br.reset(counters=counters)


def force_open_all(duration: Optional[float] = None) -> None:
    for f in FAMILIES:
        breaker(f).force_open(duration)


# ---------------------------------------------------------------------------
# Fault injection (the scripted seam)
# ---------------------------------------------------------------------------

_inj_lock = threading.Lock()
_inj_raw: Optional[str] = None
_inj_spec: Optional[Dict[str, Any]] = None
_inj_next_left = 0


def parse_injection(raw: Optional[str]) -> Optional[Dict[str, Any]]:
    """CEPH_TPU_INJECT_DEVICE_FAIL spec -> {p, next, hang_ms,
    oom_batch, sick_device} or None when injection is off.  A bare
    float is shorthand for p=<float>; unknown keys raise (a typo'd
    fault spec silently injecting nothing would invalidate the
    test)."""
    raw = (raw or "").strip()
    if not raw or raw == "0":
        return None
    spec: Dict[str, Any] = {"p": 0.0, "next": 0, "hang_ms": 0.0,
                            "oom_batch": None, "sick_device": None,
                            "down_host": None}
    try:
        spec["p"] = float(raw)
        return spec
    except ValueError:
        pass
    for part in raw.split(","):
        key, _, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if key in ("p", "prob"):
            spec["p"] = float(val)
        elif key in ("next", "fail_next", "fail-next"):
            spec["next"] = int(val)
        elif key in ("hang", "hang_ms", "hang-ms"):
            spec["hang_ms"] = float(val)
        elif key in ("oom", "oom_batch", "oom-above-batch"):
            spec["oom_batch"] = int(val)
        elif key in ("sick", "sick_device", "sick-device"):
            spec["sick_device"] = int(val)
        elif key in ("down_host", "down-host", "host"):
            spec["down_host"] = int(val)
        else:
            raise ValueError(
                f"unknown CEPH_TPU_INJECT_DEVICE_FAIL mode {part!r}")
    return spec


def injection() -> Optional[Dict[str, Any]]:
    """Current injection spec; the env var is re-read every call so
    flipping it mid-workload takes effect on the next dispatch."""
    global _inj_raw, _inj_spec, _inj_next_left
    raw = flags.get("CEPH_TPU_INJECT_DEVICE_FAIL") or ""
    with _inj_lock:
        if raw != _inj_raw:
            _inj_raw = raw
            _inj_spec = parse_injection(raw)
            _inj_next_left = _inj_spec["next"] if _inj_spec else 0
        return _inj_spec


def _maybe_inject(family: str, batch: Optional[int],
                  devices: Optional[Tuple[int, ...]] = None) -> None:
    """Runs INSIDE the watchdog-supervised dispatch body, so hang
    injection exercises the real timeout path."""
    global _inj_next_left
    spec = injection()
    if spec is None:
        return
    if spec["hang_ms"]:
        time.sleep(spec["hang_ms"] / 1e3)
    if spec["sick_device"] is not None and devices \
            and spec["sick_device"] in devices:
        raise DeviceFault(
            f"injected device fault ({family}: sick device"
            f" {spec['sick_device']} in dispatch set {devices})")
    if spec["down_host"] is not None and devices \
            and any(_host_of(d) == spec["down_host"]
                    for d in devices):
        raise DeviceFault(
            f"injected host loss ({family}: host"
            f" {spec['down_host']} down, dispatch set {devices})")
    if spec["oom_batch"] is not None and batch is not None \
            and batch > spec["oom_batch"]:
        raise InjectedResourceExhausted(
            f"RESOURCE_EXHAUSTED (injected: {family} batch {batch} >"
            f" {spec['oom_batch']})")
    fire = False
    if spec["next"]:
        with _inj_lock:
            if _inj_next_left > 0:
                _inj_next_left -= 1
                fire = True
    if fire:
        raise DeviceFault(f"injected device fault ({family}:"
                          " fail-next)")
    if spec["p"] and random.random() < spec["p"]:
        raise DeviceFault(f"injected device fault ({family}:"
                          f" p={spec['p']})")


# ---------------------------------------------------------------------------
# device_call: the guarded dispatch choke point
# ---------------------------------------------------------------------------


def _default_timeout() -> float:
    return _env_float("CEPH_TPU_DEVICE_TIMEOUT_S", 120.0)


class _Worker:
    """One reusable watchdog thread: dispatches are handed over on a
    semaphore instead of paying a Thread spawn per device call (the
    guard sits on the OSD write hot path).  A worker whose dispatch
    wedges past the timeout is ABANDONED — never recycled — so the
    runaway body can finish (or hang forever) without ever touching a
    later dispatch's result slot."""

    __slots__ = ("_sem", "_task")

    def __init__(self) -> None:
        self._sem = threading.Semaphore(0)
        self._task: Optional[tuple] = None
        t = threading.Thread(target=self._loop, daemon=True,
                             name="devcall-worker")
        t.start()

    def _loop(self) -> None:
        while True:
            self._sem.acquire()
            fn, box, done = self._task  # type: ignore[misc]
            self._task = None
            try:
                box["out"] = fn()
            except BaseException as e:  # classified by device_call
                box["err"] = e
            done.set()

    def submit(self, fn: Callable) -> Tuple[dict, threading.Event]:
        box: dict = {}
        done = threading.Event()
        self._task = (fn, box, done)
        self._sem.release()
        return box, done


_pool_lock = threading.Lock()
_idle_workers: list = []


def _run_watchdog(fn: Callable, timeout: float
                  ) -> Tuple[bool, dict]:
    """Run fn on a (pooled) watchdog thread; (finished, box)."""
    with _pool_lock:
        worker = _idle_workers.pop() if _idle_workers else None
    if worker is None:
        worker = _Worker()
    box, done = worker.submit(fn)
    if done.wait(timeout):
        with _pool_lock:
            _idle_workers.append(worker)
        return True, box
    return False, box   # worker abandoned with its wedged dispatch


def probe_raw(family: str, fn: Callable,
              devices: Optional[Tuple[int, ...]] = None,
              timeout: Optional[float] = None) -> bool:
    """Run one attribution probe with the watchdog and the injection
    seam but NO breaker verdict: the host-aware mesh attribution
    (ec/plan.py) aggregates raw per-chip results first — a whole
    host's chips failing must become ONE host:<id> event, not N
    device-breaker trips — and only then records where the fault
    actually lives.  Returns True when the probe body succeeded."""
    if not enabled():
        try:
            fn()
            return True
        except Exception:
            return False

    def _body():
        _maybe_inject(family, 1, devices)
        return fn()

    finished, box = _run_watchdog(
        _body, timeout if timeout is not None else _default_timeout())
    return finished and box.get("err") is None


def device_call(family: str, fn: Callable, *args,
                batch: Optional[int] = None, label: str = "",
                timeout: Optional[float] = None,
                oom_to_fail: bool = False,
                benign: Tuple[type, ...] = (),
                devices: Optional[Tuple[int, ...]] = None,
                ) -> Tuple[str, Any]:
    """Run one device dispatch through the family's breaker, the
    injection seam, and a watchdog thread.  NEVER raises; returns
    (status, value):

      ("ok", result)       dispatched; breaker recorded a success
      ("open", None)       breaker refused (host path, no dispatch)
      ("oom", exc)         RESOURCE_EXHAUSTED: halve the batch and
                           retry (breaker untouched — capacity, not
                           health); pass oom_to_fail=True at the
                           single-stripe floor to record it as a
                           real failure instead
      ("benign", exc)      exception in `benign`: no breaker impact
                           (e.g. NotImplementedError from an
                           unsupported CRUSH rule)
      ("timeout", None)    watchdog fired: breaker trips immediately
                           (the runaway dispatch is abandoned on its
                           daemon thread)
      ("fail", exc)        dispatch raised: breaker failure recorded

    `devices` names the chips participating in a mesh dispatch (jax
    device ids): success records on every chip's ``device:<id>``
    breaker.  Failures are NEVER attributed here — a failed
    multi-chip dispatch says nothing about which chip, and a failed
    ordinary single-chip dispatch must not trip the chip's
    threshold-1 breaker on a transient the family breaker would
    tolerate.  Only an actual attribution probe (whose `family` IS
    the chip's ``device:<id>`` breaker — plan._probe_devices) speaks
    for a chip's failure.  The sick-device injection mode keys on
    this set.

    With CEPH_TPU_BREAKER=0 the guard is bypassed entirely: fn runs
    inline and exceptions propagate raw (pre-guard behavior).
    """
    if not enabled():
        return "ok", fn(*args)
    br = breaker(family)
    if not br.allow():
        br.note_fallback()
        tracing.event(f"circuit {family} open (host fallback)")
        return "open", None
    # chips whose breaker this call may speak for — when the family
    # itself IS a device:<id> breaker, skip that id (one verdict, not
    # two, per dispatch)
    attr = tuple(d for d in (devices or ())
                 if family != f"{DEVICE_FAMILY_PREFIX}{d}")

    def _body():
        _maybe_inject(family, batch, devices)
        return fn(*args)

    finished, box = _run_watchdog(
        _body, timeout if timeout is not None else _default_timeout())
    if not finished:
        br.record_failure(timeout=True)
        tracing.event(f"circuit {family} watchdog timeout")
        return "timeout", None
    err = box.get("err")
    if err is None:
        br.record_success()
        for d in attr:
            device_breaker(d).record_success()
        if attr and _host_families_used:
            # a successful dispatch touching a previously-retired
            # host's chips is the host's de-facto half-open probe:
            # its breaker re-closes (the chips rejoined when the
            # backoff expired; the host verdict must follow them)
            for h in {_host_of(d) for d in attr}:
                hb = host_breaker(h)
                if hb.state != CLOSED:
                    hb.record_success()
        return "ok", box.get("out")
    if isinstance(err, benign):
        # no health verdict: hand a half-open probe slot back so the
        # breaker cannot wedge in half_open on a benign outcome
        br.release_probe()
        return "benign", err
    if is_resource_exhausted(err) and not oom_to_fail:
        br.release_probe()
        tracing.event(f"circuit {family} oom (batch {batch})")
        return "oom", err
    br.record_failure()
    tracing.event(f"circuit {family} dispatch failed")
    return "fail", err

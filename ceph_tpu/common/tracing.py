"""Distributed tracing spans (the blkin/zipkin role) + critical-path
attribution.

Reference parity: /root/reference/src/blkin/ + the OSD/Messenger
tracepoints behind `osd_blkin_trace_all` — a client op carries a trace
context across the wire; every daemon it touches contributes spans
(parent-linked, timestamped, annotated) so one request's journey
(client -> primary -> replica sub-ops) reconstructs as a tree.  The
reference emits LTTng events consumed by an external zipkin collector;
this build keeps spans IN the daemons (bounded ring per Tracer) and
exposes them over the admin-socket/tell surface (`dump_traces`), which
fits the single-binary deployment the way the asok perf dump does.

Propagation: a (trace_id, span_id) pair rides in MOSDOp / MOSDSubWrite
/ MOSDSubRead / MOSDSubCompute (versioned tail fields — untraced
peers skip them).

Stage names are a span's first whitespace token (`stage_of`): the
pipeline seams emit `admission`, `queue.<class>`, `objlock`,
`encode_wait`/`encode_flush`, `subread osd.N` / `subwrite osd.N`,
`kv_commit_wait`/`fsync`, and the coded-compute workload adds
`compute_op` (the scan op root), `subcompute osd.N` (per-peer
hedged sub-compute flights) and `compute ...` (kernel evaluation /
result-domain decode) — each workload class gets its own rows in
the stage histograms.
Inside a daemon the active span travels by contextvar, so nested sends
(the primary's sub-ops fanned out under the op task) attach the right
parent without threading a span through every call signature.

Clock discipline: every DURATION comes from `time.monotonic()` — an
NTP step mid-span must not corrupt latencies — while each span keeps
ONE wall-clock anchor (`start`) captured at creation for display.
Events record monotonic offsets from the span start.

Critical-path analysis: `critical_path(spans)` walks a finished span
tree backward from the root's end and attributes every instant of the
op's wall time to exactly one span — the LATEST-ENDING overlapping
child owns its interval (recursively), the gaps are the parent's
self-time.  Children annotated `cancelled` (hedged stragglers cut
loose at early completion) are real work but NOT on the path: the op
never waited for them.  Per-stage self-times aggregate into bounded
log-bucket streaming histograms (loadgen/stats.py LatencyHistogram),
surfaced as the `trace` perf-dump section and prometheus
`ceph_osd_trace_stage_*` rows.

Sampling: head-based for the bulk — a locally-rooted trace is RETAINED
in the ring with probability `sample_rate`; a trace arriving with a
wire context inherits its parent's (already made) decision.  Retention
is separate from existence: spans are still built for unsampled ops so
the per-stage histograms see every op and the TAIL can keep its full
tree (the OpTracker exemplar ring) even at sample rate 0.

Kill switch: CEPH_TPU_TRACE=0 (env, re-read per trace) or constructing
the Tracer with enabled=False makes `start()` return the NULL_SPAN
singleton — every downstream annotation is a no-op attribute lookup.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import random
import os

from ceph_tpu.common import flags
import secrets
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "NULL_SPAN", "Span", "Tracer", "child_span", "child_span_sync",
    "critical_path", "critical_path_spans", "current_span",
    "env_enabled", "event", "stage_of", "start_child",
]

# the span the running task is working under (primary op execution
# sets it; sub-op sends read it) — context propagates per asyncio task
current_span: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("ceph_tpu_current_span", default=None)

#: per-trace span-tree bound: a runaway fan-out must not turn one op's
#: trace into an unbounded buffer (overflow spans are counted, dropped)
TREE_CAP = 512


def env_enabled() -> bool:
    return flags.enabled("CEPH_TPU_TRACE")


# span/trace ids need uniqueness, not unpredictability — a PRNG
# seeded once from the CSPRNG is an order of magnitude cheaper per id
# than os.urandom, and ids are minted on the op hot path
_rand = random.Random(secrets.randbits(64))


def _id64() -> int:
    return _rand.getrandbits(63) | 1  # nonzero


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "service", "start", "end", "events", "attrs",
                 "links", "sampled", "_t0", "_end", "_tree",
                 "_dropped")

    def __init__(self, trace_id: int, span_id: int, parent_id: int,
                 name: str, service: str, sampled: bool = True,
                 tree: Optional[list] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = service
        # wall-clock anchor (display only): one syscall per trace —
        # children derive theirs from the root's in child()
        self.start = time.time() if tree is None else 0.0
        self._t0 = time.monotonic()     # duration source
        self._end: Optional[float] = None
        self.end: Optional[float] = None  # wall end (display only)
        # events / links allocate lazily: most spans on the hot path
        # carry neither, and three empty containers per span add up
        self.events: Optional[List[Tuple[float, str]]] = None
        self.attrs: Dict[str, Any] = {}
        # span links: contexts this span SERVED without parenting them
        # (one batched device dispatch serving N ops' encodes)
        self.links: Optional[List[Tuple[int, int]]] = None
        self.sampled = sampled
        # the local trace buffer, owned by the local root and shared
        # by every descendant created through child()
        self._tree: list = tree if tree is not None else [self]
        self._dropped = 0

    def __bool__(self) -> bool:
        return True

    def event(self, what: str) -> None:
        if self.events is None:
            self.events = []
        self.events.append((time.monotonic() - self._t0, what))

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def link(self, context: Optional[Tuple[int, int]]) -> None:
        if context is not None:
            if self.links is None:
                self.links = []
            self.links.append((int(context[0]), int(context[1])))

    def child(self, name: str, **attrs: Any) -> "Span":
        """A child span in the same local tree (bounded): the in-daemon
        complement of start(context=...) for spans that never cross
        the wire."""
        sp = Span(self.trace_id, _id64(), self.span_id, name,
                  self.service, sampled=self.sampled, tree=self._tree)
        root = self._tree[0]
        # derive the wall anchor from the root's (one time.time() per
        # TRACE, not per span — children are on the op hot path)
        sp.start = root.start + (sp._t0 - root._t0)
        if attrs:
            sp.attrs.update(attrs)
        if len(self._tree) < TREE_CAP:
            self._tree.append(sp)
        else:
            root._dropped += 1
        return sp

    def finish(self) -> None:
        if self._end is None:
            self._end = time.monotonic()
            self.end = self.start + (self._end - self._t0)

    @property
    def duration_s(self) -> float:
        return (self._end if self._end is not None
                else time.monotonic()) - self._t0

    @property
    def context(self) -> Optional[Tuple[int, int]]:
        """What goes on the wire: (trace_id, my span id)."""
        return (self.trace_id, self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        root = self._tree[0]
        out = {"trace_id": f"{self.trace_id:016x}",
               "span_id": f"{self.span_id:016x}",
               "parent_id": f"{self.parent_id:016x}"
                            if self.parent_id else "",
               "name": self.name, "service": self.service,
               "start": self.start,
               # offset from the local root's start: what the
               # critical-path reducer orders by (monotonic-derived,
               # NTP-step immune)
               "t0_us": int((self._t0 - root._t0) * 1e6),
               "duration_us": int(self.duration_s * 1e6),
               "events": [{"t": self.start + dt,
                           "offset_us": int(dt * 1e6), "what": w}
                          for dt, w in (self.events or ())]}
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.links:
            out["links"] = [f"{t:016x}/{s:016x}" for t, s in self.links]
        return out

    def tree_dicts(self) -> List[Dict[str, Any]]:
        """The local span tree (roots only own one), dict-rendered."""
        out = [sp.to_dict() for sp in self._tree]
        if self._dropped:
            out[0].setdefault("attrs", {})["dropped_spans"] = \
                self._dropped
        return out


class _NullSpan:
    """The disabled-tracing twin: every annotation is a no-op, the
    wire context is None (nothing propagates), bool() is False so
    call sites can gate on `if span:`."""

    __slots__ = ()
    trace_id = 0
    span_id = 0
    parent_id = 0
    name = ""
    service = ""
    sampled = False
    start = 0.0
    end = None
    events: list = []
    attrs: dict = {}
    links: list = []
    duration_s = 0.0
    context = None

    def __bool__(self) -> bool:
        return False

    def event(self, what: str) -> None:
        pass

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def link(self, context) -> None:
        pass

    def child(self, name: str, **attrs: Any) -> "_NullSpan":
        return self

    def finish(self) -> None:
        pass

    def to_dict(self) -> Dict[str, Any]:
        return {}

    def tree_dicts(self) -> List[Dict[str, Any]]:
        return []


NULL_SPAN = _NullSpan()


def start_child(name: str, **attrs: Any):
    """Child of the task's current span, or NULL_SPAN when untraced.
    Caller owns finish() — prefer child_span()/child_span_sync() which
    finish on every path."""
    parent = current_span.get()
    if parent is None or not parent:
        return NULL_SPAN
    return parent.child(name, **attrs)


def event(what: str) -> None:
    """Annotate the current span (no-op when untraced): the cheap
    seam for leaf layers (tier hit/miss, breaker outcomes) that must
    not depend on a Tracer."""
    span = current_span.get()
    if span is not None:
        span.event(what)


@contextlib.asynccontextmanager
async def child_span(name: str, **attrs: Any):
    """Async stage-span helper: child of the current span, installed
    as current for the body, finished on EVERY path.  Cancellation is
    annotated (`cancelled` attr + event) — the critical-path reducer
    keeps cancelled spans off the path."""
    parent = current_span.get()
    if parent is None or not parent:
        yield NULL_SPAN
        return
    span = parent.child(name, **attrs)
    token = current_span.set(span)
    try:
        yield span
    except asyncio.CancelledError:
        span.set_attr("cancelled", True)
        span.event("cancelled")
        raise
    finally:
        current_span.reset(token)
        span.finish()


@contextlib.contextmanager
def child_span_sync(name: str, **attrs: Any):
    """Sync twin of child_span for non-async seams (store commits,
    scheduler internals) running on the op task's context."""
    parent = current_span.get()
    if parent is None or not parent:
        yield NULL_SPAN
        return
    span = parent.child(name, **attrs)
    token = current_span.set(span)
    try:
        yield span
    finally:
        current_span.reset(token)
        span.finish()


# ---------------------------------------------------------------------------
# Critical-path attribution
# ---------------------------------------------------------------------------


def stage_of(name: str) -> str:
    """Stage key of a span name: the first whitespace token
    ('subread osd.3' -> 'subread')."""
    return name.split(" ", 1)[0] if name else "unknown"


def _cp_walk(rec: tuple, lo: int, hi: int,
             kids: Dict[Any, list], stages: Dict[str, int],
             path: Optional[List[Dict[str, Any]]],
             depth: int) -> None:
    """Attribute [lo, hi) of a span's interval: walk backward from hi,
    hand each stretch to the latest-ending overlapping non-cancelled
    child, keep the gaps as this span's self-time.  rec is the
    normalized (span_id, name, t0_us, dur_us) tuple."""
    children = []
    for c in kids.get(rec[0], ()):
        c0, c1 = max(c[2], lo), min(c[2] + c[3], hi)
        if c1 > c0:
            children.append((c0, c1, c))
    cursor = hi
    self_us = 0
    while children and cursor > lo:
        live = [(c0, min(c1, cursor), c)
                for c0, c1, c in children if c0 < cursor]
        live = [t for t in live if t[1] > t[0]]
        if not live:
            break
        c0, c1, c = max(live, key=lambda t: (t[1], t[0]))
        self_us += cursor - c1
        _cp_walk(c, c0, c1, kids, stages, path, depth + 1)
        cursor = c0
        children = [e for e in children if e[2] is not c]
    self_us += max(cursor - lo, 0)
    st = stage_of(rec[1])
    stages[st] = stages.get(st, 0) + self_us
    if path is not None:
        path.append({"name": rec[1], "stage": st, "depth": depth,
                     "self_us": self_us, "span_us": hi - lo})


def _cp_reduce(recs: List[tuple], want_path: bool) -> Dict[str, Any]:
    """Shared reducer body over normalized (span_id, name, t0_us,
    dur_us, parent_id, cancelled) records."""
    by_id = {r[0] for r in recs}
    kids: Dict[Any, list] = {}
    roots = []
    for r in recs:
        if r[5]:
            continue  # cancelled: ran, but the op never waited for it
        if r[4] and r[4] in by_id:
            kids.setdefault(r[4], []).append(r)
        else:
            roots.append(r)
    if not roots:
        return {"total_us": 0, "stages": {}, "path": []}
    root = min(roots, key=lambda r: r[2])
    lo, hi = root[2], root[2] + root[3]
    stages: Dict[str, int] = {}
    path: Optional[List[Dict[str, Any]]] = [] if want_path else None
    _cp_walk(root, lo, hi, kids, stages, path, 0)
    if path is not None:
        path.reverse()  # the walk appends leaves-first
    return {"total_us": hi - lo, "stages": stages,
            "path": path if path is not None else []}


def critical_path(spans: Iterable[Dict[str, Any]],
                  want_path: bool = True) -> Dict[str, Any]:
    """Per-stage self-time on the critical path of one finished span
    tree (to_dict shape: span_id/parent_id/t0_us/duration_us/attrs).

    Walks backward from the root's end: at every instant the op was
    waiting on exactly one span — the latest-ending overlapping child
    (recursively), or the parent itself in the gaps.  Parallel hedged
    children therefore attribute to the LONGEST child; a cancelled
    straggler (attrs.cancelled) is excluded — it ran, but nothing
    waited for it.  Returns {"total_us", "stages": {stage: self_us},
    "path": [{name, stage, depth, self_us, span_us}, ...]} with the
    path listed root-first (empty when want_path=False)."""
    recs = [(s["span_id"], s.get("name", ""), s.get("t0_us", 0),
             s.get("duration_us", 0), s.get("parent_id") or "",
             bool((s.get("attrs") or {}).get("cancelled")))
            for s in spans if s]
    return _cp_reduce(recs, want_path)


def critical_path_spans(root: Span,
                        want_path: bool = False) -> Dict[str, Any]:
    """The hot-path twin of critical_path: reduces a live Span tree
    WITHOUT rendering dicts (per-op overhead at sample rate 0 is this
    function plus span bookkeeping — keep it allocation-light)."""
    if not root:
        return {"total_us": 0, "stages": {}, "path": []}
    t0 = root._t0
    recs = []
    for s in root._tree:
        recs.append((s.span_id, s.name,
                     int((s._t0 - t0) * 1e6),
                     int(s.duration_s * 1e6),
                     s.parent_id,
                     bool(s.attrs.get("cancelled"))))
    return _cp_reduce(recs, want_path)


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------

#: bound on distinct stage histograms per tracer: stage names come
#: from span names (first token), which are code-controlled — the cap
#: is a backstop against an attr leaking into a name
STAGE_CAP = 64

# lazily bound loadgen.stats.LatencyHistogram (loadgen pulls in the
# rados stack; the tracer must stay importable from anywhere)
_LatencyHistogram = None


class Tracer:
    """Per-daemon span collector: bounded ring, head sampling,
    per-stage critical-path histograms, admin-socket dump."""

    def __init__(self, service: str, max_spans: int = 2048,
                 sample_rate: float = 1.0, enabled: bool = True):
        self.service = service
        self._done: deque = deque(maxlen=max_spans)
        self.sample_rate = float(sample_rate)
        self._enabled = bool(enabled)
        # per-stage critical-path self-time histograms (bounded
        # log-bucket, constant memory — loadgen/stats.py)
        self.stage_hist: Dict[str, Any] = {}
        self.counters: Dict[str, int] = {
            "traces": 0, "spans_retained": 0, "stage_samples": 0}
        # the admin-socket serve THREAD dumps (dump_traces/perf dump)
        # while the event loop appends: structural mutations of the
        # ring and the stage map take this lock, as do their snapshots
        # (in-place histogram increments are read-torn at worst)
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        # env re-read per trace: the kill switch takes effect without
        # rebuilding daemons
        return self._enabled and env_enabled()

    @enabled.setter
    def enabled(self, value: bool) -> None:
        self._enabled = bool(value)

    def start(self, name: str,
              context: Optional[Tuple[int, int]] = None,
              sampled: Optional[bool] = None) -> Span:
        """New local-root span: child of `context` ((trace_id,
        parent_span_id) from the wire or a local parent's .context),
        or a fresh root trace when context is None.  A wire context
        inherits the sender's sampling decision; a fresh root samples
        at `sample_rate` — unsampled spans are still BUILT (stage
        histograms and tail exemplars need them), just not retained in
        the ring.  NULL_SPAN when tracing is off."""
        if not self.enabled:
            return NULL_SPAN
        if context is not None:
            trace_id, parent = int(context[0]), int(context[1])
            if sampled is None:
                sampled = True
        else:
            trace_id, parent = _id64(), 0
            if sampled is None:
                sampled = (self.sample_rate > 0.0
                           and _rand.random() < self.sample_rate)
        self.counters["traces"] += 1
        return Span(trace_id, _id64(), parent, name, self.service,
                    sampled=bool(sampled))

    def finish(self, span: Span
               ) -> Optional[List[Dict[str, Any]]]:
        """Finish a local root: its whole tree lands in the ring when
        sampled (children finished via child_span land with it).
        Returns the rendered tree when one was built — callers that
        also need the dicts (the tail-exemplar hook) reuse it instead
        of rendering twice."""
        if not span:
            return None
        span.finish()
        if not span.sampled:
            return None
        tree = span.tree_dicts()
        self.counters["spans_retained"] += len(tree)
        with self._lock:
            self._done.extend(tree)
        return tree

    @contextlib.asynccontextmanager
    async def span(self, name: str,
                   context: Optional[Tuple[int, int]] = None,
                   sampled: Optional[bool] = None,
                   set_current: bool = True):
        """Root-span context manager: start + install as current +
        finish on every path — the idiomatic fix for the span-leak
        lint rule."""
        sp = self.start(name, context=context, sampled=sampled)
        token = current_span.set(sp) if (set_current and sp) else None
        try:
            yield sp
        finally:
            if token is not None:
                current_span.reset(token)
            self.finish(sp)

    def record_stages(self, stages: Dict[str, int]) -> None:
        """Feed one op's critical-path decomposition (stage -> micro-
        seconds of self-time) into the streaming histograms."""
        global _LatencyHistogram
        if _LatencyHistogram is None:  # lazy: loadgen imports rados
            from ceph_tpu.loadgen.stats import LatencyHistogram

            _LatencyHistogram = LatencyHistogram
        for stage, us in stages.items():
            h = self.stage_hist.get(stage)
            if h is None:
                with self._lock:   # structural insert vs dump snapshot
                    if len(self.stage_hist) >= STAGE_CAP:
                        continue
                    h = self.stage_hist.setdefault(
                        stage, _LatencyHistogram())
            h.record(us / 1e6)
            self.counters["stage_samples"] += 1

    def stage_perf(self) -> Dict[str, Any]:
        """Per-stage nested perf section: the streaming histogram in
        prometheus {bounds, buckets, count, sum} shape plus p50/p99
        gauges (the flattener renders ceph_osd_trace_stage_* rows)."""
        out: Dict[str, Any] = {}
        with self._lock:
            items = sorted(self.stage_hist.items())
        for stage, h in items:
            p50, p99 = h.percentile(0.5), h.percentile(0.99)
            out[stage] = {
                "self_seconds": h.to_perf_histogram(),
                "count": h.count,
                "p50_ms": round(p50 * 1e3, 3) if p50 is not None
                else 0.0,
                "p99_ms": round(p99 * 1e3, 3) if p99 is not None
                else 0.0,
            }
        return out

    def dump(self, trace_id: Optional[int] = None) -> List[Dict]:
        with self._lock:
            out = list(self._done)
        if trace_id is not None:
            want = f"{trace_id:016x}"
            out = [s for s in out if s["trace_id"] == want]
        return out

"""Distributed tracing spans (the blkin/zipkin role).

Reference parity: /root/reference/src/blkin/ + the OSD/Messenger
tracepoints behind `osd_blkin_trace_all` — a client op carries a trace
context across the wire; every daemon it touches contributes spans
(parent-linked, timestamped, annotated) so one request's journey
(client -> primary -> replica sub-ops) reconstructs as a tree.  The
reference emits LTTng events consumed by an external zipkin collector;
this build keeps spans IN the daemons (bounded ring per Tracer) and
exposes them over the admin-socket/tell surface (`dump_traces`), which
fits the single-binary deployment the way the asok perf dump does.

Propagation: a (trace_id, span_id) pair rides in MOSDOp/MOSDSubWrite
(versioned tail fields — untraced peers skip them).  Inside a daemon
the active span travels by contextvar, so nested sends (the primary's
sub-writes fanned out under the op task) attach the right parent
without threading a span through every call signature.
"""

from __future__ import annotations

import contextvars
import secrets
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# the span the running task is working under (primary op execution
# sets it; sub-op sends read it) — context propagates per asyncio task
current_span: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("ceph_tpu_current_span", default=None)


def _id64() -> int:
    return secrets.randbits(63) | 1  # nonzero


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "service", "start", "end", "events")

    def __init__(self, trace_id: int, span_id: int, parent_id: int,
                 name: str, service: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = service
        self.start = time.time()
        self.end: Optional[float] = None
        self.events: List[Tuple[float, str]] = []

    def event(self, what: str) -> None:
        self.events.append((time.time(), what))

    @property
    def context(self) -> Tuple[int, int]:
        """What goes on the wire: (trace_id, my span id)."""
        return (self.trace_id, self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": f"{self.trace_id:016x}",
                "span_id": f"{self.span_id:016x}",
                "parent_id": f"{self.parent_id:016x}"
                             if self.parent_id else "",
                "name": self.name, "service": self.service,
                "start": self.start,
                "duration_us": int(((self.end or time.time())
                                    - self.start) * 1e6),
                "events": [{"t": t, "what": w}
                           for t, w in self.events]}


class Tracer:
    """Per-daemon span collector: bounded ring, admin-socket dump."""

    def __init__(self, service: str, max_spans: int = 2048):
        self.service = service
        self._done: deque = deque(maxlen=max_spans)

    def start(self, name: str,
              context: Optional[Tuple[int, int]] = None) -> Span:
        """New span: child of `context` ((trace_id, parent_span_id)
        from the wire or a local parent's .context), or a fresh root
        trace when context is None."""
        if context is not None:
            trace_id, parent = int(context[0]), int(context[1])
        else:
            trace_id, parent = _id64(), 0
        return Span(trace_id, _id64(), parent, name, self.service)

    def finish(self, span: Span) -> None:
        span.end = time.time()
        self._done.append(span)

    def dump(self, trace_id: Optional[int] = None) -> List[Dict]:
        out = [s.to_dict() for s in self._done]
        if trace_id is not None:
            want = f"{trace_id:016x}"
            out = [s for s in out if s["trace_id"] == want]
        return out

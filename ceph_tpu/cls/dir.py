"""cls_dir: atomic name -> value directory entries in omap.

Reference: the dir_add_image/dir_remove_image methods of cls_rbd
(/root/reference/src/cls/rbd/cls_rbd.cc:dir_add_image) — check-and-set
of a directory key must run server-side under the object lock or two
concurrent creators both 'win' and clobber each other's metadata.
"""

from __future__ import annotations

import json

from ceph_tpu.cls import ClsError, EINVAL, ENOENT, MethodContext, RD, WR, as_text

EEXIST = -17


async def _omap(ctx: MethodContext) -> dict:
    try:
        return await ctx.omap_get()
    except ClsError as e:
        if e.rc != ENOENT:
            raise
        return {}


async def add(ctx: MethodContext, data: bytes) -> bytes:
    req = json.loads(as_text(data))
    key, value = req.get("key"), req.get("value", "")
    if not key:
        raise ClsError(EINVAL, "missing key")
    omap = await _omap(ctx)
    if key in omap:
        raise ClsError(EEXIST, f"{key!r} exists")
    await ctx.omap_set({key: value.encode()})
    return b""


async def remove(ctx: MethodContext, data: bytes) -> bytes:
    """{key, value?}: remove an entry; with `value`, only if the
    stored value still matches (compare-and-swap — a racing writer who
    replaced the entry must not have it deleted under them)."""
    req = json.loads(as_text(data))
    key = req.get("key")
    omap = await _omap(ctx)
    if key not in omap:
        raise ClsError(ENOENT, f"no entry {key!r}")
    expect = req.get("value")
    if expect is not None and omap[key].decode() != expect:
        raise ClsError(EEXIST, f"{key!r} value changed")
    await ctx.omap_rm_keys([key])
    return b""


async def get(ctx: MethodContext, data: bytes) -> bytes:
    req = json.loads(as_text(data))
    omap = await _omap(ctx)
    value = omap.get(req.get("key", ""))
    if value is None:
        raise ClsError(ENOENT, "no entry")
    return value


async def list_keys(ctx: MethodContext, data: bytes) -> bytes:
    omap = await _omap(ctx)
    return json.dumps(sorted(omap)).encode()


def register(handler) -> None:
    handler.register("dir", "add", RD | WR, add)
    handler.register("dir", "remove", RD | WR, remove)
    handler.register("dir", "get", RD, get)
    handler.register("dir", "list", RD, list_keys)

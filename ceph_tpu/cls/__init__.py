"""cls role: object classes (server-side stored procedures).

Reference parity: the ClassHandler + cls SDK
(/root/reference/src/osd/ClassHandler.h, src/objclass/objclass.h, and
the classes under src/cls/).  A client `exec` op names (class, method,
input); the primary runs the registered handler ATOMICALLY under the
object lock, giving it read/write access to the object through the
same op engine ops a client would use — so class writes are logged,
replicated, and recovered like any other write.

The reference loads .so plugins; here classes are python callables in
a registry (the plugin_registry pattern used by EC/compressor), and
the in-tree classes mirror the reference's most-used ones:

- hello    (src/cls/hello/cls_hello.cc — the SDK demo)
- lock     (src/cls/lock/ — advisory exclusive/shared object locks)
- numops   (src/cls/numops/ — atomic arithmetic on stored values)

Method flags mirror CLS_METHOD_RD/CLS_METHOD_WR: a method registered
RD-only is refused write access, and calling a WR method sends the
op down the write path (version bump) like the reference does.
"""

from __future__ import annotations

import json
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

RD = 1   # CLS_METHOD_RD
WR = 2   # CLS_METHOD_WR


def as_text(data, encoding: str = "utf-8") -> str:
    """Decode a method payload (bytes OR a zero-copy wire/store view)
    to text without materializing an intermediate bytes object —
    str(buffer, encoding) reads any buffer directly.  The cls-SDK
    twin of common/buffer.as_buffer for the JSON-argument idiom."""
    if isinstance(data, str):
        return data
    return str(data, encoding)

ENOENT = -2
EINVAL = -22
EPERM = -1
EBUSY = -16
ENOATTR = -61


class ClsError(Exception):
    """Raised by class methods to return an error rc to the client."""

    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc


class MethodContext:
    """The objclass.h surface handed to a running method: object I/O
    routed through the hosting OSD's op engine (cls_cxx_read,
    cls_cxx_write_full, cls_cxx_getxattr, cls_cxx_map_* roles).
    Write access requires the method's WR flag."""

    def __init__(self, daemon, state, pool, oid: str,
                 admit_epoch: int, snapc, flags: int):
        self._d = daemon
        self._state = state
        self._pool = pool
        self.oid = oid
        self._admit_epoch = admit_epoch
        self._snapc = snapc
        self._flags = flags

    def _need_wr(self) -> None:
        if not self._flags & WR:
            raise ClsError(EPERM, "method not registered WR")

    # -- reads -------------------------------------------------------------

    async def read(self, offset: int = 0, length: int = 0):
        """Object bytes as a ZERO-COPY readonly view of the read
        path's buffer (frozen decode output / store buffer / frame
        view): RD-only methods that only slice or compare never pay a
        whole-object copy.  Methods that genuinely need to own the
        payload (caching it across awaits, returning it to the wire
        after a subsequent write) take bytes() themselves; JSON
        parsing goes through `cls.as_text`."""
        from ceph_tpu.common.buffer import as_buffer

        rc, data = await self._d._op_read(self._state, self._pool,
                                          self.oid, offset, length)
        if rc != 0:
            raise ClsError(rc, "read")
        buf = as_buffer(data)
        if isinstance(buf, bytes):
            return buf
        view = buf if isinstance(buf, memoryview) else memoryview(buf)
        return view.toreadonly()

    async def stat(self) -> Dict[str, Any]:
        rc, out = await self._d._op_stat(self._state, self._pool,
                                         self.oid)
        if rc != 0:
            raise ClsError(rc, "stat")
        return out

    async def getxattr(self, name: str) -> bytes:
        rc, data = await self._d._op_getxattr(self._state, self._pool,
                                              self.oid, name)
        if rc != 0:
            raise ClsError(rc, f"getxattr {name!r}")
        return data

    async def omap_get(self) -> Dict[str, bytes]:
        from ceph_tpu.msg.messages import decode_kv_map

        rc, data = await self._d._op_omap_get(self._state, self._pool,
                                              self.oid)
        if rc != 0:
            raise ClsError(rc, "omap_get")
        return decode_kv_map(data) if data else {}

    # -- writes (flags-gated) ----------------------------------------------

    async def write_full(self, data: bytes) -> None:
        self._need_wr()
        rc, _out = await self._d._op_write_full(
            self._state, self._pool, self.oid, data,
            self._admit_epoch, self._snapc)
        if rc != 0:
            raise ClsError(rc, "write_full")

    async def write(self, offset: int, data: bytes) -> None:
        self._need_wr()
        rc = await self._d._op_write(
            self._state, self._pool, self.oid, offset, data,
            self._admit_epoch, self._snapc)
        if rc != 0:
            raise ClsError(rc, "write")

    async def setxattr(self, name: str, value: Optional[bytes]) -> None:
        self._need_wr()
        rc = await self._d._op_setxattr(
            self._state, self._pool, self.oid, name, value,
            self._admit_epoch, self._snapc)
        if rc != 0:
            raise ClsError(rc, f"setxattr {name!r}")

    async def omap_set(self, kv: Dict[str, bytes]) -> None:
        from ceph_tpu.msg.messages import encode_kv_map

        self._need_wr()
        rc = await self._d._op_omap_write(
            self._state, self._pool, self.oid, "omap_set",
            encode_kv_map(kv), self._admit_epoch, self._snapc)
        if rc != 0:
            raise ClsError(rc, "omap_set")

    async def omap_rm_keys(self, keys) -> None:
        from ceph_tpu.msg.messages import encode_str_list

        self._need_wr()
        rc = await self._d._op_omap_write(
            self._state, self._pool, self.oid, "omap_rm",
            encode_str_list(list(keys)), self._admit_epoch,
            self._snapc)
        if rc != 0:
            raise ClsError(rc, "omap_rm_keys")

    async def remove(self) -> None:
        self._need_wr()
        rc = await self._d._op_remove(self._state, self._pool,
                                      self.oid, self._admit_epoch,
                                      self._snapc)
        if rc != 0:
            raise ClsError(rc, "remove")


Method = Callable[[MethodContext, bytes], Awaitable[bytes]]


class ClassHandler:
    """cls registry: (class, method) -> (handler, flags)."""

    def __init__(self):
        self._methods: Dict[Tuple[str, str], Tuple[Method, int]] = {}

    def register(self, cls: str, method: str, flags: int,
                 fn: Method) -> None:
        self._methods[(cls, method)] = (fn, flags)

    def method(self, cls: str, method: str, flags: int):
        def deco(fn: Method) -> Method:
            self.register(cls, method, flags, fn)
            return fn
        return deco

    def lookup(self, cls: str, method: str
               ) -> Optional[Tuple[Method, int]]:
        return self._methods.get((cls, method))

    def list_classes(self) -> Dict[str, list]:
        out: Dict[str, list] = {}
        for (cls, method) in sorted(self._methods):
            out.setdefault(cls, []).append(method)
        return out


def default_handler() -> ClassHandler:
    """The in-tree classes, registered (ClassHandler::open_all role)."""
    from ceph_tpu.cls import dir as dir_cls
    from ceph_tpu.cls import hello, journal, lock, numops

    handler = ClassHandler()
    dir_cls.register(handler)
    hello.register(handler)
    journal.register(handler)
    lock.register(handler)
    numops.register(handler)
    return handler

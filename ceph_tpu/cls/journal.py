"""cls_journal: epoch-fenced append log on one object.

The MDS journal's server-side half (the roles of
/root/reference/src/cls/journal/cls_journal.cc — client registration
and fencing for journal objects — collapsed onto the omap surface this
framework's journals use).

Fencing model: the object carries an "epoch" xattr.  `take_over` bumps
it and returns the new value; `append`/`set_applied`/`trim` REQUIRE the
caller's epoch to equal the stored one.  RADOS serializes ops per
object, so after a take_over commits, every in-flight or later call
from the deposed epoch fails with EPERM — the mutation never lands,
which is what makes a deposed MDS harmless without trusting any clock
(the ADVICE finding: wall-clock staleness comparison cannot fence).

omap layout:
  e<seq:020d>  one journal entry (opaque payload)
  (xattr) epoch    fencing epoch, decimal
  (xattr) applied  highest seq known applied to the backing objects
"""

from __future__ import annotations

import json

from ceph_tpu.cls import ClsError, EINVAL, EPERM, MethodContext, RD, WR, as_text

ENTRY_PREFIX = "e"


def entry_key(seq: int) -> str:
    return f"{ENTRY_PREFIX}{seq:020d}"


async def _stored_epoch(ctx: MethodContext) -> int:
    try:
        return int((await ctx.getxattr("epoch")).decode())
    except ClsError:
        return 0


def _check_epoch(stored: int, claimed) -> int:
    try:
        claimed = int(claimed)
    except (TypeError, ValueError):
        raise ClsError(EINVAL, "bad epoch")
    if claimed != stored:
        raise ClsError(EPERM,
                       f"fenced: epoch {claimed} != {stored}")
    return claimed


async def take_over(ctx: MethodContext, data: bytes) -> bytes:
    """Bump the fencing epoch; returns the new epoch.  Everything the
    previous epoch tries afterwards fails EPERM."""
    epoch = await _stored_epoch(ctx) + 1
    # first takeover ever: materialize the journal object (omap_set
    # carries a create op; setxattr alone would ENOENT)
    await ctx.omap_set({})
    await ctx.setxattr("epoch", str(epoch).encode())
    return str(epoch).encode()


async def get_state(ctx: MethodContext, data: bytes) -> bytes:
    try:
        applied = int((await ctx.getxattr("applied")).decode())
    except ClsError:
        applied = 0
    return json.dumps({"epoch": await _stored_epoch(ctx),
                       "applied": applied}).encode()


async def append(ctx: MethodContext, data: bytes) -> bytes:
    """{epoch, seq, entry}: fenced, durable journal append."""
    req = json.loads(as_text(data))
    _check_epoch(await _stored_epoch(ctx), req.get("epoch"))
    try:
        seq = int(req["seq"])
        entry = req["entry"]
    except (KeyError, ValueError, TypeError):
        raise ClsError(EINVAL, "bad append")
    await ctx.omap_set({entry_key(seq): json.dumps(entry).encode()})
    return b""


async def set_applied(ctx: MethodContext, data: bytes) -> bytes:
    """{epoch, applied, from}: advance the applied watermark and trim
    entries in (from, applied] (fenced — a deposed trim could
    otherwise erase entries the new active has not replayed).  The
    caller supplies its previous watermark so trimming is O(trimmed),
    never a full-journal read."""
    req = json.loads(as_text(data))
    _check_epoch(await _stored_epoch(ctx), req.get("epoch"))
    try:
        applied = int(req["applied"])
        low = int(req.get("from", 0))
    except (KeyError, ValueError, TypeError):
        raise ClsError(EINVAL, "bad applied")
    await ctx.setxattr("applied", str(applied).encode())
    dead = [entry_key(s) for s in range(low + 1, applied + 1)]
    if dead:
        await ctx.omap_rm_keys(dead)
    return b""


async def guarded_update(ctx: MethodContext, data: bytes) -> bytes:
    """{epoch, set: {key: json|null}}: omap update on THIS object,
    refused if a NEWER epoch already stamped it (monotonic "fence"
    xattr).  The apply-phase fence: a deposed active can re-apply only
    state the new active already replayed (idempotent) — any object
    the new epoch has touched refuses the old epoch outright."""
    req = json.loads(as_text(data))
    try:
        epoch = int(req["epoch"])
        updates = req["set"]
    except (KeyError, ValueError, TypeError):
        raise ClsError(EINVAL, "bad guarded_update")
    try:
        stored = int((await ctx.getxattr("fence")).decode())
    except ClsError:
        stored = 0
    if epoch < stored:
        raise ClsError(EPERM, f"fenced: epoch {epoch} < {stored}")
    if epoch > stored:
        # materialize the object first: an xattr on a missing object
        # is ENOENT (the same first-touch shape as take_over)
        await ctx.omap_set({})
        await ctx.setxattr("fence", str(epoch).encode())
    sets = {k: v.encode() if isinstance(v, str) else v
            for k, v in updates.items() if v is not None}
    dels = [k for k, v in updates.items() if v is None]
    if sets:
        await ctx.omap_set(sets)
    elif not dels:
        await ctx.omap_set({})  # pure create
    if dels:
        await ctx.omap_rm_keys(dels)
    return b""


async def guarded_remove(ctx: MethodContext, data: bytes) -> bytes:
    """{epoch}: remove THIS object unless fenced by a newer epoch."""
    req = json.loads(as_text(data))
    try:
        epoch = int(req["epoch"])
    except (KeyError, ValueError, TypeError):
        raise ClsError(EINVAL, "bad epoch")
    try:
        stored = int((await ctx.getxattr("fence")).decode())
    except ClsError:
        stored = 0
    if epoch < stored:
        raise ClsError(EPERM, f"fenced: epoch {epoch} < {stored}")
    await ctx.remove()
    return b""


def register(handler) -> None:
    handler.register("journal", "take_over", RD | WR, take_over)
    handler.register("journal", "get_state", RD, get_state)
    handler.register("journal", "append", RD | WR, append)
    handler.register("journal", "set_applied", RD | WR, set_applied)
    handler.register("journal", "guarded_update", RD | WR,
                     guarded_update)
    handler.register("journal", "guarded_remove", RD | WR,
                     guarded_remove)

"""cls_lock: advisory object locks.

Reference: /root/reference/src/cls/lock/cls_lock.cc — lock(name, type,
cookie, tag), unlock, break_lock, get_info.  Lock state lives in an
object xattr keyed by lock name; EXCLUSIVE admits one owner, SHARED
many; re-locking with the same (owner, cookie) renews; unlocking
someone else's lock is EPERM (break_lock is the admin override).
RBD/RGW use this for image and bucket-index ownership.
"""

from __future__ import annotations

import json
import time

from ceph_tpu.cls import (
    ClsError,
    EBUSY,
    EINVAL,
    ENOATTR,
    ENOENT,
    MethodContext,
    RD,
    WR,
    as_text,
)

EXCLUSIVE = "exclusive"
SHARED = "shared"


def _attr(name: str) -> str:
    return f"lock.{name}"


async def _load(ctx: MethodContext, name: str) -> dict:
    try:
        st = json.loads(await ctx.getxattr(_attr(name)))
    except ClsError as e:
        if e.rc in (ENOENT, ENOATTR):
            return {"type": None, "tag": "", "lockers": {}}
        # EIO/EAGAIN etc: the lock state is UNKNOWN, not absent —
        # treating it as unlocked would grant a second exclusive owner
        raise
    # expiry (the reference lock_info_t expiration,
    # src/cls/lock/cls_lock.cc:147 remove expired): a locker taken
    # with duration>0 that outlived it is dropped on load, so a
    # crashed client can never brick the object forever
    now = time.time()
    expired = [k for k, v in st["lockers"].items()
               if v.get("expires", 0) and v["expires"] < now]
    for k in expired:
        del st["lockers"][k]
    if not st["lockers"]:
        st["type"] = None
    return st


def _key(owner: str, cookie: str) -> str:
    return f"{owner}\x00{cookie}"


async def _store(ctx: MethodContext, name: str, st: dict) -> None:
    """Persist lock state, creating the object if needed (a WR exec
    on a nonexistent object creates it, like the reference)."""
    raw = json.dumps(st).encode()
    try:
        await ctx.setxattr(_attr(name), raw)
    except ClsError as e:
        if e.rc != ENOENT:
            raise
        await ctx.write_full(b"")
        await ctx.setxattr(_attr(name), raw)


async def lock(ctx: MethodContext, data: bytes) -> bytes:
    req = json.loads(as_text(data))
    name = req["name"]
    ltype = req.get("type", EXCLUSIVE)
    if ltype not in (EXCLUSIVE, SHARED):
        raise ClsError(EINVAL, f"bad lock type {ltype!r}")
    owner, cookie = req["owner"], req.get("cookie", "")
    tag = req.get("tag", "")
    st = await _load(ctx, name)
    me = _key(owner, cookie)
    if st["lockers"]:
        if st["tag"] != tag:
            raise ClsError(EBUSY, "held with a different tag")
        if me in st["lockers"]:
            # renewal; a type change is only legal for a SOLE locker —
            # upgrading shared->exclusive over other holders would hand
            # out exclusivity that isn't exclusive
            others = set(st["lockers"]) - {me}
            if ltype != st["type"] and others:
                raise ClsError(EBUSY,
                               "type change with other lockers held")
            st["type"] = ltype
        elif st["type"] == EXCLUSIVE or ltype == EXCLUSIVE:
            raise ClsError(EBUSY, "conflicting lock held")
    else:
        st["type"] = ltype
    st["tag"] = tag
    duration = float(req.get("duration", 0) or 0)
    st["lockers"][me] = {"owner": owner, "cookie": cookie,
                         "expires": time.time() + duration
                         if duration else 0}
    await _store(ctx, name, st)
    return b""


async def unlock(ctx: MethodContext, data: bytes) -> bytes:
    req = json.loads(as_text(data))
    st = await _load(ctx, req["name"])
    me = _key(req["owner"], req.get("cookie", ""))
    if me not in st["lockers"]:
        raise ClsError(ENOENT, "not held by this owner/cookie")
    del st["lockers"][me]
    if not st["lockers"]:
        st["type"] = None
    await _store(ctx, req["name"], st)
    return b""


async def break_lock(ctx: MethodContext, data: bytes) -> bytes:
    """Admin override: evict a named locker (cls_lock break_lock)."""
    req = json.loads(as_text(data))
    st = await _load(ctx, req["name"])
    victim = _key(req["locker"], req.get("cookie", ""))
    if victim not in st["lockers"]:
        raise ClsError(ENOENT, "no such locker")
    del st["lockers"][victim]
    if not st["lockers"]:
        st["type"] = None
    await _store(ctx, req["name"], st)
    return b""


async def get_info(ctx: MethodContext, data: bytes) -> bytes:
    req = json.loads(as_text(data))
    st = await _load(ctx, req["name"])
    return json.dumps(st).encode()


def register(handler) -> None:
    handler.register("lock", "lock", RD | WR, lock)
    handler.register("lock", "unlock", RD | WR, unlock)
    handler.register("lock", "break_lock", RD | WR, break_lock)
    handler.register("lock", "get_info", RD, get_info)

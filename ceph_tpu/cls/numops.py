"""cls_numops: atomic arithmetic on omap-stored values.

Reference: /root/reference/src/cls/numops/cls_numops.cc — add/sub/
mul/div on a decimal value stored under an omap key, atomically under
the object lock (the class exists to prove read-modify-write classes
compose with replication).
"""

from __future__ import annotations

import json

from ceph_tpu.cls import ClsError, EINVAL, ENOENT, MethodContext, RD, WR, as_text


async def _rmw(ctx: MethodContext, data: bytes, op) -> bytes:
    req = json.loads(as_text(data))
    key = req["key"]
    try:
        operand = float(req["value"])
    except (KeyError, ValueError, TypeError):
        raise ClsError(EINVAL, "bad operand")
    try:
        omap = await ctx.omap_get()
    except ClsError as e:
        if e.rc != ENOENT:  # first call: object does not exist yet
            raise
        omap = {}
    try:
        current = float(omap.get(key, b"0").decode())
    except ValueError:
        raise ClsError(EINVAL, "stored value not numeric")
    result = op(current, operand)
    raw = repr(result).encode()
    await ctx.omap_set({key: raw})
    return raw


async def add(ctx: MethodContext, data: bytes) -> bytes:
    return await _rmw(ctx, data, lambda a, b: a + b)


async def sub(ctx: MethodContext, data: bytes) -> bytes:
    return await _rmw(ctx, data, lambda a, b: a - b)


async def mul(ctx: MethodContext, data: bytes) -> bytes:
    return await _rmw(ctx, data, lambda a, b: a * b)


async def div(ctx: MethodContext, data: bytes) -> bytes:
    def _div(a: float, b: float) -> float:
        if b == 0:
            raise ClsError(EINVAL, "division by zero")
        return a / b
    return await _rmw(ctx, data, _div)


def register(handler) -> None:
    handler.register("numops", "add", RD | WR, add)
    handler.register("numops", "sub", RD | WR, sub)
    handler.register("numops", "mul", RD | WR, mul)
    handler.register("numops", "div", RD | WR, div)

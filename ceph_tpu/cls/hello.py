"""cls_hello: the object-class SDK demo.

Reference: /root/reference/src/cls/hello/cls_hello.cc — say_hello
(pure RD compute), record_hello (WR: persists a greeting and refuses a
rewrite with EEXIST), replay (reads it back).
"""

from __future__ import annotations

from ceph_tpu.cls import ClsError, ENOATTR, ENOENT, MethodContext, RD, WR, as_text

EEXIST = -17
GREETING_ATTR = "hello.greeting"


async def say_hello(ctx: MethodContext, data: bytes) -> bytes:
    name = as_text(data) or "world"
    if len(name) > 100:
        raise ClsError(-22, "name too long")
    return f"Hello, {name}!".encode()


async def record_hello(ctx: MethodContext, data: bytes) -> bytes:
    recorded = True
    try:
        await ctx.getxattr(GREETING_ATTR)
    except ClsError as e:
        if e.rc not in (ENOENT, ENOATTR):
            raise  # EIO etc: state UNKNOWN — never clobber
        recorded = False
    if recorded:
        raise ClsError(EEXIST, "already said hello")
    greeting = await say_hello(ctx, data)
    await ctx.write_full(greeting)
    await ctx.setxattr(GREETING_ATTR, greeting)
    return b""


async def replay(ctx: MethodContext, data: bytes) -> bytes:
    return await ctx.getxattr(GREETING_ATTR)


def register(handler) -> None:
    handler.register("hello", "say_hello", RD, say_hello)
    handler.register("hello", "record_hello", RD | WR, record_hello)
    handler.register("hello", "replay", RD, replay)

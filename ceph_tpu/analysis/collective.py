"""Collective-site map: the static universe of the SPMD cross-process
plane.

Multi-host SPMD correctness is a *congruence* property: every process
in the group must reach the same collectives, in the same order, with
the same participation decisions.  The canonical failure is not a
wrong answer but a silent wedge — one process raises past an
agreement, branches on ``process_index``, or reorders two
collectives, and every peer blocks forever (or worse, retires a live
host).  This module extracts, per ``ast.Call`` that crosses the
process boundary, the facts the rules in ``rules_spmd.py`` and the
runtime cross-check in ``interleave.py`` need:

* **kind** — ``agreement`` (``multihost.agree``/``agree_healthy``/
  ``agreed_healthy``), ``put-global``, ``gather``, ``allgather``
  (``multihost_utils.process_allgather``), ``barrier``
  (``sync_global_devices`` / ``wait_at_barrier``), ``kv-wait``
  (``blocking_key_value_get``), ``kv-set`` (``key_value_set``), and
  ``collective`` (``jax.lax`` collectives inside traced bodies).
* **process_branches** — enclosing ``if``/``while`` tests that depend
  on the process identity (``process_index``, ``process_count``,
  ``local_host``, ``local_addressable`` or names assigned from them).
  Group-uniform kill switches (``is_multiprocess``, ``enabled``) are
  NOT process-dependent: every process takes the same branch.
* **swallow_line** — the enclosing ``try`` whose handler neither
  re-raises nor returns, i.e. an exception path on which this process
  silently *skips* the collective and continues with state its peers
  don't share.
* **prior_divergent_exits** — ``raise``/``return`` statements earlier
  in the same function guarded by a process-dependent predicate: the
  "process 1 bails before the agreement" shape.
* **has_timeout** — for coordinator-KV waits, whether a hard timeout
  argument is present (a dead host must read as a timeout, never a
  wedge — the discipline ``multihost.agree`` established).

``collective_site_map(project)`` renders the sites as a
``{(relpath, line): site}`` dict covering every line of each call
span (mirroring ``callgraph.await_site_map``), so a runtime trace
frame — whose ``f_lineno`` may land anywhere inside a multi-line
call — can be checked for membership: runtime ⊆ static.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import ModuleInfo, Project, dotted

# seam entry points: calls resolving to these (module-qualified) names
# are the cross-process plane.  Tail-matched against the resolved
# dotted callee so both `multihost.agree` at a call site and the bare
# `agree` inside parallel/multihost.py itself classify.
_SEAM_KINDS = {
    "agree": "agreement",
    "agree_healthy": "agreement",
    "agreed_healthy": "agreement",
    "put_global": "put-global",
    "gather": "gather",
}
_MULTIHOST_UTILS = {
    "process_allgather": "allgather",
    "sync_global_devices": "barrier",
}
# coordinator-KV client methods: the names are distinctive enough to
# classify on the attribute tail alone (the client object is opaque)
_KV_KINDS = {
    "blocking_key_value_get": "kv-wait",
    "wait_at_barrier": "barrier",
    "key_value_set": "kv-set",
}
# jax.lax collectives — required to carry a jax/lax-qualified head so
# an arbitrary method named `all_gather` does not classify
_LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter",
}

# process-identity reads: two processes evaluating the same predicate
# over these can take DIFFERENT branches
PROCESS_DEPENDENT = {
    "process_index", "process_count", "local_host",
    "local_addressable", "host_of_id",
}

# kinds that can block on peers (or retire them): divergence here is
# a wedge / false host-retirement, not a handled timeout.  kv-wait
# and kv-set are excluded — the per-peer timeout-to-None discipline
# inside multihost.agree makes their divergence a verdict, not a hang.
WEDGEABLE = {
    "agreement", "put-global", "gather", "allgather", "barrier",
    "collective",
}


@dataclass
class CollectiveSite:
    """One cross-process call site plus its control-flow facts."""

    node: ast.Call
    mod: ModuleInfo
    qualname: str
    scope_line: int
    kind: str
    callee: str
    line: int
    end_line: int
    # (line, predicate-name) of enclosing process-dependent tests
    process_branches: Tuple[Tuple[int, str], ...] = ()
    # enclosing `try` line whose handler swallows (no raise/return)
    swallow_line: int = 0
    # (line, predicate-name) of earlier raise/return under a
    # process-dependent predicate in the same function scope
    prior_divergent_exits: Tuple[Tuple[int, str], ...] = ()
    has_timeout: bool = False

    def key(self) -> Tuple[str, int]:
        return (self.mod.relpath.replace("\\", "/"), self.line)


def _call_name(mod: ModuleInfo, call: ast.Call) -> str:
    """Resolved dotted callee: the import table maps the head
    (`import X as m; m.f(..)` -> `X.f`); bare names stay bare."""
    name = dotted(call.func)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    if head in mod.imports:
        base, attr = mod.imports[head]
        full = base + ("." + attr if attr else "")
        return full + ("." + rest if rest else "")
    return name


def classify_call(mod: ModuleInfo, call: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, resolved-callee) when the call crosses the process
    boundary; None otherwise."""
    name = _call_name(mod, call)
    if not name:
        return None
    parts = name.split(".")
    tail = parts[-1]
    if tail in _KV_KINDS:
        return (_KV_KINDS[tail], name)
    if tail in _MULTIHOST_UTILS and "multihost_utils" in parts:
        return (_MULTIHOST_UTILS[tail], name)
    if tail in _SEAM_KINDS:
        # module-qualified seam call, or a bare call to the seam
        # function from inside the multihost module itself
        if len(parts) > 1 and parts[-2] == "multihost":
            return (_SEAM_KINDS[tail], name)
        if len(parts) == 1 and \
                mod.modname.rsplit(".", 1)[-1] == "multihost" and \
                tail in mod.functions:
            return (_SEAM_KINDS[tail], mod.modname + "." + tail)
        return None
    if tail in _LAX_COLLECTIVES and \
            any(p in ("lax", "jax") for p in parts[:-1]):
        return ("collective", name)
    return None


def _names_in(expr: ast.AST) -> Set[str]:
    """Every Name id and Attribute tail mentioned in an expression."""
    out: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _process_tainted_names(scope: ast.AST) -> Set[str]:
    """Names assigned (anywhere in the scope) from an expression that
    reads the process identity — `pid = process_index()` taints `pid`
    so `p == pid` reads as process-dependent."""
    tainted: Set[str] = set()
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign) and \
                _names_in(n.value) & PROCESS_DEPENDENT:
            for t in n.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
    return tainted


def _predicate_dependence(test: ast.AST,
                          tainted: Set[str]) -> Optional[str]:
    """The process-identity name a predicate reads, or None when the
    test is group-uniform (data-dependent or a kill switch)."""
    names = _names_in(test)
    hit = names & PROCESS_DEPENDENT
    if hit:
        return sorted(hit)[0]
    hit = names & tainted
    if hit:
        return sorted(hit)[0]
    return None


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """A handler that neither raises nor returns lets execution fall
    through past the try with the collective skipped — divergent
    state peers don't share.  `except: return sentinel` is an
    explicit verdict and does not count."""
    for n in ast.walk(handler):
        if isinstance(n, (ast.Raise, ast.Return)):
            return False
    return True


def _in_block(node: ast.AST, block: List[ast.stmt],
              parents: Dict[ast.AST, ast.AST]) -> bool:
    """True when node's ancestor chain passes through one of the
    given statements (e.g. membership in a Try body vs its
    handlers)."""
    stmts = set(map(id, block))
    cur: Optional[ast.AST] = node
    while cur is not None:
        if id(cur) in stmts:
            return True
        cur = parents.get(cur)
    return False


def _scope_of(mod: ModuleInfo, node: ast.AST) -> Tuple[ast.AST, str, int]:
    """(enclosing scope node, qualname, scope line)."""
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for fi in mod.functions.values():
                if fi.node is cur:
                    return (cur, fi.qualname, cur.lineno)
            return (cur, cur.name, cur.lineno)
        cur = mod.parents.get(cur)
    return (mod.tree, "<module>", 0)


def _site_facts(mod: ModuleInfo, call: ast.Call, kind: str,
                scope: ast.AST, tainted: Set[str]) -> Tuple[
                    Tuple[Tuple[int, str], ...], int]:
    """Walk the parent chain from the call up to its scope collecting
    process-dependent branch tests and the nearest swallowing try."""
    branches: List[Tuple[int, str]] = []
    swallow = 0
    child: ast.AST = call
    cur = mod.parents.get(call)
    while cur is not None and cur is not scope:
        if isinstance(cur, (ast.If, ast.While)) and \
                not _in_block(child, [cur.test], mod.parents):
            dep = _predicate_dependence(cur.test, tainted)
            if dep:
                branches.append((cur.lineno, dep))
        elif isinstance(cur, ast.IfExp):
            dep = _predicate_dependence(cur.test, tainted)
            if dep:
                branches.append((cur.lineno, dep))
        elif isinstance(cur, ast.Try) and not swallow and \
                _in_block(child, cur.body, mod.parents):
            for h in cur.handlers:
                if _handler_swallows(h):
                    swallow = cur.lineno
                    break
        child = cur
        cur = mod.parents.get(cur)
    return (tuple(branches), swallow)


def _divergent_exits(mod: ModuleInfo, scope: ast.AST,
                     tainted: Set[str]) -> List[Tuple[int, str]]:
    """raise/return statements inside this scope whose enclosing If
    test is process-dependent: past one of these, processes are on
    different progress trajectories.  `continue`/`break` only skip
    loop iterations, never subsequent collectives, so they don't
    count."""
    out: List[Tuple[int, str]] = []
    for n in ast.walk(scope):
        if not isinstance(n, (ast.Raise, ast.Return)):
            continue
        cur = mod.parents.get(n)
        while cur is not None and cur is not scope:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break       # nested scope: not this function's exit
            if isinstance(cur, (ast.If, ast.While)):
                dep = _predicate_dependence(cur.test, tainted)
                if dep:
                    out.append((n.lineno, dep))
                    break
            cur = mod.parents.get(cur)
    return sorted(out)


def _has_timeout_arg(call: ast.Call) -> bool:
    if len(call.args) >= 2:
        return True
    return any(kw.arg and "timeout" in kw.arg for kw in call.keywords)


def collect_sites(project: Project) -> List[CollectiveSite]:
    """Every collective site in the project, with facts (memoized on
    the project — three rules and the runtime cross-check share one
    extraction pass)."""
    cached = getattr(project, "_collective_sites", None)
    if cached is not None:
        return cached
    sites: List[CollectiveSite] = []
    for mod in project.modules.values():
        # lazily computed per enclosing scope
        scope_cache: Dict[int, Tuple[Set[str],
                                     List[Tuple[int, str]]]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cls = classify_call(mod, node)
            if cls is None:
                continue
            kind, callee = cls
            scope, qualname, scope_line = _scope_of(mod, node)
            cached = scope_cache.get(id(scope))
            if cached is None:
                tainted = _process_tainted_names(scope)
                exits = _divergent_exits(mod, scope, tainted)
                cached = scope_cache[id(scope)] = (tainted, exits)
            tainted, exits = cached
            branches, swallow = _site_facts(mod, node, kind, scope,
                                            tainted)
            sites.append(CollectiveSite(
                node=node, mod=mod, qualname=qualname,
                scope_line=scope_line, kind=kind, callee=callee,
                line=node.lineno,
                end_line=getattr(node, "end_lineno", None)
                or node.lineno,
                process_branches=branches,
                swallow_line=swallow,
                prior_divergent_exits=tuple(
                    e for e in exits if e[0] < node.lineno),
                has_timeout=_has_timeout_arg(node)))
    sites.sort(key=lambda s: (s.mod.relpath, s.line,
                              s.node.col_offset))
    project._collective_sites = sites
    return sites


def collective_site_map(project: Project) -> Dict[Tuple[str, int],
                                                  Dict[str, object]]:
    """{(relpath, line): {qualname, kind, callee}} for every line a
    collective call spans — a runtime frame anywhere inside the call
    must map back to the site (narrowest span wins on overlap, the
    ``await_site_map`` convention)."""
    out: Dict[Tuple[str, int], Dict[str, object]] = {}
    width: Dict[Tuple[str, int], int] = {}
    for s in collect_sites(project):
        rel = s.mod.relpath.replace("\\", "/")
        span = s.end_line - s.line
        for line in range(s.line, s.end_line + 1):
            key = (rel, line)
            if key in out and width[key] <= span:
                continue
            out[key] = {"qualname": s.qualname, "kind": s.kind,
                        "callee": s.callee}
            width[key] = span
    return out

"""CLI gate: `python -m ceph_tpu.analysis [paths ...]`.

Exit 0 when every finding is baselined or suppressed, 1 when any new
finding survives, 2 on usage errors — usable verbatim as a CI step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from ceph_tpu.analysis import (
    Baseline, analyze_paths, default_baseline_path, default_rules,
    load_baseline, write_baseline,
)


def _default_paths() -> List[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.analysis",
        description="AST-based trace-safety / dtype / async-hazard "
                    "linter for ceph_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the ceph_tpu "
                         "package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/"
                         "lint_baseline.json at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings: rewrite the "
                         "baseline file (keeps existing justifications)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in default_rules():
            print(name)
        return 0

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(default_rules())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    findings, _ = analyze_paths(paths, rules=rules)

    baseline_path = args.baseline or default_baseline_path()
    baseline = Baseline()
    if baseline_path and os.path.exists(baseline_path) and \
            not args.no_baseline:
        baseline = load_baseline(baseline_path)

    if args.write_baseline:
        out = args.baseline or baseline_path or os.path.join(
            "tools", "lint_baseline.json")
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        write_baseline(out, findings, old=baseline)
        print(f"wrote {len(findings)} finding(s) to {out}",
              file=sys.stderr)
        return 0

    new = [f for f in findings if f not in baseline]
    suppressed = len(findings) - len(new)

    if args.json:
        print(json.dumps([f.as_dict() for f in new], indent=2))
    else:
        for f in new:
            print(f.render())
    stale = baseline.stale(findings)
    summary = (f"{len(new)} finding(s), {suppressed} baselined"
               + (f", {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}"
                  if stale else ""))
    print(summary, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())

"""CLI gate: `python -m ceph_tpu.analysis [paths ...]`.

Exit 0 when every GATING finding (severity error/warning) is baselined
or suppressed, 1 when any new one survives, 2 on usage errors — usable
verbatim as a CI step.  "info" findings are advisory worklists
(hot-path-copy): they never gate and are surfaced separately via
`--hot-path-report`.

Warm runs replay the incremental cache (.lint_cache.json, keyed by
per-module sha256 — see cache.py) so the interprocedural pass costs
hash time, not parse+fixpoint time; `--no-cache` forces a full pass.

`--format=json` emits machine-readable records
(file/line/col/rule/fingerprint/severity/message) for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from ceph_tpu.analysis import (
    Baseline, analyze_paths, default_baseline_path, default_rules,
    load_baseline, write_baseline,
)
from ceph_tpu.analysis import cache as lint_cache
from ceph_tpu.analysis.core import iter_py_files
from ceph_tpu.analysis.findings import Finding, gating


def _default_paths() -> List[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _emit(findings: List[Finding], fmt: str) -> None:
    if fmt == "json":
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.analysis",
        description="AST-based trace-safety / dtype / async-hazard "
                    "linter for ceph_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the ceph_tpu "
                         "package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/"
                         "lint_baseline.json at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings: rewrite the "
                         "baseline file (keeps existing justifications)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text", dest="fmt",
                    help="finding output format (json: one record per "
                         "finding for CI annotation)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format=json")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and don't write .lint_cache.json")
    ap.add_argument("--hot-path-report", action="store_true",
                    help="print the hot-path-copy worklist (ROADMAP "
                         "item 2's zero-copy targets) instead of "
                         "gating; always exits 0")
    args = ap.parse_args(argv)
    if args.json:
        args.fmt = "json"

    if args.list_rules:
        for name in default_rules():
            print(name)
        return 0

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(default_rules())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    rule_names = sorted(rules if rules is not None else default_rules())
    cache_path = lint_cache.default_cache_path()
    findings = None
    # the cache is keyed by the active rule-set hash, so a `--rules`
    # subset run stores under its own entry and can never poison (or
    # evict) the full gate's; explicit path subsets still bypass —
    # they change the FILE set, and a warm whole-tree entry per
    # ad-hoc path selection isn't worth the churn
    use_cache = not args.no_cache and not args.paths
    if use_cache:
        hashes = lint_cache.scan_hashes(iter_py_files(paths))
        findings, changed = lint_cache.load(
            cache_path, hashes, rule_names)
        if findings is not None:
            print(f"cache hit: {len(hashes)} unchanged module(s)",
                  file=sys.stderr)
        elif changed:
            print(f"cache miss: {len(changed)} changed module(s), "
                  f"e.g. {os.path.basename(changed[0])}",
                  file=sys.stderr)
    if findings is None:
        findings, _ = analyze_paths(paths, rules=rules)
        if use_cache:
            lint_cache.save(cache_path, hashes, rule_names, findings)

    gate = gating(findings)
    worklist = [f for f in findings if f.severity == "info"]

    if args.hot_path_report:
        _emit(worklist, args.fmt)
        print(f"{len(worklist)} hot-path copy site(s) — ROADMAP item "
              "2 zero-copy worklist", file=sys.stderr)
        return 0

    baseline_path = args.baseline or default_baseline_path()
    baseline = Baseline()
    if baseline_path and os.path.exists(baseline_path) and \
            not args.no_baseline:
        baseline = load_baseline(baseline_path)

    if args.write_baseline:
        out = args.baseline or baseline_path or os.path.join(
            "tools", "lint_baseline.json")
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        # info findings are worklists, never baseline entries
        write_baseline(out, gate, old=baseline)
        print(f"wrote {len(gate)} finding(s) to {out}",
              file=sys.stderr)
        return 0

    new = [f for f in gate if f not in baseline]
    suppressed = len(gate) - len(new)

    _emit(new, args.fmt)
    stale = baseline.stale(gate)
    summary = (f"{len(new)} finding(s), {suppressed} baselined, "
               f"{len(worklist)} advisory"
               + (f", {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}"
                  if stale else ""))
    print(summary, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())

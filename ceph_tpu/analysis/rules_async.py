"""Interprocedural async-atomicity / cancellation-safety rules.

Four rules over the callgraph.py whole-program layer, each the static
twin of a bug class a previous PR fixed by hand after a runtime hunt:

  await-atomicity      read-modify-write of `self.` state spanning an
                       `await` with no lockdep.Lock scope covering both
                       sides — the PR-3 class (a suspension between
                       version allocation and submit let a concurrent
                       write clobber the counter)
  cancellation-unsafe-acquire
                       a resource/counter/seq acquired, then a
                       suspension outside try/finally or
                       asyncio.shield BEFORE the paired use — the PR-6
                       class (a sub-read cancelled while parked behind
                       the send lock consumed a frame seq that never
                       hit the wire, gapping the receiver's replay
                       check and killing the connection)
  transitive-blocking-call
                       sync file/socket/sleep I/O reachable from an
                       `async def` through ANY depth of sync helpers
                       (rule async-blocking only sees direct calls)
  hot-path-copy        bytes()/b"".join/slice/.copy()/.tobytes()
                       copies in the msgr→OSD→ec/plan hot path.
                       Severity "info": this rule is a WORKLIST, not a
                       gate — its finding list enumerates the copy
                       sites ROADMAP item 2's zero-copy pass must
                       retire (`--hot-path-report` prints it)

plus the suppression-hygiene satellite:

  unused-suppression   a `# lint: disable=<rule>` comment that
                       suppressed nothing this run — dead suppressions
                       otherwise accumulate and silently swallow the
                       next real finding on that line
"""

from __future__ import annotations

import ast
import re
from types import SimpleNamespace
from typing import Optional

from ceph_tpu.analysis.callgraph import (
    CallGraph, async_context, function_atomicity_windows,
    walk_scope_ordered,
)
from ceph_tpu.analysis.core import Analyzer, dotted
from ceph_tpu.analysis.rules import (
    _enclosing_qualname, _inside_lambda, _scope_line, walk_scope,
)

# ---------------------------------------------------------------------
# await-atomicity
# ---------------------------------------------------------------------

# daemon modules whose `self.` state is shared across concurrent tasks
# on one event loop — exactly the processes whose every prior
# concurrency bug was an unprotected await window
_ATOMICITY_PATHS = ("ceph_tpu/osd/", "ceph_tpu/msg/", "ceph_tpu/os/",
                    "ceph_tpu/mon/", "ceph_tpu/mds/")


def rule_await_atomicity(a: Analyzer) -> None:
    """Read-modify-write of `self.<attr>` whose read and write straddle
    a suspension point with no single lockdep.Lock `async with` scope
    covering both: between the read and the write every other task on
    the loop may run, read the SAME value, and one of the two writes is
    silently lost.  Fix: hold a lockdep.Lock across the window, move
    the await out of it, or re-derive the value after the await."""
    paths = a.config.get("atomicity_paths", _ATOMICITY_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            for w in function_atomicity_windows(a.project, fi):
                if w.protected:
                    continue
                span = w.suspensions[0].line if w.suspensions \
                    else w.write_line
                a.emit(
                    "await-atomicity", mod, w.write_node,
                    f"read-modify-write of `{w.attr}` in "
                    f"`{fi.qualname}` spans an await (read at line "
                    f"{w.read_line}, suspension at line {span}): "
                    "another task can interleave and this write "
                    "clobbers its update — hold one lockdep.Lock "
                    "scope across the window or re-read after the "
                    "await",
                    symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# cancellation-unsafe-acquire
# ---------------------------------------------------------------------

_CANCEL_PATHS = ("ceph_tpu/osd/", "ceph_tpu/msg/")
# call tails that ACQUIRE a resource whose loss on cancellation is a
# protocol gap: explicit acquire/reserve/alloc verbs, plus this
# codebase's version allocator
_ACQUIRE_ATTR_RE = re.compile(r"^(acquire|reserve|alloc)")
_ACQUIRE_NAMES = {"_next_entry"}
# `next(<counter>)` on seq/count-named counters consumes a monotonic
# value (the msgr frame-seq class)
_COUNTER_RE = re.compile(r"seq|count", re.I)


def _acquire_kind(call: ast.Call) -> Optional[str]:
    name = dotted(call.func) or ""
    tail = name.split(".")[-1]
    if tail in _ACQUIRE_NAMES or _ACQUIRE_ATTR_RE.match(tail):
        return tail
    if tail == "next" and call.args:
        arg = dotted(call.args[0]) or ""
        if _COUNTER_RE.search(arg):
            return f"next({arg})"
    return None


def rule_cancellation_unsafe_acquire(a: Analyzer) -> None:
    """A monotonic seq / version / reservation is acquired, then the
    coroutine can suspend BEFORE the paired use — a cancellation landing
    on that suspension consumes the resource without ever submitting
    it (the msgr seq-gap class: the receiver's replay check sees the
    hole and kills the connection).  Safe shapes: acquire after the
    last pre-use suspension, the suspension under a try/finally that
    releases, or `await asyncio.shield(...)`."""
    paths = a.config.get("cancel_paths", _CANCEL_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            ctx = async_context(a.project, fi)
            if not ctx.suspensions:
                continue
            nodes = list(walk_scope_ordered(fi.node))
            for stmt in nodes:
                if not isinstance(stmt, ast.Assign) or \
                        not isinstance(stmt.value, ast.Call):
                    continue
                kind = _acquire_kind(stmt.value)
                if kind is None:
                    continue
                bound = {t.id for t in stmt.targets
                         if isinstance(t, ast.Name)}
                if not bound:
                    continue
                acq_line = getattr(stmt, "end_lineno", stmt.lineno)
                # first later statement that references the value =
                # the paired submit/use
                use_line = None
                for other in nodes:
                    if getattr(other, "lineno", 0) <= acq_line or \
                            not isinstance(other, ast.stmt):
                        continue
                    names = {n.id for n in ast.walk(other)
                             if isinstance(n, ast.Name)}
                    if names & bound:
                        use_line = other.lineno
                        break
                if use_line is None:
                    continue   # never used: nothing paired to lose
                gaps = [s for s in ctx.suspensions
                        if acq_line < s.line < use_line
                        and not s.in_try_finally and not s.shielded]
                if not gaps:
                    continue
                a.emit(
                    "cancellation-unsafe-acquire", mod, stmt,
                    f"`{kind}` acquired in `{fi.qualname}` but the "
                    f"coroutine can suspend at line {gaps[0].line} "
                    f"before the paired use at line {use_line}: a "
                    "cancellation there consumes the resource "
                    "without submitting it (seq gap / leaked "
                    "reservation) — acquire after the suspension, "
                    "cover it with try/finally that releases, or "
                    "shield the await",
                    symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# transitive-blocking-call
# ---------------------------------------------------------------------


# callees the blocking closure treats as non-blocking: memoized
# one-shot inits whose steady-state call is a dict read.  get_lib is
# the native library's build-once entry — every daemon AND client
# PREWARMS it off-loop at the msgr bind/connect choke point
# (Messenger._prewarm_native, asyncio.to_thread), so the subprocess
# compile never runs on a serving event loop; every call after that
# returns the cached binding.  Module-qualified so only the native
# package's get_lib is exempt — a future blocking helper that happens
# to share the name still gets flagged.
_BLOCKING_EXEMPT = (
    "ceph_tpu.native.get_lib",
    # the collective-trace recorder's JSONL append: diagnostics-only,
    # armed by env in the multi-process harness, never on a hot
    # daemon path — and the data plane must not be restructured
    # around its instrument
    "ceph_tpu.analysis.interleave.record_collective",
)


def rule_transitive_blocking_call(a: Analyzer) -> None:
    """Event-loop-blocking I/O (open / time.sleep / subprocess /
    urllib / socket) reachable from an `async def` through a chain of
    SYNC helpers — rule async-blocking's interprocedural closure.  The
    finding names the whole chain; fix by awaiting an async
    equivalent, shipping the helper through asyncio.to_thread, or
    justifying a deliberate boot-time/CLI block in the baseline."""
    paths = a.config.get("transitive_paths", ())
    cg = CallGraph(a.project, blocking_exempt=a.config.get(
        "blocking_exempt", _BLOCKING_EXEMPT))
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if paths and not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            seen_callees = set()
            for call, callee in cg.callees(fi):
                if callee.is_async or _inside_lambda(mod, call):
                    continue
                chain = cg.blocking_chain(callee)
                if chain is None:
                    continue
                key = (call.lineno, id(callee.node))
                if key in seen_callees:
                    continue
                seen_callees.add(key)
                route = " -> ".join([fi.qualname] + chain)
                a.emit(
                    "transitive-blocking-call", mod, call,
                    f"sync helper `{callee.qualname}` called from "
                    f"`async def {fi.qualname}` reaches blocking "
                    f"I/O ({route}): the event loop stalls for "
                    "every task on this daemon — await an async "
                    "equivalent or ship the helper through "
                    "asyncio.to_thread",
                    symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# hot-path-copy
# ---------------------------------------------------------------------

# the msgr→daemon→ec/plan data path: every op's payload crosses these
# modules, so each pattern here is a per-op full-buffer copy.  cls/
# (object-class methods run per op on the primary) and the coded-
# compute layer (whole WAVES of shard payloads per dispatch) are on
# the path too.
_HOT_PATHS = ("ceph_tpu/msg/", "ceph_tpu/osd/daemon.py",
              "ceph_tpu/osd/ec_util.py",
              "ceph_tpu/osd/encode_service.py", "ceph_tpu/ec/",
              "ceph_tpu/cls/", "ceph_tpu/compute/",
              "ceph_tpu/osd/compute.py")
# receivers that plausibly hold bulk payload bytes (the slice
# heuristic's noise bound: an int index or a small-tuple slice on an
# unrelated name is not a worklist entry)
_BUF_NAME_RE = re.compile(
    r"data|payload|buf|blob|chunk|shard|stream|frame|part", re.I)

# constructors whose result slices ZERO-COPY: a name bound to one of
# these is a view, and slicing it is exactly the discipline this
# rule's findings prescribe — flagging it would re-list every
# converted site forever
_VIEW_CTORS = {"memoryview", "StridedBuf", "toreadonly", "bytes_view"}


def _recv_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _view_names(mod) -> dict:
    """(enclosing qualname) -> names assigned from a view constructor
    (memoryview(...), StridedBuf(...), .toreadonly(), .bytes_view())
    anywhere in that scope.  Scope-level, not flow-sensitive — good
    enough for a worklist rule: a name that is EVER a view in a
    function is overwhelmingly view-typed at its slice sites."""
    out: dict = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        name = dotted(node.value.func) or ""
        if name.split(".")[-1] not in _VIEW_CTORS:
            continue
        scope = _enclosing_qualname(mod, node)
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.setdefault(scope, set()).add(t.id)
    return out


def rule_hot_path_copy(a: Analyzer) -> None:
    """Buffer copies on the msgr→OSD→ec/plan hot path: `bytes(x)`,
    `b"".join(...)`, payload slicing, `.copy()`, `.tobytes()`.  Each
    costs a full memcpy per op at line rate.  Severity "info" — the
    finding list IS ROADMAP item 2's zero-copy worklist (surfaced via
    `python -m ceph_tpu.analysis --hot-path-report`), not a gate:
    retire entries with memoryview/StridedBuf views end to end."""
    paths = a.config.get("hot_paths", _HOT_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        views = _view_names(mod)
        for node in ast.walk(mod.tree):
            msg = None
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name) and fn.id == "bytes" \
                        and len(node.args) == 1 and not isinstance(
                            node.args[0], ast.Constant):
                    msg = ("bytes(...) materializes a full copy of "
                           "the buffer")
                elif isinstance(fn, ast.Attribute) and \
                        fn.attr == "join" and isinstance(
                            fn.value, ast.Constant) and isinstance(
                            fn.value.value, bytes):
                    msg = ("b\"\".join(...) concatenates by copying "
                           "every part")
                elif isinstance(fn, ast.Attribute) and \
                        fn.attr == "copy" and not node.args:
                    msg = ".copy() duplicates the array/buffer"
                elif isinstance(fn, ast.Attribute) and \
                        fn.attr == "tobytes" and not node.args:
                    msg = (".tobytes() copies device/array data into "
                           "a fresh bytes object")
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.slice, ast.Slice) and isinstance(
                    node.ctx, ast.Load):
                name = _recv_name(node.value)
                if name and _BUF_NAME_RE.search(name) and \
                        name not in views.get(
                            _enclosing_qualname(mod, node), ()):
                    msg = (f"slicing `{name}` copies the byte range "
                           "(a memoryview slice is zero-copy)")
            if msg is None:
                continue
            a.emit(
                "hot-path-copy", mod, node,
                f"{msg} on the msgr→OSD→plan hot path — ROADMAP "
                "item 2 worklist entry: keep a view "
                "(memoryview/StridedBuf) end to end, or accept the "
                "copy knowingly",
                severity="info",
                symbol=_enclosing_qualname(mod, node),
                scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# unused-suppression
# ---------------------------------------------------------------------


def rule_unused_suppression(a: Analyzer) -> None:
    """A `# lint: disable=<rule>` (or disable-file) comment that
    suppressed NOTHING in this run: the violation it covered was fixed
    (or never existed), and the stale comment now silently swallows
    the next real finding on that line.  Delete it.  Judged only for
    rules that actually ran, so subset runs can't cry wolf.

    Registered LAST in default_rules(): it reads the suppression-hit
    ledger every earlier emit() recorded into."""
    active = set(a.rules) - {"unused-suppression"}
    for mod in a.project.modules.values():
        for line in sorted(mod.suppress):
            for rule in sorted(mod.suppress[line]):
                if rule not in active:
                    continue
                if (mod.relpath, line, rule) in a.suppression_hits:
                    continue
                a.emit(
                    "unused-suppression", mod,
                    SimpleNamespace(lineno=line, col_offset=0),
                    f"`# lint: disable={rule}` suppresses nothing "
                    "(the finding it covered is gone) — delete the "
                    "stale suppression before it swallows the next "
                    "real finding here",
                    severity="warning", symbol="<suppression>")
        for rule in sorted(mod.file_suppress):
            if rule not in active:
                continue
            if (mod.relpath, -1, rule) in a.suppression_hits:
                continue
            a.emit(
                "unused-suppression", mod,
                SimpleNamespace(lineno=1, col_offset=0),
                f"`# lint: disable-file={rule}` suppresses nothing "
                "in this module — delete the stale file-wide "
                "suppression",
                severity="warning", symbol="<suppression>")

"""Incremental analysis cache for the CLI gate (.lint_cache.json).

The interprocedural pass (callgraph + async-context + traced-set
fixpoint) costs whole seconds on the ~55-module tree; CI and the
pre-commit habit both run `python -m ceph_tpu.analysis` on trees that
usually haven't changed since the last run.  The cache keys every
scanned module by its file sha256 — plus the analyzer's OWN sources,
so editing a rule invalidates results the old rule produced — and
replays the stored findings when *everything* matches.

Scope is deliberately all-or-nothing: the new rules are
interprocedural, so a one-line edit in a helper module can create or
retire a finding in a caller three modules away (that is the entire
point of transitive-blocking-call).  Reusing per-module results across
an edit would need the reverse dependency closure of the call graph;
replaying only bit-identical trees needs nothing but hashes and is
always sound.  The per-module sha map still earns its keep: a miss
report names exactly which files moved.

Entries are keyed by the ACTIVE RULE-SET hash: a `--rules` subset run
stores under its own key and can never poison (or evict) the full
gate's entry — each ruleset replays only findings produced by exactly
that ruleset over exactly these hashes.

Cache hygiene: the file is advisory and self-invalidating — delete it
freely, never check it in (.gitignore'd), `--no-cache` bypasses it.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from ceph_tpu.analysis.findings import Finding

CACHE_VERSION = 2
CACHE_BASENAME = ".lint_cache.json"

_ANALYSIS_DIR = os.path.dirname(os.path.abspath(__file__))


def default_cache_path() -> str:
    """<repo root>/.lint_cache.json (next to tools/), falling back to
    the working directory for out-of-repo runs."""
    pkg_parent = os.path.dirname(os.path.dirname(_ANALYSIS_DIR))
    root = pkg_parent if os.path.isdir(
        os.path.join(pkg_parent, "tools")) else os.getcwd()
    return os.path.join(root, CACHE_BASENAME)


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 16), b""):
            h.update(block)
    return h.hexdigest()


def _analyzer_sha() -> str:
    """One hash over the analysis package's own sources: a rule edit
    must never replay findings the previous rule computed."""
    h = hashlib.sha256()
    for fn in sorted(os.listdir(_ANALYSIS_DIR)):
        if fn.endswith(".py"):
            h.update(fn.encode())
            h.update(file_sha256(os.path.join(_ANALYSIS_DIR, fn))
                     .encode())
    return h.hexdigest()


def scan_hashes(files: Iterable[str]) -> Dict[str, str]:
    """abspath -> sha256 for every scanned module (sorted for a
    stable on-disk representation)."""
    return {os.path.abspath(p): file_sha256(p) for p in sorted(files)}


def _ruleset_key(rule_names: Iterable[str]) -> str:
    """Stable hash of the active rule set: the entry key that keeps a
    `--rules` subset run from ever poisoning the full gate's entry."""
    h = hashlib.sha256("\n".join(sorted(rule_names)).encode())
    return h.hexdigest()[:16]


def load(path: str, files: Dict[str, str],
         rule_names: Iterable[str]
         ) -> Tuple[Optional[List[Finding]], List[str]]:
    """(replayed findings, changed files) — findings is None on any
    miss, with `changed` naming the modules whose hash moved (empty
    when the miss is structural: version, rule set, file set)."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None, []
    if data.get("version") != CACHE_VERSION or \
            data.get("analyzer") != _analyzer_sha():
        return None, []
    entry = data.get("entries", {}).get(_ruleset_key(rule_names))
    if entry is None or entry.get("rules") != sorted(rule_names):
        return None, []
    cached_files = entry.get("files", {})
    if set(cached_files) != set(files):
        return None, []
    changed = [p for p, sha in files.items()
               if cached_files.get(p) != sha]
    if changed:
        return None, sorted(changed)
    findings = [Finding(**rec) for rec in entry.get("findings", [])]
    return findings, []


def save(path: str, files: Dict[str, str],
         rule_names: Iterable[str],
         findings: List[Finding]) -> None:
    # merge into the existing entry table when version + analyzer
    # still match — a subset run must not evict the full gate's entry
    entries: Dict[str, dict] = {}
    try:
        with open(path) as fh:
            old = json.load(fh)
        if old.get("version") == CACHE_VERSION and \
                old.get("analyzer") == _analyzer_sha():
            entries = dict(old.get("entries", {}))
    except (OSError, ValueError):
        pass
    entries[_ruleset_key(rule_names)] = {
        "rules": sorted(rule_names),
        "files": dict(sorted(files.items())),
        "findings": [f.as_dict() for f in findings],
    }
    data = {
        "version": CACHE_VERSION,
        "analyzer": _analyzer_sha(),
        "entries": entries,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(data, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)
    except OSError:
        # a read-only checkout must not break the gate
        try:
            os.unlink(tmp)
        except OSError:
            pass

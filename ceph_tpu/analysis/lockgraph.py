"""Static lock-order pass: the lint-time twin of common/lockdep.py.

The runtime detector builds a class-level order graph ("B acquired
while holding A") from acquisitions it actually sees; whole-cluster
tests only teach it the orders tests happen to execute.  This pass
extracts the same graph from the AST — every `async with <lock>`
nesting, plus locks acquired by functions *called* while a lock is
held (transitive call summaries) — so a would-be inversion on a path
no test reaches still fails lint.

Lock classes mirror the runtime's naming:
  - `self._mutation_lock = asyncio.Lock()` on class C of module
    ceph_tpu.mds  ->  "mds.mutation"  (module tail + attr, underscores
    and the `_lock` suffix stripped)
  - `state.obj_lock(key)` -> "osd.objlock" / "osd.sublock" /
    "osd.clslock" by key prefix, the exact mapping of
    osd/daemon.py:_lock_class
  - `lockdep.guard(lock, "x.y")` -> "x.y" verbatim

Same-class nesting is allowed (the recovery wave's many object locks);
cross-class cycles are findings.  `build_lock_graph()` is also the API
tests use to cross-check that every runtime-observed lockdep edge is a
subset of this static graph.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import (
    Analyzer, FunctionInfo, ModuleInfo, Project, dotted,
)


def _attr_label(mod: ModuleInfo, attr: str) -> str:
    tail = mod.modname.split(".")[-1]
    name = attr.strip("_")
    if name.endswith("_lock"):
        name = name[: -len("_lock")]
    elif name.startswith("lock_"):
        name = name[len("lock_"):]
    return f"{tail}.{name}"


def _objlock_label(call: ast.Call) -> str:
    """Mirror of osd/daemon.py:_lock_class, applied to the key
    expression's leading string constant when one is visible."""
    prefix = ""
    if call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            prefix = arg.value
        elif isinstance(arg, ast.JoinedStr) and arg.values and \
                isinstance(arg.values[0], ast.Constant):
            prefix = str(arg.values[0].value)
    if prefix.startswith("sub\x00"):
        return "osd.sublock"
    if prefix.startswith("_cls_\x00"):
        return "osd.clslock"
    return "osd.objlock"


def classify_lock(project: Project, mod: ModuleInfo,
                  expr: ast.AST) -> Optional[str]:
    """Lock class label for an `async with <expr>` item, or None."""
    if isinstance(expr, ast.Call):
        callee = dotted(expr.func) or ""
        tail = callee.split(".")[-1]
        if tail == "obj_lock":
            return _objlock_label(expr)
        if tail == "guard" and len(expr.args) >= 2 and \
                isinstance(expr.args[1], ast.Constant) and \
                isinstance(expr.args[1].value, str):
            return expr.args[1].value
        return None
    if isinstance(expr, ast.Attribute):
        # label by the module defining the lock attr; prefer the
        # current module when it defines one of the same name.  An
        # explicit lockdep.Lock("x.y") label wins over the derived one
        # (it is what the runtime detector will record).
        if expr.attr in _own_attrs(mod):
            return mod.lock_labels.get(expr.attr) \
                or _attr_label(mod, expr.attr)
        for m in project.modules.values():
            if expr.attr in _own_attrs(m):
                return m.lock_labels.get(expr.attr) \
                    or _attr_label(m, expr.attr)
    return None


def _own_attrs(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for attrs in mod.lock_attrs.values():
        out |= attrs
    return out


@dataclass
class Edge:
    src: str
    dst: str
    mod: ModuleInfo
    node: ast.AST          # the inner acquisition (or call) site
    holder: str            # qualname of the function holding src
    via: str = ""          # callee qualname when interprocedural


class LockGraphBuilder:
    def __init__(self, project: Project):
        self.project = project
        self.edges: List[Edge] = []
        # function id -> set of lock labels it (transitively) acquires
        self._acquires: Dict[int, Set[str]] = {}
        # method name -> its unique FunctionInfo project-wide (None
        # when the name is ambiguous): the over-approximating fallback
        # for attribute calls like `self.paxos.propose(...)` that the
        # import-table resolver can't bind.  Lock analysis wants the
        # conservative direction — a spurious edge is noise, a missed
        # edge is a missed deadlock.
        self._unique_methods: Dict[str, Optional[FunctionInfo]] = {}
        for m in project.modules.values():
            for f in m.functions.values():
                if f.parent_class is None:
                    continue
                key = f.name
                self._unique_methods[key] = (
                    f if key not in self._unique_methods else None)

    # -- call resolution (extends Project's with <locals> scoping) -----

    def _resolve_call(self, fi: FunctionInfo,
                      call: ast.Call) -> Optional[FunctionInfo]:
        name = dotted(call.func)
        if name and "." not in name:
            nested = fi.module.functions.get(
                f"{fi.qualname}.<locals>.{name}")
            if nested:
                return nested
        target = self.project.resolve_function(
            fi.module, call.func, cls=fi.parent_class)
        if target is None and name and "." in name:
            target = self._unique_methods.get(name.split(".")[-1])
        return target

    # -- per-function direct acquisitions ------------------------------

    def _direct_acquires(self, fi: FunctionInfo) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.AsyncWith):
                for item in node.items:
                    label = classify_lock(
                        self.project, fi.module, item.context_expr)
                    if label:
                        out.add(label)
        return out

    def _transitive_acquires(self) -> None:
        funcs: List[FunctionInfo] = [
            fi for m in self.project.modules.values()
            for fi in m.functions.values()]
        for fi in funcs:
            self._acquires[id(fi.node)] = self._direct_acquires(fi)
        changed = True
        while changed:
            changed = False
            for fi in funcs:
                acc = self._acquires[id(fi.node)]
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Call):
                        callee = self._resolve_call(fi, node)
                        if callee is not None:
                            extra = self._acquires.get(
                                id(callee.node), set()) - acc
                            if extra:
                                acc |= extra
                                changed = True

    # -- held-context walk ---------------------------------------------

    def build(self) -> List[Edge]:
        self._transitive_acquires()
        for mod in self.project.modules.values():
            for fi in mod.functions.values():
                self._walk(fi, fi.node, [])
        return self.edges

    def _walk(self, fi: FunctionInfo, node: ast.AST,
              held: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                # nested defs are walked as their own functions (their
                # bodies run when called, not where defined); calls to
                # them are covered by the transitive summaries
                continue
            if isinstance(child, ast.AsyncWith):
                labels: List[str] = []
                for item in child.items:
                    label = classify_lock(
                        self.project, fi.module, item.context_expr)
                    if label:
                        for h in held + labels:
                            if h != label:
                                self.edges.append(Edge(
                                    h, label, fi.module,
                                    item.context_expr, fi.qualname))
                        labels.append(label)
                self._walk(fi, child, held + labels)
                continue
            if isinstance(child, ast.Call) and held:
                callee = self._resolve_call(fi, child)
                if callee is not None:
                    for label in self._acquires.get(
                            id(callee.node), ()):
                        for h in held:
                            if h != label:
                                self.edges.append(Edge(
                                    h, label, fi.module, child,
                                    fi.qualname,
                                    via=callee.qualname))
            self._walk(fi, child, held)


def build_lock_graph(project: Project) -> Tuple[
        Dict[str, Set[str]], List[Edge]]:
    """(adjacency {src: {dst,...}}, edge list with sites)."""
    edges = LockGraphBuilder(project).build()
    adj: Dict[str, Set[str]] = {}
    for e in edges:
        adj.setdefault(e.src, set()).add(e.dst)
    return adj, edges


def _reachable(adj: Dict[str, Set[str]], src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(adj.get(n, ()))
    return False


def rule_lock_order(a: Analyzer) -> None:
    adj, edges = build_lock_graph(a.project)
    reported: Set[Tuple[str, str, str, int]] = set()
    for e in edges:
        # this edge closes a cycle iff dst already reaches src
        if not _reachable(adj, e.dst, e.src):
            continue
        key = (e.mod.relpath, e.src, e.dst,
               getattr(e.node, "lineno", 0))
        if key in reported:
            continue
        reported.add(key)
        via = f" via {e.via}()" if e.via else ""
        a.emit("lock-order", e.mod, e.node,
               f"lock-order cycle: `{e.dst}` acquired{via} while "
               f"holding `{e.src}`, but the reverse order exists "
               "elsewhere — would-be deadlock (lockdep class graph)",
               symbol=e.holder)

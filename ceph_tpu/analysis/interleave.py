"""Deterministic-interleaving asyncio explorer: lockdep's schedule twin.

The static rules in rules_async.py claim "no await window is
unprotected"; this module is the runtime instrument that tries to
DRIVE the windows.  In the mold of CEPH_TPU_LOCKDEP (runtime lock-edge
recorder cross-checked against the static lock graph) and the PR-8
crash sweep ("enumerate every legal schedule mechanically" — there for
power cuts, here for await interleavings):

  InterleaveLoop   a SelectorEventLoop whose `_run_once` PERMUTES the
                   ready-queue positions of task wakeups with a seeded
                   PRNG before running them.  Any ordering of ready
                   callbacks is a legal asyncio schedule; the default
                   FIFO is merely the one schedule every test always
                   sees.  Non-task callbacks (transport plumbing,
                   timers) keep their slots — only task wakeup order
                   permutes, which is exactly the freedom a real
                   contended daemon exercises.

  recording        at each permutation the explorer records a
                   (task, await-site, locks-held) triple per task
                   about to step: the innermost ceph_tpu frame the
                   task is suspended at, plus lockdep's held-class
                   stack for that task.  tests/test_static_analysis.py
                   cross-checks runtime ⊆ static: every observed
                   await site must exist in the analyzer's
                   await-site map (callgraph.await_site_map), and a
                   site the static pass claims lock-protected must be
                   observed with that lock actually held.

Arming:

  CEPH_TPU_INTERLEAVE=1        install the policy process-wide (the
                               tier's conftest does this), every new
                               event loop permutes
  CEPH_TPU_INTERLEAVE_SEED=N   base seed (default 0); loop i of the
                               process uses seed N+i so reruns replay
                               the same schedule sequence
  explore(seed)                context manager for tests: install the
                               policy + recording for one block

Determinism contract: the schedule is a pure function of (seed, the
program's own behavior); replaying the same test with the same seed
replays the same permutations.  No wall clock, no os.urandom.

The SPMD collective plane gets the same treatment: a collective-trace
recorder (``record_collective``, armed by CEPH_TPU_COLLECTIVE_TRACE=1
for in-memory records or CEPH_TPU_COLLECTIVE_TRACE_FILE=<path> for a
per-process JSONL the multi-process harness collects) is called at
every ``multihost`` seam entry (agree / agree_healthy /
agreed_healthy / put_global / gather) and records the CALLER's
package call site.  tests/test_spmd_safety.py and the meshbench
multi-process legs cross-check runtime ⊆ static against
``collective.collective_site_map`` and assert per-process ORDER
CONGRUENCE: every process must observe the same collective sequence,
or the group was divergent (the wedge class rules_spmd.py flags
statically).
"""

from __future__ import annotations

import asyncio
import contextlib
import os

from ceph_tpu.common import flags
import random
from dataclasses import dataclass
from typing import Iterator, List, Optional, Set, Tuple

__all__ = [
    "InterleaveLoop", "InterleavePolicy", "explore", "enabled",
    "install_if_enabled", "records", "clear_records", "await_sites",
    "AwaitRecord", "CollectiveRecord", "collective_trace_armed",
    "record_collective", "collective_records",
    "clear_collective_records", "collective_sites",
]

enabled = flags.get("CEPH_TPU_INTERLEAVE") == "1"

#: cap on retained triples: the cross-check needs site coverage, not
#: an unbounded event log (a cluster test wakes tasks ~1e5 times)
RECORD_CAP = 200_000

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass(frozen=True)
class AwaitRecord:
    task_name: str
    path: str          # ceph_tpu-relative path ("ceph_tpu/osd/...")
    line: int
    locks: Tuple[str, ...]   # lockdep held-class stack at suspension


_records: List[AwaitRecord] = []
_recording = False
_loop_counter = 0


def records() -> List[AwaitRecord]:
    return list(_records)


def clear_records() -> None:
    _records.clear()


def await_sites() -> Set[Tuple[str, int]]:
    """Distinct (relpath, line) await sites observed so far."""
    return {(r.path, r.line) for r in _records}


# ---------------------------------------------------------------
# SPMD collective-trace recorder: the cross-process runtime twin
# ---------------------------------------------------------------

@dataclass(frozen=True)
class CollectiveRecord:
    kind: str          # agreement / put-global / gather / ...
    op: str            # seam entry point name (agree, gather, ...)
    path: str          # caller site, ceph_tpu-relative when in-pkg
    line: int
    topic: str         # agreement topic ("" for data collectives)
    seq: int           # per-process monotonic sequence number


_collective_records: List[CollectiveRecord] = []
_collective_seq = 0


def collective_trace_armed() -> bool:
    return bool(flags.get("CEPH_TPU_COLLECTIVE_TRACE") == "1"
                or flags.get("CEPH_TPU_COLLECTIVE_TRACE_FILE"))


def collective_records() -> List[CollectiveRecord]:
    return list(_collective_records)


def clear_collective_records() -> None:
    global _collective_seq
    _collective_records.clear()
    _collective_seq = 0


def collective_sites() -> Set[Tuple[str, int]]:
    """Distinct in-package (relpath, line) collective call sites
    observed so far — the runtime side of runtime ⊆ static."""
    return {(r.path, r.line) for r in _collective_records
            if r.path.startswith("ceph_tpu/")}


def _caller_site(depth: int) -> Optional[Tuple[str, int]]:
    """(path, lineno) of the frame `depth` levels up: the package
    call site that entered the seam.  In-package paths are
    ceph_tpu-relative (matching ModuleInfo.relpath); out-of-package
    callers (tests, scratch worker scripts) keep their basename so
    order congruence still compares across processes."""
    import sys
    try:
        f = sys._getframe(depth)
    except ValueError:
        return None
    fn = f.f_code.co_filename
    idx = fn.rfind(os.sep + "ceph_tpu" + os.sep)
    if idx >= 0:
        rel = fn[idx + 1:].replace(os.sep, "/")
    else:
        rel = os.path.basename(fn)
    return (rel, f.f_lineno)


def record_collective(op: str, kind: str, topic: str = "",
                      depth: int = 2) -> None:
    """Record one seam entry.  Cheap no-op unless armed; with
    CEPH_TPU_COLLECTIVE_TRACE_FILE set, each record is also appended
    as a JSON line so a subprocess worker's trace survives its exit
    (the multi-process harness reads the per-process files back)."""
    if not collective_trace_armed():
        return
    site = _caller_site(depth)
    if site is None:
        return
    global _collective_seq
    _collective_seq += 1
    rec = CollectiveRecord(kind=kind, op=op, path=site[0],
                           line=site[1], topic=topic,
                           seq=_collective_seq)
    if len(_collective_records) < RECORD_CAP:
        _collective_records.append(rec)
    path = flags.get("CEPH_TPU_COLLECTIVE_TRACE_FILE")
    if path:
        import json
        try:
            with open(path, "a") as fh:
                fh.write(json.dumps({
                    "kind": rec.kind, "op": rec.op, "path": rec.path,
                    "line": rec.line, "topic": rec.topic,
                    "seq": rec.seq}) + "\n")
        except OSError:  # tracing must never break the data plane
            pass


def _is_task_wakeup(handle) -> Optional[asyncio.Task]:
    """The Task this ready-queue handle steps, or None for transport/
    timer/future plumbing (which keeps its FIFO slot)."""
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    return owner if isinstance(owner, asyncio.Task) else None


def _innermost_pkg_frame(task: asyncio.Task
                         ) -> Optional[Tuple[str, int]]:
    """(relpath, lineno) of the deepest ceph_tpu frame the suspended
    task will resume in — the await site, in this package's terms."""
    try:
        frames = task.get_stack()
    except Exception:
        return None
    site = None
    for f in frames:   # outermost -> innermost
        if f.f_lasti < 0:
            # coroutine created but never stepped: f_lineno is the
            # `def` line, not a suspension point — no site to record
            continue
        fn = f.f_code.co_filename
        if os.sep + "ceph_tpu" + os.sep in fn or \
                fn.startswith(_PKG_DIR):
            rel = fn
            idx = fn.rfind(os.sep + "ceph_tpu" + os.sep)
            if idx >= 0:
                rel = fn[idx + 1:]
            site = (rel.replace(os.sep, "/"), f.f_lineno)
    return site


def _held_locks(task: asyncio.Task) -> Tuple[str, ...]:
    from ceph_tpu.common import lockdep
    return tuple(lockdep._held.get(task, ()))


class InterleaveLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop with seeded ready-task permutation."""

    def __init__(self, seed: int = 0):
        super().__init__()
        self.interleave_seed = seed
        self._ilv_rng = random.Random(seed)
        self.permutations = 0

    def _run_once(self):   # noqa: D401 - asyncio internal override
        ready = self._ready
        if len(ready) > 1:
            items = list(ready)
            idxs = [i for i, h in enumerate(items)
                    if _is_task_wakeup(h) is not None]
            if len(idxs) > 1:
                order = idxs[:]
                self._ilv_rng.shuffle(order)
                if order != idxs:
                    self.permutations += 1
                # permute IN PLACE via indexed assignment: a worker
                # thread's call_soon_threadsafe can append to _ready
                # concurrently, and clear()+extend() would silently
                # drop any handle landing between the snapshot and
                # the rebuild — the awaiting coroutine then hangs on
                # a deadlock that is the instrument's, not the code's
                for dst, src in zip(idxs, order):
                    ready[dst] = items[src]
                if _recording and len(_records) < RECORD_CAP:
                    for i in idxs:
                        task = _is_task_wakeup(items[i])
                        site = _innermost_pkg_frame(task)
                        if site is None:
                            continue
                        _records.append(AwaitRecord(
                            task_name=task.get_name(),
                            path=site[0], line=site[1],
                            locks=_held_locks(task)))
        super()._run_once()


class InterleavePolicy(asyncio.DefaultEventLoopPolicy):
    """Every new loop is an InterleaveLoop; loop i uses seed base+i so
    a multi-loop test (cluster setup/teardown cycles) stays
    deterministic end to end."""

    def __init__(self, seed: int = 0):
        super().__init__()
        self.base_seed = seed

    def new_event_loop(self):
        global _loop_counter
        loop = InterleaveLoop(self.base_seed + _loop_counter)
        _loop_counter += 1
        return loop


def install_if_enabled() -> bool:
    """conftest hook: arm the policy when CEPH_TPU_INTERLEAVE=1."""
    if not enabled:
        return False
    seed = flags.flag_int("CEPH_TPU_INTERLEAVE_SEED")
    asyncio.set_event_loop_policy(InterleavePolicy(seed))
    global _recording
    _recording = True
    return True


@contextlib.contextmanager
def explore(seed: int = 0, record: bool = True) -> Iterator[None]:
    """Run a block's event loops under seeded interleaving:

        with interleave.explore(seed=3):
            asyncio.run(cluster_scenario())
        triples = interleave.records()
    """
    global _recording, _loop_counter
    prev_policy = asyncio.get_event_loop_policy()
    prev_recording = _recording
    prev_counter = _loop_counter
    _loop_counter = 0
    asyncio.set_event_loop_policy(InterleavePolicy(seed))
    _recording = record
    try:
        yield
    finally:
        _recording = prev_recording
        _loop_counter = prev_counter
        asyncio.set_event_loop_policy(prev_policy)

"""Finding model + baseline for the static analyzer.

A Finding carries a *stable fingerprint* — a hash of (rule, file,
enclosing symbol, normalized source line) that survives line-number
drift — so the checked-in baseline (tools/lint_baseline.json)
suppresses pre-existing findings while anything NEW fails the gate,
the same ratchet discipline as a sanitizer suppression file
(WITH_ASAN/WITH_TSAN suppressions in the reference build).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

#: "info" findings are ADVISORY: worklist entries (hot-path-copy),
#: never gate failures and never baseline entries
SEVERITIES = ("error", "warning", "info")


def gating(findings: Iterable["Finding"]) -> List["Finding"]:
    """The findings that can fail the CI gate (info is advisory)."""
    return [f for f in findings if f.severity != "info"]


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, stable across checkouts
    line: int
    col: int
    message: str
    severity: str = "error"
    symbol: str = ""     # enclosing function/class qualname
    text: str = ""       # stripped source line the finding points at
    # filled by fingerprint_all (occurrence index disambiguates
    # identical lines within one symbol)
    fingerprint: str = ""

    def key(self) -> tuple:
        return (self.rule, self.path, self.symbol, self.text)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.severity}: "
                f"{self.rule}: {self.message} [{self.fingerprint}]")

    def as_dict(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "text": self.text,
            "severity": self.severity,
            "message": self.message,
        }


def fingerprint_all(findings: List[Finding]) -> List[Finding]:
    """Assign stable fingerprints; identical (rule, path, symbol, text)
    findings get an occurrence suffix in source order so each one
    baselines independently."""
    by_key: Dict[tuple, List[Finding]] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        by_key.setdefault(f.key(), []).append(f)
    for key, group in by_key.items():
        for i, f in enumerate(group):
            raw = "\x00".join((f.rule, f.path, f.symbol, f.text, str(i)))
            f.fingerprint = hashlib.sha256(
                raw.encode("utf-8", "surrogatepass")).hexdigest()[:16]
    return findings


@dataclass
class Baseline:
    """Checked-in set of accepted findings, each with a one-line
    justification (why it is defensible rather than fixed)."""

    path: Optional[str] = None
    entries: Dict[str, dict] = field(default_factory=dict)  # fp -> entry

    def __contains__(self, f: Finding) -> bool:
        return f.fingerprint in self.entries

    def stale(self, findings: Iterable[Finding]) -> List[dict]:
        """Baseline entries no longer produced (candidates to drop)."""
        live = {f.fingerprint for f in findings}
        return [e for fp, e in self.entries.items() if fp not in live]


def load_baseline(path) -> Baseline:
    with open(path) as fh:
        data = json.load(fh)
    entries = {e["fingerprint"]: e for e in data.get("findings", [])}
    return Baseline(path=str(path), entries=entries)


def write_baseline(path, findings: List[Finding],
                   old: Optional[Baseline] = None) -> None:
    """Write the current finding set as a baseline, carrying forward
    justifications already recorded for surviving fingerprints."""
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        entry = f.as_dict()
        prev = old.entries.get(f.fingerprint) if old else None
        entry["justification"] = (prev or {}).get("justification", "")
        out.append(entry)
    with open(path, "w") as fh:
        json.dump({"version": 1, "findings": out}, fh, indent=2)
        fh.write("\n")

"""SPMD collective-safety rules over the collective-site map.

The cross-process plane's contract is lockstep congruence: every
process must reach the same collectives, in the same order, under
the same verdicts.  These rules flag the static shapes that break
it:

* ``divergent-collective`` — a wedgeable collective (agreement,
  put_global/gather, allgather, barrier, lax collective) reachable
  under a process-dependent predicate (``process_index``/
  ``process_count``/host-topology reads, or names tainted by them),
  or skippable on an exception path peers don't share (an enclosing
  ``try`` whose handler neither raises nor returns), or preceded in
  the same function by a ``raise``/``return`` under a
  process-dependent predicate (the "one process bails before the
  agreement" shape).  Group-uniform kill switches
  (``is_multiprocess()``-style) take the same branch everywhere and
  are exempt.
* ``collective-order`` — an ``if`` whose two arms issue the same
  collectives in *inverted* relative order: two processes taking
  different arms deadlock against each other (A waits in collective
  X while B waits in collective Y).
* ``unguarded-collective-timeout`` — a coordinator-KV wait without a
  hard timeout argument, a KV call outside the ``multihost.agree``
  seam (ad-hoc half-protocols must ride the agreement discipline),
  or an untimed global barrier: a dead host must read as a timeout,
  never a wedge.
* ``topology-stale-state`` — a module-level cache keyed by a
  device-id-derived expression in a function that never consults
  ``topology_signature()``/mesh-signature: the same chips under a
  different cluster shape (1x8 vs 2x4) replay stale state — the
  stale-plan-after-shrink/join class that elasticity makes hot.

All four are path-scoped to the cross-process tier
(``ceph_tpu/parallel/``, ``ceph_tpu/ec/``) via ``spmd_paths``-family
config keys, mirroring the other production-scoped rules.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ceph_tpu.analysis.collective import (
    WEDGEABLE, CollectiveSite, collect_sites)

_SPMD_PATHS = ("ceph_tpu/parallel/", "ceph_tpu/ec/")
_SEAM_PATHS = ("ceph_tpu/parallel/multihost.py",)

# function-body mentions that mark a cache key as topology-aware
_TOPO_AWARE = {"topology_signature", "_topology", "mesh_sig",
               "_mesh_sig"}


def _scoped_sites(a, key: str, default=_SPMD_PATHS) -> List[
        CollectiveSite]:
    paths = a.config.get(key, default)
    out = []
    for s in collect_sites(a.project):
        rel = s.mod.relpath.replace("\\", "/")
        if any(p in rel for p in paths):
            out.append(s)
    return out


def rule_divergent_collective(a) -> None:
    """Wedgeable collectives whose reachability is process-dependent."""
    for s in _scoped_sites(a, "spmd_paths"):
        if s.kind not in WEDGEABLE:
            continue
        if s.process_branches:
            line, name = s.process_branches[0]
            a.emit("divergent-collective", s.mod, s.node,
                   f"{s.kind} collective `{s.callee}` is guarded by a "
                   f"process-dependent predicate (`{name}` at line "
                   f"{line}): processes taking different branches "
                   "skip it and peers wedge (or retire a live host)",
                   symbol=s.qualname, scope_line=s.scope_line)
        elif s.swallow_line:
            a.emit("divergent-collective", s.mod, s.node,
                   f"{s.kind} collective `{s.callee}` sits in a try "
                   f"(line {s.swallow_line}) whose handler neither "
                   "raises nor returns: on a local exception this "
                   "process silently skips the collective and "
                   "continues with state its peers don't share",
                   symbol=s.qualname, scope_line=s.scope_line)
        elif s.prior_divergent_exits:
            line, name = s.prior_divergent_exits[0]
            a.emit("divergent-collective", s.mod, s.node,
                   f"{s.kind} collective `{s.callee}` follows a "
                   f"raise/return at line {line} guarded by "
                   f"process-dependent `{name}`: a process exiting "
                   "there never reaches the collective its peers "
                   "block in",
                   symbol=s.qualname, scope_line=s.scope_line)


def _order_token(s: CollectiveSite) -> str:
    """Identity of a collective for ordering: callee plus the static
    prefix of its first argument (the topic distinguishes two agree()
    calls)."""
    tok = s.callee
    if s.node.args:
        arg = s.node.args[0]
        if isinstance(arg, ast.Constant):
            tok += ":" + repr(arg.value)
        elif isinstance(arg, ast.JoinedStr):
            head = arg.values[0] if arg.values else None
            if isinstance(head, ast.Constant):
                tok += ":" + repr(head.value)
    return tok


def _arm_tokens(a, sites: List[CollectiveSite],
                block: List[ast.stmt],
                mod) -> List[str]:
    from ceph_tpu.analysis.collective import _in_block

    return [_order_token(s) for s in sites
            if _in_block(s.node, block, mod.parents)]


def rule_collective_order(a) -> None:
    """Branch arms that issue the same collectives in inverted order."""
    sites = [s for s in _scoped_sites(a, "spmd_paths")
             if s.kind in WEDGEABLE]
    by_mod: Dict[str, List[CollectiveSite]] = {}
    for s in sites:
        by_mod.setdefault(s.mod.relpath, []).append(s)
    for rel, mod_sites in by_mod.items():
        mod = mod_sites[0].mod
        seen_ifs = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.If) or not node.orelse:
                continue
            if id(node) in seen_ifs:
                continue
            seen_ifs.add(id(node))
            body = _arm_tokens(a, mod_sites, node.body, mod)
            other = _arm_tokens(a, mod_sites, node.orelse, mod)
            common = [t for t in dict.fromkeys(body)
                      if t in other]
            if len(common) < 2:
                continue
            body_order = [t for t in body if t in common]
            other_order = [t for t in other if t in common]
            # compare first-occurrence order of the shared tokens
            first_b = list(dict.fromkeys(body_order))
            first_o = list(dict.fromkeys(other_order))
            if first_b != first_o:
                a.emit("collective-order", mod, node,
                       "branch arms issue the same collectives in "
                       f"different relative order ({first_b} vs "
                       f"{first_o}): two processes taking different "
                       "arms block in different collectives and "
                       "deadlock against each other",
                       symbol=mod_sites[0].qualname,
                       scope_line=mod_sites[0].scope_line)


def rule_unguarded_collective_timeout(a) -> None:
    """Coordinator-KV waits and barriers outside the hard-timeout
    discipline."""
    seam = a.config.get("spmd_seam_paths", _SEAM_PATHS)
    for s in collect_sites(a.project):
        if s.kind not in ("kv-wait", "kv-set", "barrier"):
            continue
        rel = s.mod.relpath.replace("\\", "/")
        in_seam = any(p in rel for p in seam)
        if s.kind == "barrier" and s.callee.endswith(
                "sync_global_devices"):
            a.emit("unguarded-collective-timeout", s.mod, s.node,
                   f"`{s.callee}` is an untimed global barrier: a "
                   "dead host wedges every peer forever — ride "
                   "`multihost.agree`, whose per-peer KV waits turn "
                   "a dead host into a timeout verdict",
                   symbol=s.qualname, scope_line=s.scope_line)
            continue
        if not in_seam:
            a.emit("unguarded-collective-timeout", s.mod, s.node,
                   f"coordinator-KV call `{s.callee}` outside the "
                   "multihost.agree seam: ad-hoc KV protocols bypass "
                   "the hard-timeout + agreement discipline — route "
                   "through `multihost.agree`",
                   symbol=s.qualname, scope_line=s.scope_line)
            continue
        if s.kind in ("kv-wait", "barrier") and not s.has_timeout:
            a.emit("unguarded-collective-timeout", s.mod, s.node,
                   f"blocking KV wait `{s.callee}` has no hard "
                   "timeout argument: a dead peer must read as a "
                   "timeout, never a wedge",
                   symbol=s.qualname, scope_line=s.scope_line)


def _module_cache_names(mod) -> set:
    names = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign):    # `_c: Dict[..] = {}`
            targets = [node.target]
            value = node.value
        else:
            continue
        is_dict = isinstance(value, ast.Dict) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "dict")
        if not is_dict:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and "cache" in t.id.lower():
                names.add(t.id)
    return names


def _device_derived(expr: ast.AST, fn: ast.AST) -> bool:
    """The key expression (or, for a bare name, its assignment in the
    function) derives from device identities (`d.id` over a device
    collection)."""
    def _reads_ids(e: ast.AST) -> bool:
        return any(isinstance(n, ast.Attribute) and n.attr == "id"
                   for n in ast.walk(e))

    if _reads_ids(expr):
        return True
    if isinstance(expr, ast.Name):
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in n.targets) and _reads_ids(n.value):
                return True
    return False


def rule_topology_stale_state(a) -> None:
    """Device-set-keyed module caches missing the topology signature."""
    paths = a.config.get("spmd_state_paths", _SPMD_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        caches = _module_cache_names(mod)
        if not caches:
            continue
        for fi in mod.functions.values():
            fn = fi.node
            mentions = {n.attr for n in ast.walk(fn)
                        if isinstance(n, ast.Attribute)}
            mentions |= {n.id for n in ast.walk(fn)
                         if isinstance(n, ast.Name)}
            if mentions & _TOPO_AWARE:
                continue
            flagged = set()
            for node in ast.walk(fn):
                cache: Optional[str] = None
                key: Optional[ast.AST] = None
                if isinstance(node, ast.Subscript) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in caches:
                    cache, key = node.value.id, node.slice
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "get" and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in caches and node.args:
                    cache, key = node.func.value.id, node.args[0]
                if cache is None or cache in flagged or key is None:
                    continue
                if not _device_derived(key, fn):
                    continue
                flagged.add(cache)
                a.emit("topology-stale-state", mod, node,
                       f"cache `{cache}` is keyed by a device-id set "
                       "but the key never folds in "
                       "`topology_signature()`: the same chips under "
                       "a different cluster shape (1x8 vs 2x4) "
                       "replay stale state after a shrink/join",
                       symbol=fi.qualname, scope_line=fi.lineno)

"""Interprocedural layer: whole-program call graph + async-context map.

PR-1's rules were per-function AST walks; every hard concurrency bug
PRs 3-10 fixed lived BETWEEN functions — a suspension point reached
through a helper, a lock scope whose protection a callee assumed, a
blocking syscall three frames below an `async def`.  This module adds
the whole-program facts those rules need:

  CallGraph          module-resolved edges (import table + `self.`
                     method binding + unique-method fallback, the
                     lockgraph.py resolution discipline), with
                     memoized transitive *blocking* summaries: the
                     helper-chain proof that a sync file/socket/sleep
                     call is reachable from a given function.

  async context      per-function map of every SUSPENSION POINT
                     (`await`, `async with` enter, `async for` step):
                     which lockdep-classified lock scopes lexically
                     enclose it, whether a try/finally covers it, and
                     whether it rides `asyncio.shield`.  This is the
                     static twin of what the interleave explorer
                     (analysis/interleave.py) observes at runtime —
                     `await_site_map()` is the universe the
                     runtime⊆static cross-check tests against.

  atomicity windows  read-modify-write of `self.` state whose read and
                     write straddle a suspension point: the PR-3 bug
                     class, exported with protection verdicts so the
                     runtime explorer can falsify a "protected by lock
                     X" claim it drives through unlocked.

Everything is pure AST — nothing is imported or executed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import (
    FunctionInfo, Project, dotted,
)
from ceph_tpu.analysis.lockgraph import classify_lock

__all__ = [
    "CallGraph", "FunctionAsyncContext", "SuspensionPoint",
    "AtomicityWindow", "async_context", "atomicity_windows",
    "function_atomicity_windows", "await_site_map",
    "walk_scope_ordered",
]

_SCOPE_BOUNDARIES = (ast.FunctionDef, ast.AsyncFunctionDef,
                     ast.ClassDef, ast.Lambda)


def walk_scope_ordered(root: ast.AST) -> Iterator[ast.AST]:
    """Walk one function scope in SOURCE order (depth-first, children
    after parents), stopping at nested def/class boundaries.  Source
    order matters here: the atomicity and cancellation rules reason
    about what happens *between* two statements."""
    stack = list(reversed(list(ast.iter_child_nodes(root))))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _SCOPE_BOUNDARIES):
            stack.extend(
                reversed(list(ast.iter_child_nodes(node))))


# ---------------------------------------------------------------------
# call graph
# ---------------------------------------------------------------------


class CallGraph:
    """Resolved call edges over a Project, plus transitive blocking
    summaries.

    Resolution mirrors lockgraph.LockGraphBuilder: the import table
    first, then `self.method` bound through the enclosing class, then
    nested `<locals>` defs, and finally the unique-method fallback
    (a method name with exactly ONE project-wide definition binds to
    it).  The conservative direction is deliberate: for blocking
    reachability a spurious edge is noise to triage once, a missed
    edge is a daemon stall no test reproduces.
    """

    def __init__(self, project: Project,
                 blocking_exempt: Tuple[str, ...] = ()):
        self.project = project
        #: callees treated as NON-blocking by blocking_chain — the
        #: memoized one-shot inits (native.get_lib) prewarmed off-loop
        #: at msgr bind/connect, where the steady-state call is a dict
        #: read.  Entries with a "." match the module-qualified name
        #: ("ceph_tpu.native.get_lib"), bare entries match any function
        #: of that name project-wide (test/config convenience)
        self.blocking_exempt = frozenset(blocking_exempt)
        self._unique_methods: Dict[str, Optional[FunctionInfo]] = {}
        for m in project.modules.values():
            for f in m.functions.values():
                if f.parent_class is None:
                    continue
                self._unique_methods[f.name] = (
                    f if f.name not in self._unique_methods else None)
        # id(fi.node) -> [(call node, callee FunctionInfo), ...]
        self._callees: Dict[int, List[Tuple[ast.Call,
                                            FunctionInfo]]] = {}
        # id(fi.node) -> blocking chain ([qualnames..., blocking-callee
        # string]) or None when nothing blocking is reachable
        self._blocking: Dict[int, Optional[List[str]]] = {}

    def resolve(self, fi: FunctionInfo,
                call: ast.Call) -> Optional[FunctionInfo]:
        name = dotted(call.func)
        if name and "." not in name:
            nested = fi.module.functions.get(
                f"{fi.qualname}.<locals>.{name}")
            if nested is not None:
                return nested
        target = self.project.resolve_function(
            fi.module, call.func, cls=fi.parent_class)
        if target is None and name and "." in name:
            target = self._unique_methods.get(name.split(".")[-1])
        return target

    def callees(self, fi: FunctionInfo
                ) -> List[Tuple[ast.Call, FunctionInfo]]:
        """Resolved (call site, callee) pairs in fi's own scope."""
        cached = self._callees.get(id(fi.node))
        if cached is not None:
            return cached
        out: List[Tuple[ast.Call, FunctionInfo]] = []
        for node in walk_scope_ordered(fi.node):
            if isinstance(node, ast.Call):
                callee = self.resolve(fi, node)
                if callee is not None:
                    out.append((node, callee))
        self._callees[id(fi.node)] = out
        return out

    # -- transitive blocking summaries ---------------------------------

    def blocking_chain(self, fi: FunctionInfo,
                       _stack: Optional[Set[int]] = None
                       ) -> Optional[List[str]]:
        """First-found helper chain from `fi` to an event-loop-
        blocking call through SYNC functions only, as
        [qualname, qualname, ..., "open"/"time.sleep"/...], or None.

        Async callees are excluded on purpose: their bodies are judged
        as their own `async def` scopes (awaiting them never blocks
        the loop), so this summary answers exactly "does calling this
        SYNC helper stall the loop".  Calls deferred through a lambda
        get the same benefit of the doubt the direct rule gives them.
        """
        from ceph_tpu.analysis.rules import (
            _BLOCKING_CALLS, _BLOCKING_PREFIXES, _inside_lambda,
            _resolved_callee, walk_scope,
        )

        key = id(fi.node)
        if key in self._blocking:
            return self._blocking[key]
        if _stack is None:
            _stack = set()
        if key in _stack:        # recursion: no new blocking evidence
            return None
        root = not _stack
        _stack.add(key)
        chain: Optional[List[str]] = None
        for node in walk_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolved_callee(fi.module, node)
            blocking = (callee == "open"
                        or callee in _BLOCKING_CALLS
                        or callee.startswith(_BLOCKING_PREFIXES))
            if blocking and not _inside_lambda(fi.module, node):
                chain = [fi.qualname, callee]
                break
        if chain is None:
            for call, callee_fi in self.callees(fi):
                if callee_fi.is_async or callee_fi.node is fi.node:
                    continue
                if (callee_fi.name in self.blocking_exempt
                        or f"{callee_fi.module.modname}."
                           f"{callee_fi.name}" in self.blocking_exempt):
                    continue
                if _inside_lambda(fi.module, call):
                    continue
                sub = self.blocking_chain(callee_fi, _stack)
                if sub is not None:
                    chain = [fi.qualname] + sub
                    break
        _stack.discard(key)
        # a None computed mid-recursion may only mean "the rest of this
        # path is on the stack" (a cycle member pruned, not proven
        # clean) — caching it would hide that member's real blocking
        # chain from every later caller.  Positive chains are concrete
        # paths and always safe to keep.
        if chain is not None or root:
            self._blocking[key] = chain
        return chain


# ---------------------------------------------------------------------
# async-context map
# ---------------------------------------------------------------------


@dataclass
class SuspensionPoint:
    """One place a coroutine can yield the event loop."""

    node: ast.AST
    kind: str                  # "await" | "async-with" | "async-for"
    line: int
    end_line: int
    #: lockdep class labels of every classified `async with` lock
    #: scope lexically enclosing this point
    locks: Tuple[str, ...]
    #: ids of the enclosing classified AsyncWith nodes (scope
    #: identity: two separate `async with self._lock` blocks share a
    #: label but not a scope)
    lock_scopes: Tuple[int, ...]
    #: True when a try/finally within the function covers this point
    in_try_finally: bool
    #: True for `await asyncio.shield(...)`
    shielded: bool


@dataclass
class FunctionAsyncContext:
    fi: FunctionInfo
    suspensions: List[SuspensionPoint] = field(default_factory=list)

    def between(self, lo: int, hi: int) -> List[SuspensionPoint]:
        """Suspension points strictly between two source lines."""
        return [s for s in self.suspensions if lo < s.line < hi]


def _is_shield(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (dotted(node.func) or "").split(".")[-1] == "shield")


class _CtxBuilder:
    """Single-pass visitor tracking (held lock scopes, try/finally
    coverage) down one function body."""

    def __init__(self, project: Project, fi: FunctionInfo):
        self.project = project
        self.fi = fi
        self.out = FunctionAsyncContext(fi)
        #: id(node) -> (lock labels, lock scope ids) for every node
        self.scope_of: Dict[int, Tuple[Tuple[str, ...],
                                       Tuple[int, ...]]] = {}

    def build(self) -> FunctionAsyncContext:
        for child in ast.iter_child_nodes(self.fi.node):
            self._visit(child, (), (), False)
        self.out.suspensions.sort(key=lambda s: s.line)
        return self.out

    def _add(self, node: ast.AST, kind: str,
             locks: Tuple[str, ...], scopes: Tuple[int, ...],
             in_finally: bool, shielded: bool = False) -> None:
        self.out.suspensions.append(SuspensionPoint(
            node=node, kind=kind, line=getattr(node, "lineno", 0),
            end_line=getattr(node, "end_lineno",
                             getattr(node, "lineno", 0)),
            locks=locks, lock_scopes=scopes,
            in_try_finally=in_finally, shielded=shielded))

    def _visit(self, node: ast.AST, locks: Tuple[str, ...],
               scopes: Tuple[int, ...], in_finally: bool) -> None:
        if isinstance(node, _SCOPE_BOUNDARIES):
            return   # nested scopes are judged as their own functions
        self.scope_of[id(node)] = (locks, scopes)
        if isinstance(node, ast.Await):
            self._add(node, "await", locks, scopes, in_finally,
                      shielded=_is_shield(node.value))
        elif isinstance(node, ast.AsyncWith):
            # __aenter__/__aexit__ are suspension points themselves,
            # recorded OUTSIDE the scopes the items introduce
            self._add(node, "async-with", locks, scopes, in_finally)
            inner_locks, inner_scopes = list(locks), list(scopes)
            for item in node.items:
                label = classify_lock(self.project, self.fi.module,
                                      item.context_expr)
                if label:
                    inner_locks.append(label)
                    inner_scopes.append(id(node))
                self._visit(item.context_expr, locks, scopes,
                            in_finally)
            for stmt in node.body:
                self._visit(stmt, tuple(inner_locks),
                            tuple(inner_scopes), in_finally)
            return
        elif isinstance(node, ast.AsyncFor):
            self._add(node, "async-for", locks, scopes, in_finally)
        elif isinstance(node, ast.Try) and node.finalbody:
            # everything under the try/else/handlers is cleanup-
            # covered; the finalbody itself keeps the outer coverage
            for stmt in node.body + node.orelse:
                self._visit(stmt, locks, scopes, True)
            for handler in node.handlers:
                for stmt in handler.body:
                    self._visit(stmt, locks, scopes, True)
            for stmt in node.finalbody:
                self._visit(stmt, locks, scopes, in_finally)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, locks, scopes, in_finally)


def _built_ctx(project: Project, fi: FunctionInfo
               ) -> Tuple[FunctionAsyncContext,
                          Dict[int, Tuple[Tuple[str, ...],
                                          Tuple[int, ...]]]]:
    """Build (or replay) one function's suspension map.  Memoized on
    the Project: three consumers walk the same bodies per run
    (await-atomicity via function_atomicity_windows, the cancellation
    rule via async_context, the cross-check via await_site_map) and
    the map is a pure function of the AST the Project owns."""
    cache = getattr(project, "_async_ctx_cache", None)
    if cache is None:
        cache = project._async_ctx_cache = {}
    key = id(fi.node)
    hit = cache.get(key)
    if hit is None:
        builder = _CtxBuilder(project, fi)
        hit = cache[key] = (builder.build(), builder.scope_of)
    return hit


def async_context(project: Project,
                  fi: FunctionInfo) -> FunctionAsyncContext:
    """The suspension-point map of one function."""
    return _built_ctx(project, fi)[0]


# ---------------------------------------------------------------------
# atomicity windows (the PR-3 bug class, exported for the explorer)
# ---------------------------------------------------------------------


@dataclass
class AtomicityWindow:
    """A `self.<attr>` read-modify-write straddling a suspension."""

    fi: FunctionInfo
    attr: str
    read_line: int
    write_node: ast.AST
    write_line: int
    suspensions: List[SuspensionPoint]
    #: lock labels whose SCOPE (the same `async with` node) covers
    #: both read and write — non-empty means statically protected
    protecting: Tuple[str, ...]

    @property
    def protected(self) -> bool:
        return bool(self.protecting)


def _attr_reads(expr: ast.AST, shared: Set[str]) -> Set[Tuple[str,
                                                              str]]:
    """(receiver, attr) pairs read in expr, for receivers in the
    shared set (`self`, parameters, and locals derived from them)."""
    out: Set[Tuple[str, str]] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in shared and \
                isinstance(node.ctx, ast.Load):
            out.add((node.value.id, node.attr))
    return out


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _write_target_attr(target: ast.AST,
                       shared: Set[str]) -> Optional[Tuple[str, str]]:
    """`recv.X = ...` / `recv.X[k] = ...` -> ("recv", "X") for shared
    receivers."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and \
            target.value.id in shared:
        return (target.value.id, target.attr)
    return None


def _assign_name_targets(targets: List[ast.AST]) -> List[str]:
    out: List[str] = []
    for t in targets:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            out.extend(e.id for e in t.elts
                       if isinstance(e, ast.Name))
    return out


def _contains_await(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Await) for n in ast.walk(expr))


def function_atomicity_windows(project: Project, fi: FunctionInfo
                               ) -> List[AtomicityWindow]:
    """RMW-across-suspension windows in one async function.

    Shape recognized (the PR-3 version-allocation bug, literally):

        v = self.next_version          # read
        ... await <anything> ...       # suspension: another task can
                                       # read the SAME value here
        self.next_version = v + 1      # write derived from the read

    plus the one-statement forms `self.x = self.x + await f()` and
    `self.x += await f()` (Python loads the target BEFORE evaluating
    an augmented assignment's value, so the await splits the RMW).

    State is any `<recv>.attr` whose receiver is SHARED across tasks:
    `self`, a parameter (the daemon passes PGState/Connection objects
    around), or a local derived from one (`q = self._buckets[key]`) —
    a local bound to a freshly constructed object is task-private and
    exempt.

    A window is *protected* when one `async with <lockdep lock>` NODE
    lexically encloses both the read and the write — the same label in
    two separate blocks does NOT protect (the suspension between them
    runs unlocked).  Reads are Assign-value reads flowing into locals
    (taint is killed by reassignment from clean expressions); if-tests
    and membership checks are out of scope by design — check-then-act
    is a different, far noisier class than lost-update RMW.
    """
    if not fi.is_async:
        return []
    ctx, scope_of = _built_ctx(project, fi)
    if not ctx.suspensions:
        return []

    def scopes_at(node: ast.AST) -> Tuple[Tuple[str, ...],
                                          Tuple[int, ...]]:
        return scope_of.get(id(node), ((), ()))

    # shared receivers: self + params, grown by derivation, shrunk by
    # rebinding to fresh objects
    shared: Set[str] = set(fi.params) | {"self"}

    windows: List[AtomicityWindow] = []
    # local name -> ((recv, attr) it carries, read line, read scopes)
    taint: Dict[str, Tuple[Tuple[str, str], int, Tuple[int, ...]]] = {}

    def flag(stmt: ast.AST, key: Tuple[str, str], read_line: int,
             read_scopes: Tuple[int, ...],
             spans: List[SuspensionPoint]) -> None:
        labels, scopes = scopes_at(stmt)
        common = set(read_scopes) & set(scopes)
        protecting = tuple(sorted({
            lbl for lbl, sc in zip(labels, scopes) if sc in common}))
        windows.append(AtomicityWindow(
            fi=fi, attr=".".join(key), read_line=read_line,
            write_node=stmt, write_line=getattr(stmt, "lineno", 0),
            suspensions=spans, protecting=protecting))

    for stmt in walk_scope_ordered(fi.node):
        if isinstance(stmt, ast.Assign):
            line = getattr(stmt, "lineno", 0)
            end = getattr(stmt, "end_lineno", line)
            w_attrs = [a for a in
                       (_write_target_attr(t, shared)
                        for t in stmt.targets) if a]
            if w_attrs and _contains_await(stmt.value):
                # one-statement RMW: self.x = f(self.x, await g())
                reads_here = _attr_reads(stmt.value, shared)
                spans = [s for s in ctx.suspensions
                         if line <= s.line <= end]
                for key in w_attrs:
                    if key in reads_here:
                        flag(stmt, key, line, scopes_at(stmt)[1],
                             spans)
            if w_attrs:
                # two-statement RMW: write derives from a tainted local
                for key in w_attrs:
                    for name in _names_in(stmt.value):
                        t = taint.get(name)
                        if t is None or t[0] != key:
                            continue
                        read_line, read_scopes = t[1], t[2]
                        spans = [s for s in ctx.suspensions
                                 if read_line < s.line < line]
                        if spans:
                            flag(stmt, key, read_line, read_scopes,
                                 spans)
                        break
            # taint + shared-receiver bookkeeping: targets assigned
            # from shared state carry it; reassignment from a clean
            # value kills both
            attrs_read = _attr_reads(stmt.value, shared)
            derives = bool(_names_in(stmt.value) & shared)
            for name in _assign_name_targets(stmt.targets):
                if attrs_read:
                    taint[name] = (sorted(attrs_read)[0], line,
                                   scopes_at(stmt)[1])
                else:
                    taint.pop(name, None)
                if derives:
                    shared.add(name)
                else:
                    shared.discard(name)
        elif isinstance(stmt, ast.AugAssign):
            key = _write_target_attr(stmt.target, shared)
            if key is not None and _contains_await(stmt.value):
                # self.x += await f(): target loads before the await
                line = getattr(stmt, "lineno", 0)
                end = getattr(stmt, "end_lineno", line)
                spans = [s for s in ctx.suspensions
                         if line <= s.line <= end]
                flag(stmt, key, line, scopes_at(stmt)[1], spans)
    return windows


def atomicity_windows(project: Project,
                      paths: Tuple[str, ...] = ()
                      ) -> List[AtomicityWindow]:
    """All RMW-across-suspension windows in async functions under
    `paths` (module relpath substrings; empty = whole project)."""
    out: List[AtomicityWindow] = []
    for mod in project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if paths and not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            out.extend(function_atomicity_windows(project, fi))
    return out


def await_site_map(project: Project) -> Dict[Tuple[str, int], dict]:
    """The static universe of suspension points, keyed by
    (module relpath, source line) with every line the suspension's
    statement spans included — the runtime⊆static contract surface:
    any await site the interleave explorer observes inside the package
    must appear here, or the async-context map is blind to a coroutine
    the runtime actually runs.

    Values carry {"qualname", "kind", "locks"} — `locks` is the
    statically-claimed lockdep class set held at that point, which the
    explorer cross-checks against lockdep's runtime held-stack.  When
    spans overlap (an `async with` header statement covers its whole
    body), the NARROWEST span owns each line — a task suspended at an
    inner await's line is at that await, so the inner scope's stronger
    lock claim is the correct one; equal-width overlaps keep the
    intersection (never claim a lock that isn't lexically certain).
    """
    out: Dict[Tuple[str, int], dict] = {}
    width: Dict[Tuple[str, int], int] = {}
    for mod in project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            ctx = async_context(project, fi)
            for s in ctx.suspensions:
                w = s.end_line - s.line
                for line in range(s.line, s.end_line + 1):
                    key = (rel, line)
                    if key not in out or w < width[key]:
                        out[key] = {"qualname": fi.qualname,
                                    "kind": s.kind,
                                    "locks": set(s.locks)}
                        width[key] = w
                    elif w == width[key]:
                        out[key]["locks"] &= set(s.locks)
    return out

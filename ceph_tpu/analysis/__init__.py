"""ceph_tpu.analysis: rule-based static analyzer for this codebase.

Pure-AST lint pass over the package catching the hazard classes the
runtime test tier can't see until a test happens to trip them: Python
side effects and host syncs traced into `@jax.jit` kernels, silent
uint8 overflow in the GF(2^8) paths, jit recompilation hazards, bare
numpy on traced arrays, direct jax.jit in the EC dispatch layers
bypassing the ExecPlan cache (ec/plan.py), event-loop-blocking calls
inside the asyncio daemons, static lock-order cycles (the lint-time
twin of common/lockdep.py), and un-awaited asyncio.Lock acquisition —
plus, on the interprocedural callgraph.py layer (module-resolved call
graph + async-context map), await-atomicity windows, cancellation-
unsafe acquires, transitive blocking calls, the hot-path-copy
zero-copy worklist, and stale-suppression hygiene; rules_async.py
holds those rules and analysis/interleave.py their runtime twin (the
deterministic-interleaving explorer, CEPH_TPU_INTERLEAVE=1).

PR 16 adds the SPMD collective-safety family: collective.py maps
every collective call site (multihost.agree*/put_global/gather,
process_allgather, coordinator-KV barriers, lax collectives) with
its enclosing control-flow predicates, exception paths and timeout
guards, and rules_spmd.py applies divergent-collective,
collective-order, unguarded-collective-timeout and
topology-stale-state over it.  Their runtime twin is interleave.py's
collective-trace recorder (CEPH_TPU_COLLECTIVE_TRACE=1), cross-
checked runtime ⊆ static with per-process order congruence by a real
2-process group in tests/test_spmd_safety.py; baselined SPMD
findings are ratchet-pinned at zero by tools/collective_ratchet.json.

Run as a gate:  python -m ceph_tpu.analysis [paths]   (exit 0/1)
Run in tests:   tests/test_static_analysis.py (tier-1)
Suppress:       `# lint: disable=<rule>` inline, or baseline a
                finding with a justification in
                tools/lint_baseline.json (regenerate with
                `python -m ceph_tpu.analysis --write-baseline`).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from ceph_tpu.analysis.core import (          # noqa: F401
    Analyzer, FunctionInfo, ModuleInfo, Project, build_project,
)
from ceph_tpu.analysis.findings import (      # noqa: F401
    Baseline, Finding, load_baseline, write_baseline,
)
from ceph_tpu.analysis.lockgraph import build_lock_graph  # noqa: F401
from ceph_tpu.analysis.rules import default_rules         # noqa: F401

#: repo-relative location of the checked-in baseline
BASELINE_RELPATH = os.path.join("tools", "lint_baseline.json")


def default_baseline_path() -> Optional[str]:
    """tools/lint_baseline.json under the repo root (the package's
    parent), falling back to the current directory."""
    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    for root in (pkg_parent, os.getcwd()):
        cand = os.path.join(root, BASELINE_RELPATH)
        if os.path.exists(cand):
            return cand
    return None


def analyze_paths(paths: List[str], rules=None,
                  config: Optional[dict] = None
                  ) -> Tuple[List[Finding], Project]:
    """Parse + run the rule set; returns (fingerprinted findings,
    project).  `rules` narrows to a subset of rule names."""
    project = build_project(paths)
    all_rules = default_rules()
    if rules is not None:
        all_rules = {k: v for k, v in all_rules.items() if k in rules}
    analyzer = Analyzer(project, all_rules, config=config)
    return analyzer.run(), project

"""AST framework for the static analyzer.

Pure-AST (nothing is imported or executed): every .py file under the
scan roots is parsed into a ModuleInfo, cross-module references are
resolved through each module's import table, and the *traced set* —
functions whose bodies run under jax tracing — is computed as a
fixpoint: decorator-traced seeds (`@jax.jit`,
`@functools.partial(jax.jit, ...)`, vmap/pmap/grad), call-site wraps
(`jax.jit(f)`, `pl.pallas_call(f, ...)`, `jax.lax.fori_loop(.., body,
..)`), defs nested inside traced functions, plus everything a traced
body calls that resolves to a function in the scanned package.

Suppressions: `# lint: disable=rule-a,rule-b` on the finding's line
(or the line above) silences those rules there; on a `def` line it
covers the whole function; `# lint: disable-file=rule` anywhere
silences the rule for the module.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis.findings import Finding, fingerprint_all

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([\w,\- ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*lint:\s*disable-file=([\w,\- ]+)")

# decorator / wrapper names that put a function body under jax tracing
_TRACING_WRAPPERS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad",
    "pallas_call", "shard_map", "remat", "checkpoint", "custom_vjp",
    "custom_jvp",
}
# jax.lax control-flow HOFs: (attr name, positions of traced callables)
_LAX_HOFS = {
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2, 3),
    "switch": (1,),
    "map": (0,),
    "associative_scan": (0,),
}


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def dynamic_names_in(e: ast.AST) -> Set[str]:
    """Names in an expression, excluding those reached only through
    `.shape`/`.ndim`/`.dtype`/`.size` — static metadata under jit, so
    a value derived from them is a plain Python int, not a tracer."""
    out: Set[str] = set()

    def rec(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
        for c in ast.iter_child_nodes(n):
            rec(c)

    rec(e)
    return out


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FunctionInfo:
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"
    qualname: str                  # e.g. "OSDDaemon.handle_op" / "f.<locals>.g"
    parent_class: Optional[str]
    is_async: bool
    params: List[str] = field(default_factory=list)
    static_params: Set[str] = field(default_factory=set)
    traced_by: Optional[str] = None   # why this function is traced
    jit_decorated: bool = False       # directly under a jit decorator

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def lineno(self) -> int:
        return self.node.lineno


@dataclass
class ModuleInfo:
    path: str
    relpath: str                   # repo-relative (fingerprint-stable)
    modname: str                   # dotted; __init__.py -> package name
    tree: ast.Module
    lines: List[str]
    suppress: Dict[int, Set[str]] = field(default_factory=dict)
    file_suppress: Set[str] = field(default_factory=set)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    # local name -> (module dotted path, attr-or-None)
    imports: Dict[str, Tuple[str, Optional[str]]] = field(
        default_factory=dict)
    # class name -> attrs assigned asyncio.Lock() somewhere in the class
    lock_attrs: Dict[str, Set[str]] = field(default_factory=dict)
    # attr -> explicit class label from lockdep.Lock("x.y")
    lock_labels: Dict[str, str] = field(default_factory=dict)
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def is_suppressed(self, rule: str, line: int,
                      scope_line: int = 0) -> bool:
        return self.suppression_match(rule, line, scope_line) \
            is not None

    def suppression_match(self, rule: str, line: int,
                          scope_line: int = 0) -> Optional[int]:
        """The comment line whose suppression covers this finding
        (-1 for a file-wide suppression), or None.  The Analyzer
        records matches so rule unused-suppression can flag the
        comments that covered nothing."""
        for ln in (line, line - 1, scope_line):
            if ln and rule in self.suppress.get(ln, ()):
                return ln
        if rule in self.file_suppress:
            return -1
        return None


def _package_root(path: str) -> Tuple[str, str]:
    """(repo_root, dotted module name) for a .py file, walking the
    __init__.py chain upward; a packageless file is named by its stem
    and rooted at its own directory."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    parts.reverse()
    if parts[-1] == "__init__":
        parts.pop()
    return d, ".".join(parts)


def parse_module(path: str) -> ModuleInfo:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    root, modname = _package_root(path)
    mod = ModuleInfo(
        path=os.path.abspath(path),
        relpath=os.path.relpath(os.path.abspath(path), root),
        modname=modname,
        tree=ast.parse(src, filename=path),
        lines=src.splitlines(),
    )
    # suppressions are honoured only in real comment tokens — a
    # docstring merely *describing* the syntax must not disable rules
    try:
        comments = [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(src).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError):
        comments = []
    for i, text in comments:
        m = _SUPPRESS_RE.search(text)
        if m:
            mod.suppress[i] = {r.strip() for r in m.group(1).split(",")
                               if r.strip()}
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            mod.file_suppress |= {r.strip() for r in m.group(1).split(",")
                                  if r.strip()}
    _index_module(mod)
    return mod


def _index_module(mod: ModuleInfo) -> None:
    for parent in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(parent):
            mod.parents[child] = parent

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[alias.asname or alias.name.split(".")[0]] = (
                    alias.name, None)
        elif isinstance(node, ast.ImportFrom):
            level_prefix = ""
            if node.level:
                # level 1 anchors at the package itself for an
                # __init__.py (whose modname already names the
                # package) but at the parent for a plain module
                base = mod.modname.split(".")
                drop = node.level - (
                    1 if os.path.basename(mod.path) == "__init__.py"
                    else 0)
                if drop:
                    base = base[: len(base) - drop]
                level_prefix = ".".join(base) + "." if base else ""
            if node.module:
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = (
                        level_prefix + node.module, alias.name)
            elif node.level:
                # `from . import sub` binds sibling submodules
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = (
                        level_prefix + alias.name, None)

    def visit(node: ast.AST, qual: List[str], cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = qual + [child.name]
                fi = FunctionInfo(
                    node=child, module=mod, qualname=".".join(q),
                    parent_class=cls,
                    is_async=isinstance(child, ast.AsyncFunctionDef),
                    params=[a.arg for a in (
                        child.args.posonlyargs + child.args.args
                        + child.args.kwonlyargs)],
                )
                _parse_decorators(fi)
                mod.functions[fi.qualname] = fi
                visit(child, q + ["<locals>"], cls)
            elif isinstance(child, ast.ClassDef):
                _collect_lock_attrs(mod, child)
                visit(child, qual + [child.name], child.name)
            else:
                visit(child, qual, cls)

    visit(mod.tree, [], None)


def _collect_lock_attrs(mod: ModuleInfo, cls: ast.ClassDef) -> None:
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            callee = dotted(node.value.func) or ""
            # asyncio.Lock() and the lockdep-instrumented
            # lockdep.Lock("x.y") count; threading.Lock does not (its
            # sync `with` is correct).  A bare Lock() only counts when
            # the import table says it came from asyncio/lockdep —
            # `from threading import Lock` must not be misclassified.
            if callee == "Lock":
                src = mod.imports.get("Lock")
                if src is None or src[1] != "Lock" or not (
                        src[0] == "asyncio"
                        or src[0].endswith("lockdep")):
                    continue
            elif not (callee.endswith("asyncio.Lock")
                      or callee.endswith("lockdep.Lock")):
                continue
            label = None
            if node.value.args and isinstance(
                    node.value.args[0], ast.Constant) and isinstance(
                    node.value.args[0].value, str):
                label = node.value.args[0].value
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    attrs.add(tgt.attr)
                    if label:
                        mod.lock_labels[tgt.attr] = label
    if attrs:
        mod.lock_attrs[cls.name] = attrs


def _is_jit_expr(node: ast.AST) -> bool:
    name = dotted(node)
    return bool(name) and name.split(".")[-1] in ("jit", "pjit")


def _static_names_from_call(call: ast.Call,
                            params: List[str]) -> Set[str]:
    """static_argnums/static_argnames out of a jit(...) call."""
    out: Set[str] = set()
    for kw in call.keywords:
        val = kw.value
        items: List[ast.AST]
        if isinstance(val, (ast.Tuple, ast.List)):
            items = list(val.elts)
        else:
            items = [val]
        if kw.arg == "static_argnames":
            out |= {i.value for i in items
                    if isinstance(i, ast.Constant)
                    and isinstance(i.value, str)}
        elif kw.arg == "static_argnums":
            for i in items:
                if isinstance(i, ast.Constant) and isinstance(
                        i.value, int) and i.value < len(params):
                    out.add(params[i.value])
    return out


def _parse_decorators(fi: FunctionInfo) -> None:
    for dec in fi.node.decorator_list:
        if _is_jit_expr(dec):
            fi.traced_by = "jit-decorator"
            fi.jit_decorated = True
        elif isinstance(dec, ast.Call):
            callee = dotted(dec.func) or ""
            if callee.split(".")[-1] == "partial" and dec.args and \
                    _is_jit_expr(dec.args[0]):
                fi.traced_by = "jit-decorator"
                fi.jit_decorated = True
                fi.static_params |= _static_names_from_call(
                    dec, fi.params)
            elif _is_jit_expr(dec.func):
                fi.traced_by = "jit-decorator"
                fi.jit_decorated = True
                fi.static_params |= _static_names_from_call(
                    dec, fi.params)
            elif (callee.split(".")[-1] in _TRACING_WRAPPERS):
                fi.traced_by = callee.split(".")[-1]
        elif dotted(dec) and dotted(dec).split(".")[-1] in \
                _TRACING_WRAPPERS:
            fi.traced_by = dotted(dec).split(".")[-1]


class Project:
    """All scanned modules + cross-module resolution + the traced set."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = {m.modname: m for m in modules}
        self._traced: Optional[Dict[int, FunctionInfo]] = None

    # -- resolution ----------------------------------------------------

    def resolve_function(self, mod: ModuleInfo, node: ast.AST,
                         cls: Optional[str] = None
                         ) -> Optional[FunctionInfo]:
        """Resolve a Name/Attribute reference to a FunctionInfo in the
        scanned set (same module, or through the import table).  `cls`
        is the caller's enclosing class, used to bind `self.method`."""
        name = dotted(node)
        if not name:
            return None
        head, _, rest = name.partition(".")
        # local function (module scope)
        if not rest and name in mod.functions:
            return mod.functions[name]
        # from X import f [as g]
        if not rest and head in mod.imports:
            src_mod, attr = mod.imports[head]
            target = self.modules.get(src_mod)
            if target and attr and attr in target.functions:
                return target.functions[attr]
        # import X [as m]; m.f(...)
        if rest and head in mod.imports:
            src_mod, attr = mod.imports[head]
            if attr is None:
                target = self.modules.get(src_mod)
                if target and rest in target.functions:
                    return target.functions[rest]
            else:  # from pkg import mod; mod.f(...)
                target = self.modules.get(f"{src_mod}.{attr}") or \
                    self.modules.get(attr)
                if target and rest in target.functions:
                    return target.functions[rest]
        # self.method(...): the enclosing class's method when known,
        # else a UNIQUE method of that name in this module — a
        # first-match fallback would bind nondeterministically when
        # two classes share a method name
        if rest and head == "self":
            if cls:
                exact = mod.functions.get(f"{cls}.{rest}")
                if exact is not None:
                    return exact
            matches = [fi for q, fi in mod.functions.items()
                       if q.endswith("." + rest) and fi.parent_class]
            if len(matches) == 1:
                return matches[0]
        return None

    # -- traced set ----------------------------------------------------

    def traced_functions(self) -> Dict[int, FunctionInfo]:
        if self._traced is None:
            self._traced = self._compute_traced()
        return self._traced

    def is_traced(self, node: ast.AST) -> bool:
        return id(node) in self.traced_functions()

    def _compute_traced(self) -> Dict[int, FunctionInfo]:
        traced: Dict[int, FunctionInfo] = {}

        def mark(fi: FunctionInfo, why: str) -> bool:
            if id(fi.node) in traced:
                return False
            fi.traced_by = fi.traced_by or why
            traced[id(fi.node)] = fi
            # defs nested in a traced body are traced (fori_loop
            # bodies, closures passed to lax HOFs, etc.)
            for inner in ast.walk(fi.node):
                if inner is not fi.node and isinstance(
                        inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    inner_fi = self._function_for(fi.module, inner)
                    if inner_fi:
                        mark(inner_fi, "nested-in-traced")
            return True

        # seeds: decorators + call-site wraps anywhere in the project
        for mod in self.modules.values():
            for fi in mod.functions.values():
                if fi.traced_by:
                    mark(fi, fi.traced_by)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted(node.func) or ""
                tail = callee.split(".")[-1]
                if tail in _TRACING_WRAPPERS:
                    for arg in node.args[:1]:
                        fi = self.resolve_function(mod, arg)
                        if fi:
                            if tail in ("jit", "pjit"):
                                fi.jit_decorated = True
                                fi.static_params |= \
                                    _static_names_from_call(
                                        node, fi.params)
                            mark(fi, f"{tail}-callsite")
                elif tail in _LAX_HOFS:
                    for pos in _LAX_HOFS[tail]:
                        if pos < len(node.args):
                            fi = self.resolve_function(
                                mod, node.args[pos])
                            if fi:
                                mark(fi, f"lax.{tail}")

        # fixpoint: anything a traced body calls (resolvable in the
        # scanned package) is traced too
        changed = True
        while changed:
            changed = False
            for fi in list(traced.values()):
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Call):
                        callee = self.resolve_function(
                            fi.module, node.func,
                            cls=fi.parent_class)
                        if callee and mark(callee, "called-from-traced"):
                            changed = True
        return traced

    def _function_for(self, mod: ModuleInfo,
                      node: ast.AST) -> Optional[FunctionInfo]:
        for fi in mod.functions.values():
            if fi.node is node:
                return fi
        return None

    # -- taint ---------------------------------------------------------

    def tainted_locals(self, fi: FunctionInfo) -> Set[str]:
        """Names in `fi` carrying traced values: non-static params plus
        locals (transitively) assigned from them, in source order."""
        tainted = set(fi.params) - fi.static_params
        tainted.discard("self")

        def expr_tainted(e: ast.AST) -> bool:
            return bool(dynamic_names_in(e) & tainted)

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
            elif isinstance(node, ast.AugAssign) and (
                    expr_tainted(node.value) or expr_tainted(node.target)):
                if isinstance(node.target, ast.Name):
                    tainted.add(node.target.id)
            elif isinstance(node, ast.For) and expr_tainted(node.iter):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        tainted.add(n.id)
        return tainted


class Analyzer:
    """Runs the rule set over a Project and collects findings."""

    def __init__(self, project: Project, rules: Dict[str, "object"],
                 config: Optional[dict] = None):
        self.project = project
        self.rules = rules
        self.config = dict(config or {})
        self.findings: List[Finding] = []
        # (module relpath, comment line | -1 for file-wide, rule) of
        # every suppression that actually suppressed a finding — the
        # ledger rule unused-suppression audits
        self.suppression_hits: Set[Tuple[str, int, str]] = set()

    def emit(self, rule: str, mod: ModuleInfo, node: ast.AST,
             message: str, severity: str = "error",
             symbol: str = "", scope_line: int = 0) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        hit = mod.suppression_match(rule, line, scope_line)
        if hit is not None:
            self.suppression_hits.add((mod.relpath, hit, rule))
            return
        self.findings.append(Finding(
            rule=rule, path=mod.relpath.replace(os.sep, "/"),
            line=line, col=col, message=message, severity=severity,
            symbol=symbol, text=mod.line_text(line)))

    def run(self) -> List[Finding]:
        for name, rule in self.rules.items():
            rule(self)
        return fingerprint_all(self.findings)


def iter_py_files(paths: List[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def build_project(paths: List[str]) -> Project:
    return Project([parse_module(p) for p in iter_py_files(paths)])
